"""dynalint rules DYN001–DYN007.

Each rule encodes a hazard this codebase has actually exhibited (see
docs/dynalint.md for the catalog with examples); the checker is one AST
walk per file with a function-context stack, so rules stay cheap and share
the async/jit scoping logic.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import (
    CorpusIndex,
    Finding,
    _walk_same_func,
    call_target,
    contains_await,
    dotted_name,
    iter_names,
)
from .registry import (
    BULK_PAYLOAD_PRODUCER_TAILS,
    BULK_SINK_TAILS,
    HUB_KEY_BUILDER_TAILS,
    HUB_KEY_SINK_TAILS,
)

# DYN001-007 run in the per-file FileChecker below; DYN1xx/2xx/3xx are the
# 2.0 corpus passes (rules_race / rules_taint / rules_schema) and
# DYN5xx/6xx the 3.0 passes (rules_lifetime / rules_stability), all built
# on the dataflow core — one ALL_RULES tuple so --rules and suppressions
# see one namespace.
ALL_RULES = (
    "DYN001",
    "DYN002",
    "DYN003",
    "DYN004",
    "DYN005",
    "DYN006",
    "DYN007",
    "DYN101",
    "DYN102",
    "DYN201",
    "DYN202",
    "DYN203",
    "DYN204",
    "DYN301",
    "DYN302",
    "DYN303",
    "DYN304",
    "DYN305",
    "DYN306",
    "DYN401",
    "DYN402",
    "DYN501",
    "DYN502",
    "DYN503",
    "DYN504",
    "DYN601",
    "DYN602",
    "DYN603",
    "DYN604",
)

RULE_TITLES = {
    "DYN001": "blocking call inside async def",
    "DYN002": "fire-and-forget task: create_task result dropped",
    "DYN003": "broad except in async code may swallow CancelledError",
    "DYN004": "sync lock held across await",
    "DYN005": "coroutine-returning call is never awaited",
    "DYN006": "request ctx/deadline not forwarded to downstream call",
    "DYN007": "host coercion / side effect inside a jitted function",
    "DYN101": "read-modify-write of shared state spans an await (TOCTOU)",
    "DYN102": "async lock release not exception-safe (no finally)",
    "DYN201": "wire-controlled value reaches a Prometheus label unsanitized",
    "DYN202": "credential-grade wire value reaches a log call",
    "DYN203": "wire-controlled value reaches a hub key/subject unsanitized",
    "DYN204": "Prometheus label interpolation not provably sanitized",
    "DYN301": "wire dataclass field missing from to_dict/from_dict",
    "DYN302": "optional wire field emitted unconditionally (omit-when-absent)",
    "DYN303": "from_dict reads a defaulted field with d[...] not .get()",
    "DYN304": "SequenceState field not threaded through SequenceSnapshot",
    "DYN305": "setdefault on a nullable wire key (null skips the rewrite)",
    "DYN306": "pytree treedef stability: frozen prefix / trailing defaults",
    "DYN401": "ad-hoc hub key construction bypasses shard routing",
    "DYN402": "bulk payload published through a hub subject",
    "DYN501": "acquired resource handle does not reach release/transfer on all paths",
    "DYN502": "registered device dispatch runs outside _device_lock",
    "DYN503": "blocking host I/O under _device_lock (lock-split class)",
    "DYN504": "stale lifetime/device registry entry (symbol gone from corpus)",
    "DYN601": "dtype-ambiguous array constructor on a registered hot path",
    "DYN602": "raw len() flows into a traced dispatch argument (compile churn)",
    "DYN603": "raw clock/RNG call inside a registered deterministic core",
    "DYN604": "stale hot-path/deterministic-core registry entry",
}

# DYN001 — calls that park the whole event loop.  Dotted names only: a bare
# `sleep(...)` may be a local helper, but `time.sleep(...)` is unambiguous.
BLOCKING_CALLS = {
    "time.sleep",
    "os.system",
    "os.wait",
    "os.waitpid",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "socket.create_connection",
    "socket.getaddrinfo",
    "urllib.request.urlopen",
    "requests.get",
    "requests.post",
    "requests.put",
    "requests.patch",
    "requests.delete",
    "requests.head",
    "requests.request",
}

# DYN002 — spawn APIs whose returned handle must be kept.
SPAWN_TAILS = {"create_task", "ensure_future"}

# DYN007 — tracer-to-host coercions and side effects inside jit.
JIT_HOST_BUILTINS = {"float", "int", "bool", "print"}
JIT_HOST_DOTTED = {
    "np.asarray",
    "np.array",
    "numpy.asarray",
    "numpy.array",
    "jax.device_get",
    "time.time",
    "time.perf_counter",
}
JIT_HOST_TAILS = {"item", "tolist"}

# DYN401 — keyword names that carry a hub key/subject at a sink call when
# it is not the first positional argument.
HUB_KEY_ARG_KWARGS = ("key", "prefix", "subject", "queue", "pattern")

# DYN006 — request-scoped values that must thread through the call graph.
FORWARD_PARAMS = ("ctx", "deadline")
# ... and the distributed-tracing context (runtime/tracing.py): a call that
# forwards ctx/deadline (i.e. is request-scoped) to a trace-accepting
# callee while holding a trace context must forward THAT too, or the
# downstream hop silently falls out of the request's timeline.
TRACE_PARAM = "trace"

_BROAD_NAMES = {"Exception", "BaseException"}


def _is_broad_handler(h: ast.ExceptHandler) -> Tuple[bool, str]:
    if h.type is None:
        return True, "bare except:"
    names = []
    types = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
    for t in types:
        d = dotted_name(t)
        names.append(d or "?")
    hit = [n for n in names if n.split(".")[-1] in _BROAD_NAMES]
    if hit:
        return True, f"except {', '.join(names)}:"
    return False, ""


def _catches_cancelled(h: ast.ExceptHandler) -> bool:
    if h.type is None:
        return False
    types = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
    return any(
        (dotted_name(t) or "").split(".")[-1] == "CancelledError" for t in types
    )


def _is_jit_decorated(node: ast.AST) -> bool:
    """@jax.jit / @jit / @partial(jax.jit, ...) / @jax.jit(...) forms."""
    for dec in getattr(node, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        d = dotted_name(target) or ""
        if d.split(".")[-1] == "jit":
            return True
        # partial(jax.jit, ...) — jit hides in the first argument
        if isinstance(dec, ast.Call) and d.split(".")[-1] == "partial":
            for a in dec.args:
                if (dotted_name(a) or "").split(".")[-1] == "jit":
                    return True
    return False


def _jitted_local_names(tree: ast.AST) -> Set[str]:
    """Names of local functions passed to jax.jit(fn, ...) call-sites —
    engine.py builds its step functions this way rather than decorating."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            d = dotted_name(node.func) or ""
            if d.split(".")[-1] == "jit" and node.args:
                first = node.args[0]
                if isinstance(first, ast.Name):
                    out.add(first.id)
    return out


class FileChecker:
    """One-pass rule evaluation over a parsed file."""

    def __init__(
        self,
        path: str,
        source: str,
        index: CorpusIndex,
        rules: Optional[Set[str]] = None,
    ):
        self.path = path
        self.lines = source.splitlines()
        self.index = index
        self.rules = set(rules) if rules else set(ALL_RULES)
        self.findings: List[Finding] = []
        # (kind, name, node) stack: kind in {"async", "sync", "class"}
        self._stack: List[Tuple[str, str, ast.AST]] = []
        self._jit_depth = 0
        self._jitted_names: Set[str] = set()
        self._cancel_scope_cache: Dict[int, bool] = {}

    # ------------------------------------------------------------- plumbing

    def run(self, tree: ast.AST) -> List[Finding]:
        self._jitted_names = _jitted_local_names(tree)
        self._visit(tree)
        return self.findings

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        if rule not in self.rules:
            return
        line = getattr(node, "lineno", 1)
        snippet = (
            self.lines[line - 1].strip() if 0 < line <= len(self.lines) else ""
        )
        self.findings.append(
            Finding(
                rule=rule,
                path=self.path,
                line=line,
                col=getattr(node, "col_offset", 0),
                message=message,
                symbol=self._symbol(),
                snippet=snippet,
            )
        )

    def _symbol(self) -> str:
        names = [n for _, n, _ in self._stack]
        return ".".join(names) if names else "<module>"

    def _in_async(self) -> bool:
        for kind, _, _ in reversed(self._stack):
            if kind == "class":
                continue
            return kind == "async"
        return False

    def _scope_cancels_tasks(self) -> bool:
        """Does the enclosing class (or, for free functions, the outermost
        enclosing def) call `.cancel()` anywhere?  Marks the deliberate
        stop()-pattern — `task.cancel(); try: await task; except
        CancelledError: pass` — where swallowing the echo is correct."""
        scope: Optional[ast.AST] = None
        for kind, _, node in reversed(self._stack):
            if kind == "class":
                scope = node
                break
        if scope is None and self._stack:
            scope = self._stack[0][2]
        if scope is None:
            return False
        key = id(scope)
        if key not in self._cancel_scope_cache:
            self._cancel_scope_cache[key] = any(
                isinstance(n, ast.Call) and call_target(n)[1] == "cancel"
                for n in ast.walk(scope)
            )
        return self._cancel_scope_cache[key]

    # ------------------------------------------------------------- traversal

    def _visit(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            jitted = _is_jit_decorated(node) or node.name in self._jitted_names
            kind = "async" if isinstance(node, ast.AsyncFunctionDef) else "sync"
            self._stack.append((kind, node.name, node))
            if jitted:
                self._jit_depth += 1
            if kind == "async":
                self._check_function_dyn006(node)
            for child in ast.iter_child_nodes(node):
                self._visit(child)
            if jitted:
                self._jit_depth -= 1
            self._stack.pop()
            return
        if isinstance(node, ast.ClassDef):
            self._stack.append(("class", node.name, node))
            for child in ast.iter_child_nodes(node):
                self._visit(child)
            self._stack.pop()
            return

        if isinstance(node, ast.Try):
            self._check_try_dyn003(node)
        elif isinstance(node, ast.With):
            self._check_with_dyn004(node)
        elif isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            self._check_stmt_call(node, node.value)
        elif isinstance(node, ast.Call):
            self._check_call(node)

        # An Expr statement's Call still needs the generic Call checks
        # (DYN001/DYN007) — visit children for every non-function node.
        for child in ast.iter_child_nodes(node):
            self._visit(child)

    # ------------------------------------------------------------- DYN001/7

    def _check_call(self, call: ast.Call) -> None:
        dotted, tail = call_target(call)
        if self._in_async() and dotted in BLOCKING_CALLS:
            self._emit(
                "DYN001",
                call,
                f"blocking call `{dotted}()` inside async def "
                f"`{self._symbol()}` stalls the event loop — use the asyncio "
                "equivalent or `asyncio.to_thread`",
            )
        if self._jit_depth > 0:
            self._check_call_dyn007(call, dotted, tail)
        if tail in HUB_KEY_SINK_TAILS:
            self._check_call_dyn401(call, tail)
        if tail in BULK_SINK_TAILS:
            self._check_call_dyn402(call, tail)

    def _check_call_dyn401(self, call: ast.Call, tail: str) -> None:
        """Hub key/subject arguments must route through a sanctioned builder
        (registry.HUB_KEY_BUILDER_TAILS) so the shard map owns routing: an
        f-string or ``+``-concatenation at the sink hard-codes a layout the
        shard hash never sees, and an unregistered helper call hides one."""
        arg: Optional[ast.AST] = call.args[0] if call.args else None
        if arg is None:
            for kw in call.keywords:
                if kw.arg in HUB_KEY_ARG_KWARGS:
                    arg = kw.value
                    break
        if arg is None:
            return
        offender = None
        if isinstance(arg, (ast.JoinedStr, ast.BinOp)):
            offender = (
                "f-string" if isinstance(arg, ast.JoinedStr) else "concatenation"
            )
        elif isinstance(arg, ast.Call):
            _, arg_tail = call_target(arg)
            if arg_tail not in HUB_KEY_BUILDER_TAILS:
                offender = f"unregistered helper `{arg_tail}()`"
        if offender:
            self._emit(
                "DYN401",
                call,
                f"ad-hoc hub key at `{tail}()` ({offender}) bypasses the "
                "shard map — build the key via hub_key/hub_prefix/"
                "hub_subject (or a helper registered in "
                "HUB_KEY_BUILDER_TAILS)",
            )

    _DYN402_PAYLOAD_KWARGS = ("payload", "value", "item")

    def _check_call_dyn402(self, call: ast.Call, tail: str) -> None:
        """Bulk payloads must not ride hub subjects (registry.BULK_SINK_TAILS):
        a KV block export or migration copy stream published through the hub
        head-of-line-blocks lease renewals and watches on that shard.  The
        checker flags the shapes it can prove — the result of a registered
        bulk producer (BULK_PAYLOAD_PRODUCER_TAILS) handed to a hub sink,
        directly or through one local assignment, and KV-block dict literals
        (both ``"k"`` and ``"v"`` keys) — and points at the bulk plane
        (transports/bulk.py; >= BULK_THRESHOLD_BYTES is bulk by contract)."""
        arg: Optional[ast.AST] = call.args[1] if len(call.args) > 1 else None
        if arg is None:
            for kw in call.keywords:
                if kw.arg in self._DYN402_PAYLOAD_KWARGS:
                    arg = kw.value
                    break
        if arg is None:
            return
        offender = self._dyn402_offender(arg)
        if offender is None and isinstance(arg, ast.Name):
            resolved = self._resolve_local(arg.id)
            if resolved is not None:
                offender = self._dyn402_offender(resolved)
        if offender:
            self._emit(
                "DYN402",
                call,
                f"bulk payload ({offender}) published through hub "
                f"`{tail}()` — the control plane carries rendezvous and "
                "control only; move >=64KiB block/stream payloads to the "
                "bulk data plane (transports/bulk.py, docs/bulk_plane.md)",
            )

    @staticmethod
    def _dyn402_offender(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Await):
            node = node.value
        if isinstance(node, ast.Call):
            _, tail = call_target(node)
            if tail in BULK_PAYLOAD_PRODUCER_TAILS:
                return f"result of `{tail}()`"
        if isinstance(node, ast.Dict):
            keys = {
                k.value
                for k in node.keys
                if isinstance(k, ast.Constant) and isinstance(k.value, str)
            }
            if {"k", "v"} <= keys:
                return 'KV block dict (`"k"`/`"v"` byte planes)'
        return None

    def _resolve_local(self, name: str) -> Optional[ast.AST]:
        """One level of local dataflow: the value last assigned to ``name``
        in the enclosing function (module scope is not resolved — a module
        constant is config, not a per-request payload)."""
        func = None
        for kind, _, node in reversed(self._stack):
            if kind in ("async", "sync"):
                func = node
                break
        if func is None:
            return None
        value: Optional[ast.AST] = None
        for stmt in _walk_same_func(func):
            if isinstance(stmt, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == name for t in stmt.targets
            ):
                value = stmt.value
            elif (
                isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
                and stmt.target.id == name
                and stmt.value is not None
            ):
                value = stmt.value
        return value

    def _check_call_dyn007(
        self, call: ast.Call, dotted: Optional[str], tail: Optional[str]
    ) -> None:
        offender = None
        if tail in JIT_HOST_TAILS and isinstance(call.func, ast.Attribute):
            offender = f".{tail}()"
        elif dotted in JIT_HOST_DOTTED:
            offender = f"{dotted}()"
        elif (
            dotted in JIT_HOST_BUILTINS
            and call.args
            and not isinstance(call.args[0], ast.Constant)
        ):
            offender = f"{dotted}()"
        if offender:
            self._emit(
                "DYN007",
                call,
                f"`{offender}` inside a jitted function forces a "
                "tracer-to-host transfer (or is a traced-away side effect) — "
                "keep jitted code pure; coerce outside jit",
            )

    # --------------------------------------------------------------- DYN002/5

    def _check_stmt_call(self, stmt: ast.Expr, call: ast.Call) -> None:
        dotted, tail = call_target(call)
        if tail in SPAWN_TAILS:
            self._emit(
                "DYN002",
                stmt,
                f"`{tail}()` result discarded: the task can be GC'd mid-flight "
                "and its exception is silently dropped — store the handle "
                "(set + done-callback discard) and cancel it on close",
            )
            return
        # DYN005: bare-statement call to a function every definition of
        # which is async — the coroutine object is created then dropped.
        # Attribute calls only count with a `self.`/`cls.` receiver: on an
        # arbitrary object the name likely belongs to a foreign type
        # (task.cancel() is not our async cancel()).
        func = call.func
        resolvable = isinstance(func, ast.Name) or (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id in ("self", "cls")
        )
        if (
            resolvable
            and tail
            and self.index.always_async(tail)
            and tail not in SPAWN_TAILS
        ):
            self._emit(
                "DYN005",
                stmt,
                f"`{tail}()` returns a coroutine that is never awaited — "
                "nothing runs; await it or wrap it in a task",
            )
        # DYN001/DYN007 on this call happen when _visit descends into it.

    # ----------------------------------------------------------------- DYN003

    def _check_try_dyn003(self, node: ast.Try) -> None:
        if not self._in_async():
            return
        seen_cancelled = False
        for h in node.handlers:
            reraises = any(
                isinstance(s, ast.Raise) and s.exc is None for s in h.body
            )
            if _catches_cancelled(h):
                # Naming CancelledError only protects if the handler
                # re-raises.  `except CancelledError: pass` is the hazard
                # in its most explicit form — except in the deliberate
                # stop()-pattern (this scope cancelled the task itself and
                # is absorbing the echo).
                if not reraises and not self._scope_cancels_tasks():
                    self._emit(
                        "DYN003",
                        h,
                        f"cancellation handler in async `{self._symbol()}` "
                        "swallows CancelledError without re-raising — the "
                        "task becomes uncancellable; add `raise`",
                    )
                seen_cancelled = True
                continue
            broad, shown = _is_broad_handler(h)
            if not broad or seen_cancelled:
                continue
            # A handler that immediately re-raises swallows nothing.
            if reraises:
                continue
            self._emit(
                "DYN003",
                h,
                f"`{shown}` in async `{self._symbol()}` can swallow "
                "cancellation — add `except asyncio.CancelledError: raise` "
                "before it",
            )

    # ----------------------------------------------------------------- DYN004

    def _check_with_dyn004(self, node: ast.With) -> None:
        for item in node.items:
            ctx = item.context_expr
            target = ctx.func if isinstance(ctx, ast.Call) else ctx
            d = (dotted_name(target) or "").lower()
            if ("lock" in d or "mutex" in d) and contains_await(node):
                self._emit(
                    "DYN004",
                    node,
                    f"sync lock `{dotted_name(target)}` held across an await "
                    "in async code: every other task blocks until this one "
                    "resumes — use asyncio.Lock or drop the lock before "
                    "awaiting",
                )
                return

    # ----------------------------------------------------------------- DYN006

    def _check_function_dyn006(self, fn: ast.AST) -> None:
        from .core import _param_names

        params = set(_param_names(fn))
        carried = [p for p in FORWARD_PARAMS if p in params]
        holds_trace = TRACE_PARAM in params
        if not carried and not holds_trace:
            return

        def _passes(sub: ast.Call, p: str) -> bool:
            if any(n == p for a in sub.args for n in iter_names(a)):
                return True
            return any(
                n == p for kw in sub.keywords for n in iter_names(kw.value)
            )

        for sub in _walk_same_func(fn):
            if not isinstance(sub, ast.Call):
                continue
            _, tail = call_target(sub)
            if not tail or tail == fn.name:
                continue
            for p in carried:
                if not self.index.every_def_accepts(tail, p):
                    continue
                if not _passes(sub, p):
                    self._emit(
                        "DYN006",
                        sub,
                        f"`{self._symbol()}` holds request `{p}` but calls "
                        f"`{tail}()` (which accepts `{p}`) without forwarding "
                        "it — deadlines/cancellation stop propagating here",
                    )
            if (
                holds_trace
                and any(_passes(sub, p) for p in FORWARD_PARAMS)
                and self.index.every_def_accepts(tail, TRACE_PARAM)
                and not _passes(sub, TRACE_PARAM)
            ):
                # Trace-propagation gap (runtime/tracing.py): the call is
                # request-scoped (it forwards ctx/deadline) and the callee
                # takes a trace context, but this hop drops the one in
                # scope — the downstream spans silently detach from the
                # request's timeline.
                self._emit(
                    "DYN006",
                    sub,
                    f"`{self._symbol()}` holds a `trace` context and "
                    f"forwards ctx/deadline to `{tail}()` (which accepts "
                    "`trace`) without forwarding the trace — downstream "
                    "spans drop out of the request's timeline",
                )
