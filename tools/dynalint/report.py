"""Text and JSON reporters for dynalint findings."""

from __future__ import annotations

import json
from collections import Counter
from typing import List, Sequence

from .core import Finding
from .rules import RULE_TITLES


def render_text(
    new: Sequence[Finding], baselined: Sequence[Finding], verbose: bool = False
) -> str:
    lines: List[str] = []
    for f in new:
        lines.append(f"{f.path}:{f.line}:{f.col}: {f.rule} {f.message}")
        if f.snippet:
            lines.append(f"    {f.snippet}")
    if verbose and baselined:
        lines.append("")
        lines.append("grandfathered (baseline):")
        for f in baselined:
            lines.append(f"  {f.path}:{f.line}: {f.rule} [{f.symbol}]")
    counts = Counter(f.rule for f in new)
    summary = ", ".join(f"{r}={n}" for r, n in sorted(counts.items()))
    lines.append("")
    if new:
        lines.append(
            f"dynalint: {len(new)} new finding(s) ({summary}); "
            f"{len(baselined)} baselined"
        )
    else:
        lines.append(
            f"dynalint: clean ({len(baselined)} baselined finding(s))"
        )
    return "\n".join(lines)


def render_json(
    new: Sequence[Finding],
    baselined: Sequence[Finding],
    timings: dict = None,
) -> str:
    return json.dumps(
        {
            "new": [f.to_dict() for f in new],
            "baselined": [f.to_dict() for f in baselined],
            "counts": dict(Counter(f.rule for f in new)),
            "ok": not new,
            # per-pass wall time (seconds) — the CI budget gate reads
            # timings.total; per-family numbers size future optimization.
            "timings": {
                k: round(v, 4) for k, v in (timings or {}).items()
            },
        },
        indent=2,
    )


def render_rules() -> str:
    return "\n".join(f"{rid}  {title}" for rid, title in RULE_TITLES.items())
