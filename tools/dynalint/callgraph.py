"""Corpus graph for dynalint 2.0: function units, await/lock-aware
linearization, call-graph and import-graph edges.

The PR 2 analyzer saw one statement at a time; the 2.0 rule families need
*order*: "read before an await, write after it" (DYN1xx), "this value flows
from that call" (DYN2xx), "who depends on the file you changed"
(``--changed-only``).  Full CFG construction is overkill for a linter that
must stay sub-second, so this module provides the deliberately simpler
shape the rules actually consume:

- :class:`FunctionUnit` — every function in the corpus with its enclosing
  class, qualname, and parse tree, extracted once.
- :func:`linearize` — a function body flattened to an ordered event stream
  (reads/writes of ``self.X`` and declared globals, await points, local
  assignments with provenance, guard tests), each event stamped with the
  set of enclosing lock-shaped context managers.  Branches contribute their
  events in source order: an over-approximation of real control flow that
  errs toward *reporting* a possible interleaving — the right bias for a
  suppressible linter.
- :class:`CorpusGraph` — name-keyed call edges and module import edges over
  the whole corpus, powering interprocedural taint summaries and the
  reverse-dependency closure of ``--changed-only``.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import call_target, dotted_name
from .registry import LOCKISH


def _is_lockish(expr: ast.AST) -> bool:
    target = expr.func if isinstance(expr, ast.Call) else expr
    d = (dotted_name(target) or "").lower()
    return any(tok in d for tok in LOCKISH)


@dataclass
class Event:
    """One step of a linearized function body."""

    kind: str  # "read" | "write" | "assign" | "await" | "test"
    key: Optional[str]  # "self.attr" / global name; local name for assign
    node: ast.AST
    index: int
    locks: frozenset  # ids of enclosing lock-shaped with/async-with nodes
    # assign/write: keys + local names read by the RHS
    value_reads: Tuple[str, ...] = ()
    # write: (guard_keys, guard_index) for each enclosing if/while test
    guards: Tuple[Tuple[Tuple[str, ...], int], ...] = ()


@dataclass
class FunctionUnit:
    path: str
    qualname: str
    name: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    is_async: bool
    class_name: Optional[str]
    params: Tuple[str, ...]


def collect_functions(path: str, tree: ast.AST) -> List[FunctionUnit]:
    out: List[FunctionUnit] = []

    def walk(node: ast.AST, prefix: List[str], cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = ".".join(prefix + [child.name])
                a = child.args
                params = tuple(
                    p.arg
                    for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)
                    if p.arg not in ("self", "cls")
                )
                out.append(
                    FunctionUnit(
                        path=path,
                        qualname=qual,
                        name=child.name,
                        node=child,
                        is_async=isinstance(child, ast.AsyncFunctionDef),
                        class_name=cls,
                        params=params,
                    )
                )
                walk(child, prefix + [child.name], cls)
            elif isinstance(child, ast.ClassDef):
                walk(child, prefix + [child.name], child.name)
            else:
                walk(child, prefix, cls)

    walk(tree, [], None)
    return out


# ---------------------------------------------------------------------------
# Linearization
# ---------------------------------------------------------------------------


def _state_key(node: ast.AST, globals_: Set[str]) -> Optional[str]:
    """'self.attr' for self attribute chains (subscripts collapse to their
    base attribute: ``self._refs[slot]`` is state of ``self._refs``), bare
    names only when declared ``global``."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return f"self.{node.attr}"
    if isinstance(node, ast.Name) and node.id in globals_:
        return node.id
    return None


def _expr_reads(
    expr: ast.AST, globals_: Set[str]
) -> Tuple[Tuple[str, ...], Tuple[str, ...]]:
    """(state keys, local names) read by an expression."""
    keys: List[str] = []
    locals_: List[str] = []
    for sub in ast.walk(expr):
        k = _state_key(sub, globals_)
        if k is not None and isinstance(getattr(sub, "ctx", None), ast.Load):
            keys.append(k)
        elif isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
            locals_.append(sub.id)
    return tuple(keys), tuple(locals_)


class _Linearizer:
    def __init__(self, fn: ast.AST):
        self.events: List[Event] = []
        self.globals_: Set[str] = set()
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Global):
                self.globals_.update(sub.names)

    def _emit(self, kind, key, node, locks, value_reads=(), guards=()):
        self.events.append(
            Event(
                kind=kind,
                key=key,
                node=node,
                index=len(self.events),
                locks=frozenset(locks),
                value_reads=tuple(value_reads),
                guards=tuple(guards),
            )
        )

    # -- expressions --------------------------------------------------------

    def expr(self, node: ast.AST, locks) -> None:
        """Emit read/await events for an expression subtree, in order
        (manual in-order pass: ast.walk is BFS and loses sequencing)."""
        self._expr_inorder(node, locks)

    def _expr_inorder(self, node: ast.AST, locks) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(node, ast.Await):
            self._expr_inorder(node.value, locks)
            self._emit("await", None, node, locks)
            return
        key = _state_key(node, self.globals_)
        if key is not None and isinstance(getattr(node, "ctx", None), ast.Load):
            self._emit("read", key, node, locks)
            # still descend (subscript indices may read other state)
        for child in ast.iter_child_nodes(node):
            self._expr_inorder(child, locks)

    # -- statements ---------------------------------------------------------

    def body(self, stmts: Sequence[ast.stmt], locks, guards) -> None:
        for stmt in stmts:
            self.stmt(stmt, locks, guards)

    def stmt(self, node: ast.stmt, locks, guards) -> None:
        g = self.globals_
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = getattr(node, "value", None)
            if value is not None:
                self.expr(value, locks)
            keys_read, locals_read = (
                _expr_reads(value, g) if value is not None else ((), ())
            )
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target]
            )
            for tgt in targets:
                key = _state_key(tgt, g)
                if isinstance(node, ast.AugAssign):
                    # x += v reads then writes x atomically (no await can
                    # interleave inside one statement) — model as a
                    # read+write pair at the same index.
                    if key is not None:
                        self._emit("read", key, node, locks)
                if key is not None:
                    vr = keys_read + locals_read
                    if isinstance(tgt, ast.Subscript):
                        # the subscript index is part of the decision
                        ik, il = _expr_reads(tgt.slice, g)
                        vr = vr + ik + il
                    self._emit("write", key, node, locks, vr, guards)
                elif isinstance(tgt, ast.Name):
                    self._emit(
                        "assign", tgt.id, node, locks, keys_read + locals_read
                    )
                else:
                    # tuple unpacking / foreign-object attribute: record
                    # reads only (already emitted via expr above).
                    pass
            return
        if isinstance(node, (ast.If, ast.While)):
            test_keys, _ = _expr_reads(node.test, g)
            self.expr(node.test, locks)
            guard = guards
            if test_keys:
                guard = guards + ((tuple(test_keys), len(self.events) - 1),)
            self.body(node.body, locks, guard)
            # The else branch is the same decision on the same read —
            # `if self.x is None: … else: <use self.x>` is as much a
            # check-then-act as the then-branch.
            self.body(node.orelse, locks, guard)
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            self.expr(node.iter, locks)
            if isinstance(node, ast.AsyncFor):
                self._emit("await", None, node, locks)
            keys_read, locals_read = _expr_reads(node.iter, g)
            if isinstance(node.target, ast.Name):
                self._emit(
                    "assign", node.target.id, node, locks,
                    keys_read + locals_read,
                )
            self.body(node.body, locks, guards)
            self.body(node.orelse, locks, guards)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            lock_ids = set(locks)
            for item in node.items:
                self.expr(item.context_expr, locks)
                if _is_lockish(item.context_expr):
                    lock_ids.add(id(node))
            if isinstance(node, ast.AsyncWith):
                self._emit("await", None, node, locks)
            self.body(node.body, frozenset(lock_ids), guards)
            return
        if isinstance(node, ast.Try):
            self.body(node.body, locks, guards)
            for h in node.handlers:
                self.body(h.body, locks, guards)
            self.body(node.orelse, locks, guards)
            self.body(node.finalbody, locks, guards)
            return
        if isinstance(node, ast.Return) and node.value is not None:
            self.expr(node.value, locks)
            return
        if isinstance(node, ast.Expr):
            self.expr(node.value, locks)
            return
        # generic: visit child expressions/statements in order
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self.stmt(child, locks, guards)
            elif isinstance(child, ast.expr):
                self.expr(child, locks)


def linearize(fn: ast.AST) -> List[Event]:
    lin = _Linearizer(fn)
    lin.body(fn.body, frozenset(), ())
    return lin.events


# ---------------------------------------------------------------------------
# Call graph + import graph
# ---------------------------------------------------------------------------


def module_name(path: str) -> str:
    """'dynamo_tpu/llm/qos.py' -> 'dynamo_tpu.llm.qos' (packages resolve
    their __init__ to the package name)."""
    mod = path[:-3] if path.endswith(".py") else path
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _resolve_relative(
    base_module: str, level: int, target: Optional[str], is_package: bool
) -> str:
    parts = base_module.split(".")
    # level 1 = current package.  For a module file that means dropping the
    # module segment; for a package __init__ (whose module name IS the
    # package after the .__init__ strip) level 1 is the package itself —
    # one fewer segment to drop.
    drop = level - 1 if is_package else level
    anchor = parts[: max(0, len(parts) - drop)]
    if target:
        anchor = anchor + target.split(".")
    return ".".join(anchor)


@dataclass
class CorpusGraph:
    """Whole-corpus view shared by the 2.0 rule passes."""

    files: List[Tuple[str, str, ast.AST]] = field(default_factory=list)
    functions: List[FunctionUnit] = field(default_factory=list)
    # bare function name -> units (cross-module resolution by unanimity,
    # same policy as CorpusIndex)
    by_name: Dict[str, List[FunctionUnit]] = field(default_factory=dict)
    # path -> imported module names (absolute, after relative resolution)
    imports: Dict[str, Set[str]] = field(default_factory=dict)
    # path -> called bare names (tails)
    calls: Dict[str, Set[str]] = field(default_factory=dict)
    # bare name -> defining paths
    def_paths: Dict[str, Set[str]] = field(default_factory=dict)

    @classmethod
    def build(cls, files: Sequence[Tuple[str, str, ast.AST]]) -> "CorpusGraph":
        g = cls(files=list(files))
        for path, _source, tree in files:
            mod = module_name(path)
            is_pkg = path.endswith("__init__.py")
            units = collect_functions(path, tree)
            g.functions.extend(units)
            for u in units:
                g.by_name.setdefault(u.name, []).append(u)
                g.def_paths.setdefault(u.name, set()).add(path)
            imps: Set[str] = set()
            calls: Set[str] = set()
            for node in ast.walk(tree):
                if isinstance(node, ast.Import):
                    imps.update(a.name for a in node.names)
                elif isinstance(node, ast.ImportFrom):
                    if node.level:
                        base = _resolve_relative(
                            mod, node.level, node.module, is_pkg
                        )
                    else:
                        base = node.module or ""
                    if base:
                        imps.add(base)
                        # `from pkg import mod` also depends on pkg.mod
                        imps.update(
                            f"{base}.{a.name}" for a in node.names
                        )
                elif isinstance(node, ast.Call):
                    _, tail = call_target(node)
                    if tail:
                        calls.add(tail)
            g.imports[path] = imps
            g.calls[path] = calls
        return g

    def unit_for_name(self, name: str) -> Optional[FunctionUnit]:
        """The single corpus definition of ``name``, or None when absent or
        ambiguous (unanimity: ambiguity disables resolution, never guesses)."""
        units = self.by_name.get(name)
        if units and len(units) == 1:
            return units[0]
        return None

    # -- changed-only closure ----------------------------------------------

    def dependents(self, changed: Set[str]) -> Set[str]:
        """``changed`` plus every file that imports a changed module or
        calls a function defined ONLY in changed files — one reverse hop,
        which is the pre-commit contract (CI runs the full corpus)."""
        changed_mods = {module_name(p) for p in changed}
        # names whose every definition lives in a changed file
        changed_names = {
            name
            for name, paths in self.def_paths.items()
            if paths and paths <= changed
        }
        out = set(changed)
        for path, _s, _t in self.files:
            if path in out:
                continue
            imps = self.imports.get(path, set())
            if imps & changed_mods:
                out.add(path)
                continue
            if self.calls.get(path, set()) & changed_names:
                out.add(path)
        return out
