"""dynalint registries: taint sources/sinks/sanitizers, wire-schema
classes and exemptions, resource lifetimes, compile-stability scopes.

The dataflow rules (DYN1xx/2xx/3xx/5xx/6xx) are only as good as their
model of *this* codebase; that model lives here, in one reviewable place,
instead of being scattered through rule logic.  Registry groups:

- **Taint** (DYN2xx): which expressions produce wire-controlled data
  (sources), which calls neutralize it (sanitizers), and which calls/format
  positions must never receive it raw (sinks).
- **Wire schema** (DYN3xx): which dataclasses cross process boundaries,
  which of their fields are deliberately exempt from a check, and the
  frozen field prefixes of the jit-pytree classes whose treedef must stay
  byte-stable.
- **Snapshot threading** (DYN304): the explicit SequenceState →
  SequenceSnapshot coverage map — every engine-consumed decode-state field
  either travels in the snapshot or is consciously exempted here.
- **Resource lifetimes** (DYN5xx): the acquire/release/transfer model of
  every handle-shaped resource (KV blocks, adapter slots, mux stream ids,
  hub leases, row slots, tmp ``.kvblk`` files) plus the device-lock
  dispatch/blocking-I/O discipline.
- **Compile stability & determinism** (DYN6xx): which functions are jit
  hot paths (dtype/shape discipline applies) and which classes/modules are
  deterministic cores (injectable clocks + seeded RNG only).

Every entry is a claim that someone thought about the case; deleting an
entry re-surfaces the finding, so the registries are self-auditing: stale
entries (naming fields/classes that no longer exist) are themselves
reported by the schema pass.
"""

from __future__ import annotations

# ---------------------------------------------------------------------------
# DYN2xx taint model
# ---------------------------------------------------------------------------

# Dict keys whose values are wire-controlled wherever they are read:
# request bodies, nvext extensions, hub-delivered registration payloads.
# Reading `<anything>.get("model")` / `<anything>["model"]` taints.
TAINT_SOURCE_KEYS = {
    "model",
    "nvext",
    "tenant",
    "adapter",
    "priority",
    "x-tenant",
    "x-priority",
    "x-api-key",
    "worker_id",
    "metadata",
}

# Keys that carry CREDENTIALS (secret material): stronger taint — reaching
# a log line is already a finding (DYN202), not just a label.
CREDENTIAL_KEYS = {
    "x-api-key",
    "authorization",
    "api_key",
    "bearer",
}

# Parameters that are wire-controlled by naming convention at the HTTP /
# hub edge (`headers` is the aiohttp-style mapping every edge handler
# threads through).
TAINT_SOURCE_PARAMS = {
    "headers": "wire",
}

# Attribute reads that produce wire data regardless of the base object.
TAINT_SOURCE_ATTRS = {
    "headers": "wire",
}

# Calls whose RESULT is wire-controlled (beyond what summaries derive).
# resolve_tenant: x-tenant / nvext.tenant / model pass through verbatim
# (credentials are hashed inside, but the common paths are raw wire).
TAINT_SOURCE_CALLS = {
    "resolve_tenant": "wire",
}

# Calls that neutralize taint: hashing, numeric coercion, Prometheus label
# escaping, and the project's own credential digest.  A sanitizer's return
# value is clean no matter what went in.
SANITIZER_TAILS = {
    "escape_label",
    "hash_credential",
    "safe_key_component",
    "bounded_label",
    "_credential_tenant",
    "sha256",
    "sha1",
    "md5",
    "blake2b",
    "crc32",
    "hexdigest",
    "normalize_priority",
    "int",
    "float",
    "bool",
    "len",
    "round",
    "abs",
    "hash",
    "id",
    "ord",
}

# Lock-shaped names (DYN101 protection detection in callgraph.py AND
# DYN102 acquire/release matching in rules_race.py read THIS tuple — one
# list, so the two rules can never disagree about what counts as a lock).
LOCKISH = ("lock", "mutex", "sem")

# Prometheus-client metric objects: `<metric>.labels(...)` is a label sink.
LABEL_SINK_TAILS = {"labels"}

# Logging sinks: `logger.<x>(...)`.
LOG_SINK_TAILS = {"debug", "info", "warning", "error", "exception", "critical"}
LOG_RECEIVERS = {"logger", "logging", "log", "LOGGER"}

# Hub-key sinks: the FIRST positional argument is a key/subject in the
# shared control-plane namespace; wire data formatted into it un-escaped
# can escape its prefix ("tenant/x" vs "tenant/../quarantine").
HUB_KEY_SINK_TAILS = {
    "kv_put",
    "kv_get",
    "kv_get_prefix",
    "kv_delete",
    "kv_list",
    "watch",
    "watch_prefix",
    "q_push",
    "q_pop",
    "q_len",
    "queue_push",
    "publish",
    "subscribe",
}

# Hub key/subject BUILDERS (DYN401): the sanctioned constructors every hub
# key/subject must route through so the shard map (runtime/transports/
# shard.py) can own routing — an ad-hoc f-string/concatenation at a hub
# sink bypasses the routing contract (and the staleness/park accounting
# keyed on it) and is a finding.  Each entry names a helper that builds
# its keys via hub_key/hub_prefix/hub_subject (or IS one of them).
HUB_KEY_BUILDER_TAILS = {
    # canonical builders (runtime/transports/shard.py)
    "hub_key",
    "hub_prefix",
    "hub_subject",
    # discovery plane (runtime/component.py)
    "instance_key",
    "instance_prefix",
    "endpoint_path",
    "subject",  # Namespace.subject / Component.subject
    # health plane (runtime/health.py)
    "quarantine_key",
    # model discovery / cards (llm/discovery.py, llm/model_card.py)
    "model_key",
    "model_prefix",
    "mdc_key",
    # deployments (deploy/api_store.py)
    "deployment_key",
    # planner actuation (planner/actuate.py)
    "target_key",
    "role_key",
    "directive_key",
    # disaggregated serving (llm/disagg/)
    "disagg_config_key",
    "prefill_queue_name",
    # bulk data plane rendezvous (runtime/transports/bulk.py)
    "bulk_addr_key",
    "bulk_ticket_key",
    "bulk_sink_key",
    "bulk_sink_prefix",
}

# ---------------------------------------------------------------------------
# DYN402 bulk-payload model
# ---------------------------------------------------------------------------

# Hub sinks whose payload argument lands on the control plane (DYN402): a
# bulk payload (KV block export, migration copy stream) published through
# one of these rides every hub shard hop, head-of-line-blocks lease renewals
# and watches, and counts against the shard's publish_bytes budget.  Bulk
# bytes belong on the direct worker<->worker plane (runtime/transports/
# bulk.py, docs/bulk_plane.md); the hub carries rendezvous + control only.
BULK_SINK_TAILS = {
    "publish",
    "q_push",
    "kv_put",
}

# Calls whose RESULT is a bulk payload by construction: publishing one
# through a hub sink is a finding regardless of size (export_prompt_blocks
# returns the full per-block KV byte planes).  Extend when a new producer
# of multi-KiB block payloads appears.
BULK_PAYLOAD_PRODUCER_TAILS = {
    "export_prompt_blocks",
}

# Documented threshold (docs/bulk_plane.md): payloads at or above this are
# bulk by definition.  The AST checker cannot size runtime values — it
# flags the *shapes* above — but the threshold anchors the rule text and
# the bulk plane's own routing decision.
BULK_THRESHOLD_BYTES = 64 * 1024

# Calls that are *safe enough* in a label position for DYN204 even though
# they are not sanitizers (they render numbers).
LABEL_SAFE_CALLS = SANITIZER_TAILS | {"min", "max", "sum", "format"}

# (path, symbol) pairs exempt from DYN204 — each entry documents why the
# interpolated value is provably not wire-controlled.  Keep EMPTY unless
# an escape-at-render fix is genuinely wrong (for internal strings the
# escape is the identity, so the bar for exempting is high; the one real
# hazard is double-escaping a value a helper already escaped — fix THAT
# by making the helper hand raw values to the render).
LABEL_HYGIENE_EXEMPT: set = set()

# ---------------------------------------------------------------------------
# DYN3xx wire-schema model
# ---------------------------------------------------------------------------

# Classes checked even without a to_dict/from_dict pair, and classes with
# serialization helpers that are deliberately NOT wire schemas.
WIRE_CLASS_EXTRA: set = set()
WIRE_CLASS_EXEMPT = {
    # Engine-internal report types whose dicts never cross a version
    # boundary (rebuilt from source every run) go here if they ever trip
    # DYN301.  Empty today: every to_dict class in dynamo_tpu is wire.
}

# (class, field): fields deliberately absent from to_dict / from_dict.
WIRE_FIELD_EXEMPT = {
    # ModelDeploymentCard.tokenizer_obj style in-memory handles would go
    # here; none exist on current wire classes.
}

# Classes that adopted omit-when-absent for OPTIONAL fields (wire compat:
# pre-existing consumers must never see keys they predate).  A class also
# auto-adopts the moment its to_dict emits any field conditionally.
OMIT_WHEN_ABSENT_CLASSES = {
    "PreprocessedRequest",
    "SequenceSnapshot",
    # Planner signal plane (planner/signals.py): the SLO percentiles and
    # the autopilot inputs (fleet_prefix_hit_rate, restore_pct, host_gap)
    # ship only when an edge measured them — pre-autopilot planners (and
    # replay fixtures) keep the original wire shape.
    "SignalSnapshot",
    # Distributed tracing (runtime/tracing.py): ``sampled`` ships only when
    # False — pre-tracing consumers (and the common sampled case) keep the
    # minimal {trace_id, span_id} wire shape.  The trace context itself
    # rides omit-when-absent keys on carriers that already adopted the
    # idiom: annotations.trace, the service-transport header, disagg queue
    # items / kv_import chunks, kv_export pull requests, migration
    # blocks/commit payloads and SequenceSnapshot.trace.
    "TraceContext",
}

# (class, field): Optional fields that MAY ship unconditionally despite
# the class adopting omit-when-absent — grandfathered keys consumers
# already rely on being present.
OMIT_WHEN_ABSENT_EXEMPT = {
    # "model" predates the convention: recorded streams and pre-tenancy
    # consumers read the key unconditionally (None means base model).
    ("PreprocessedRequest", "model"),
}

# Wire-optional keys where a client-sent explicit ``null`` satisfies
# ``setdefault`` and silently skips the rewrite path (the PR 8
# ``"nvext": null`` bug class) — DYN305 flags setdefault on these.
NULLABLE_WIRE_KEYS = {
    "nvext",
    "annotations",
    "sampling_options",
    "stop_conditions",
}

# jit-pytree NamedTuples whose treedef must stay byte-stable: the FROZEN
# field prefix (wire/compile compatibility) — new fields must append after
# it with defaults, never reorder or insert (DYN306).
TREEDEF_FROZEN_PREFIX = {
    "SamplingParams": (
        "seeds",
        "steps",
        "temperature",
        "top_k",
        "top_p",
        "freq_penalty",
        "pres_penalty",
        "counts",
        "need_logprobs",
    ),
    "RaggedBatch": (
        "token_ids",
        "positions",
        "slot_mapping",
        "kv_lens",
        "page_indices",
        "cu_q_lens",
        "num_seqs",
    ),
}

# ---------------------------------------------------------------------------
# DYN304: SequenceState -> SequenceSnapshot threading map
# ---------------------------------------------------------------------------

# Decode-state fields the sampler/pipeline consumes and HOW each travels in
# the snapshot ("field" or "field.sub" of SequenceSnapshot).  A new
# SequenceState field must land in exactly one of these two tables or
# DYN304 fails the gate — the PR 6 bug class (grammar/adapter added to the
# state but not the snapshot ⇒ migrated streams silently diverged).
SNAPSHOT_STATE_CLASS = "SequenceState"
SNAPSHOT_CLASS = "SequenceSnapshot"

SNAPSHOT_COVERED = {
    "request_id": "request_id",
    "prompt": "token_ids",
    "output": "token_ids",  # folded: snapshot ships prompt+output
    "orig_prompt_len": "orig_prompt_len",
    "sampling_temperature": "sampling.temperature",
    "sampling_top_k": "sampling.top_k",
    "sampling_top_p": "sampling.top_p",
    "sampling_seed": "sampling.seed",
    "freq_penalty": "sampling.frequency_penalty",
    "pres_penalty": "sampling.presence_penalty",
    "logprobs": "sampling.logprobs",
    "spec_enabled": "sampling.spec_decode",
    "max_new_tokens": "stop.max_tokens",
    "min_new_tokens": "stop.min_tokens",
    "stop_token_ids": "stop.stop_token_ids",
    "ignore_eos": "stop.ignore_eos",
    "spec_k": "spec.k",
    "spec_ewma": "spec.ewma",
    "spec_bench_until": "spec.bench_until",
    "spec_next_try": "spec.next_try",
    "spec_miss": "spec.miss",
    "kv_salt": "kv_salt",
    "adapter": "adapter",
    "grammar": "grammar",
    "tenant": "tenant",
    "priority": "priority",
    # Tracing continuity: only the CONTEXT travels (trace_id/span_id wire
    # dict) — timing anchors are source-local; the target opens fresh
    # spans under the same trace_id (docs/tracing.md).
    "trace": "trace",
}

# Fields that deliberately do NOT travel, with the reason recorded:
SNAPSHOT_EXEMPT = {
    # KV/block bookkeeping: the target re-derives all of it when the
    # transferred blocks admit as a prefix hit.
    "block_seq": "rebuilt from token_ids on the target",
    "block_ids": "target-side allocation",
    "num_computed": "target-side admission state",
    "num_cached_prompt": "target-side admission metric",
    "num_sealed_blocks": "target-side sealing cursor",
    "pin_ids": "pre-admission pin never outlives the source scheduler",
    # Transient scheduler/engine flags that must NOT travel:
    "awaiting_fetch": "in-flight fetch is quiesced before freeze",
    "frozen": "migration-local flag",
    "finished": "finished sequences are not migrated",
    "enqueue_t": "per-queue latency bookkeeping",
    # Tenancy handles resolved per engine:
    "adapter_slot": "target resolves its own resident slot",
    "adapter_released": "source-side release idempotency flag",
    "grammar_state": "re-derived by advancing through resumed output",
}

# DYN304's second face (the generalization the SignalSnapshot autopilot
# fields forced): wire SNAPSHOT classes with more than one PRODUCER.  Each
# registered producer ("Class.method") must pass every field of the
# snapshot class explicitly at its construction site, or carry a
# per-producer exemption naming why the default is correct THERE.  The bug
# class: a field added to the snapshot and populated by the production
# collector but not the sim's — seeded replays then exercise a policy
# against permanently-absent signals and the sim silently stops being a
# model of the fleet.
WIRE_SNAPSHOT_PRODUCERS = {
    "SignalSnapshot": {
        "SignalCollector.snapshot": set(),
        "SimCluster.snapshot": {
            # the sim models one fleet without a real edge/engine plane;
            # these edge-derived signals stay at their absent defaults
            # (policies reading them must already tolerate None edges)
            "hit_isl_blocks",
            "hit_overlap_blocks",
            "edge_brownout_rung",
            "restore_pct",
            "host_gap",
        },
    },
}

# ---------------------------------------------------------------------------
# DYN5xx resource-lifetime model
# ---------------------------------------------------------------------------

# Each entry declares one resource class as the rule sees it:
#
# - ``acquire``: call tails that mint a handle (the call's result).
# - ``release``: call tails that return the handle to its pool.
# - ``transfer``: call tails that move OWNERSHIP somewhere else (sealing a
#   block into the prefix cache, os.replace-ing a tmp file into place) —
#   they satisfy the lifetime obligation exactly like a release.
# - ``receivers``: when set, the acquire only matches on these receiver
#   attribute names (``self.admission.acquire`` yes, ``self._lock.acquire``
#   no) — generic tails need the hint, unambiguous tails don't.
# - ``handleless``: the protocol pairs by RECEIVER, not by a returned
#   handle (admission slots, adapter refcounts keyed by name).  Handleless
#   resources are only checked when acquire and release appear in the SAME
#   function — cross-function protocols stay out of scope, like DYN102.
# - ``flag_dropped``: a bare-statement acquire whose result is discarded is
#   itself a finding (the handle is unreleasable without it).
#
# ``external`` lists tails implemented OUTSIDE the corpus (os.*) which the
# DYN504 staleness check must not demand a local definition for.
LIFETIME_RESOURCES = {
    "kv_blocks": dict(
        acquire={"allocate_sequence", "acquire_prefix", "allocate_block",
                 "_pin_prefix"},
        release={"free_sequence"},
        transfer={"seal_block"},
        receivers=None,
        handleless=False,
        flag_dropped=True,
    ),
    "adapter_slot": dict(
        acquire={"acquire"},
        release={"release"},
        transfer=set(),
        receivers={"_lora_registry", "lora_registry", "adapters",
                   "adapter_registry"},
        handleless=True,
        flag_dropped=False,
    ),
    "admission_slot": dict(
        acquire={"acquire"},
        release={"release"},
        transfer=set(),
        receivers={"admission", "_admission", "admission_controller"},
        handleless=True,
        flag_dropped=False,
    ),
    "mux_stream": dict(
        acquire={"open_stream"},
        release={"release"},
        transfer=set(),
        receivers=None,
        handleless=False,
        flag_dropped=True,
    ),
    "hub_lease": dict(
        acquire={"lease_grant"},
        release={"lease_revoke"},
        # The hub serving loop mints leases FOR remote clients: shipping
        # the id over the wire (``send``) hands the renew/revoke
        # obligation to the client side.
        transfer={"send"},
        receivers=None,
        handleless=False,
        flag_dropped=True,
    ),
    "row_slot": dict(
        acquire={"assign"},
        release={"free", "retire"},
        transfer=set(),
        receivers={"slots", "_slots", "row_slots"},
        handleless=False,
        flag_dropped=False,
    ),
    "tmp_kvblk": dict(
        acquire={"_tmp_path"},
        release={"remove", "unlink"},
        transfer={"replace", "rename"},
        receivers=None,
        handleless=False,
        flag_dropped=True,
        external={"remove", "unlink", "replace", "rename"},
    ),
}

# Call tails whose handle may be passed WITHOUT transferring ownership —
# pure builtins that cannot retain a reference.  (Used for alias
# propagation: a value built from the handle through these stays an alias.)
PURE_BUILTIN_TAILS = {
    "len", "zip", "enumerate", "list", "tuple", "set", "frozenset",
    "sorted", "reversed", "min", "max", "sum", "any", "all", "str",
    "repr", "range", "print", "isinstance", "bool", "int", "float",
    "iter", "next", "hash", "map", "filter",
}

# Custody sinks: passing a tracked handle to one of these MOVES ownership
# out of the function (into a container that outlives the frame, or into
# another task), so DYN501 stands down.  Every other call BORROWS the
# handle — the scatter/ping/publish idioms pass block ids around freely
# while the function keeps the release obligation; treating those as
# escapes would blind the rule to exactly the historical leaks
# (transfer.py scatter, the health-probe ping).
CUSTODY_SINK_TAILS = {
    "append", "appendleft", "add", "extend", "insert",
    "put", "put_nowait", "push",
    "create_task", "ensure_future",
    "setdefault", "update",
}

# Device-lock discipline (DYN502/DYN503 — the PR 11 lock-split class).
# Jitted dispatch entry points (``self.<tail>(...)`` or
# ``asyncio.to_thread(self.<tail>, ...)``) must run under ``_device_lock``
# so a concurrent dispatch can never interleave donated-buffer reuse;
# blocking host I/O must NOT run under it, or every decode step queues
# behind a disk write.
DEVICE_DISPATCH_TAILS = {"_step_fn", "_multi_fn", "_inject_fn", "_gather_fn"}
DEVICE_LOCK_NAME = "_device_lock"
# Functions sanctioned to dispatch without the lock: startup-only warmup
# compilation runs before the serving loop exists (single task, no
# concurrent dispatch possible).
DEVICE_LOCK_EXEMPT_FUNCS = {"warmup"}
# Functions whose CONTRACT is "caller holds _device_lock" (sync bodies run
# via asyncio.to_thread under the caller's lock).  Their bodies check as
# locked; every reference to them OUTSIDE the lock is itself a DYN502
# finding, so the contract is enforced at both ends.
DEVICE_LOCK_REQUIRED_FUNCS = {"_offload_store", "_restore_inject"}

# Blocking host I/O that must never run under the device lock.
HOST_BLOCKING_DOTTED = {
    "time.sleep",
    "os.fsync",
    "os.replace",
    "os.remove",
    "os.rename",
    "os.unlink",
    "shutil.copyfile",
    "shutil.move",
}
HOST_BLOCKING_TAILS = {"write_bytes", "read_bytes", "write_text", "read_text"}
HOST_BLOCKING_BARE = {"open"}

# ---------------------------------------------------------------------------
# DYN6xx compile-stability & determinism model
# ---------------------------------------------------------------------------

# Hot-path scope for DYN601: every function in these paths (prefix match)
# plus these function names (the names make fixtures/tests expressible and
# are validated for staleness by DYN604).
HOT_PATH_PATHS = ("dynamo_tpu/ops/", "dynamo_tpu/engine/pipeline.py")
HOT_PATH_FUNCTIONS = {
    "ragged_decode_attention",
    "ragged_attention",
    "write_kv_ragged",
    "fused_prefill_attention",
    "resolve_prefill_kernel",
}

# Array constructors whose result dtype depends on jax's weak-type /
# x64-flag defaults when no dtype is given.  Shape constructors are always
# ambiguous without a dtype; array/asarray only when fed a Python literal
# (an ndarray argument carries its own dtype).
SHAPE_CONSTRUCTOR_TAILS = {"zeros", "ones", "empty", "full", "arange"}
LITERAL_CONSTRUCTOR_TAILS = {"array", "asarray"}
ARRAY_NAMESPACES = ("jnp", "jax.numpy")
DTYPE_NAME_TAILS = {
    "float64", "float32", "float16", "bfloat16",
    "float8_e4m3fn", "float8_e5m2",
    "int64", "int32", "int16", "int8",
    "uint64", "uint32", "uint16", "uint8",
    "bool_", "complex64",
}

# DYN602: jit-traced dispatch sites — a raw per-request ``len(...)`` in an
# argument keys a fresh executable per length; route it through the
# power-of-two padding idiom (``1 << (n - 1).bit_length()``) or a
# registered bucket helper first.
TRACED_DISPATCH_TAILS = DEVICE_DISPATCH_TAILS
BUCKET_HELPER_TAILS = {"bit_length", "next_pow2", "pad_bucket", "round_up"}

# DYN603: deterministic cores — decision logic whose outputs must be a
# function of its inputs so tests/sim/replay stay exact.  Wall clocks are
# injected (``clock=time.monotonic`` default parameter, called as
# ``self._clock()``); RNG is seeded (``random.Random(seed)``).  Registered
# by class name and by module path.
DETERMINISTIC_CORE_CLASSES = {
    "DecisionEngine",   # planner/policy.py — scaling decisions
    "BrownoutLadder",   # llm/qos.py — degradation rungs
    "WfqQueue",         # engine/scheduler.py — virtual-time fairness
    "TimedWindow",      # llm/metrics.py — the PR 8 wall-clock bug class
    "AdapterRegistry",  # llm/tenancy/lora.py — promotion deadlines
    "DefaultWorkerSelector",  # llm/kv_router/scheduler.py — tie-breaks
    "RetryPolicy",      # runtime/resilience.py — backoff jitter
}
DETERMINISTIC_CORE_PATHS = ("dynamo_tpu/planner/sim.py",)

# Raw time sources forbidden inside deterministic cores (calls only —
# referencing ``time.monotonic`` as an injectable default is the idiom).
RAW_CLOCK_DOTTED = {
    "time.time", "time.monotonic", "time.perf_counter",
    "time.time_ns", "time.monotonic_ns", "time.perf_counter_ns",
    "datetime.now", "datetime.utcnow",
    "datetime.datetime.now", "datetime.datetime.utcnow",
}
# RNG namespaces forbidden unseeded; constructors that take an explicit
# seed argument are the sanctioned form.
RAW_RNG_PREFIXES = ("random.", "np.random.", "numpy.random.")
SEEDED_RNG_TAILS = {"Random", "default_rng"}
