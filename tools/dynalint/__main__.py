"""CLI: ``python -m tools.dynalint [paths] [options]``.

Exit codes: 0 = no non-baselined findings, 1 = new findings, 2 = bad usage.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from .baseline import (
    DEFAULT_BASELINE,
    load_baseline,
    save_baseline,
    split_by_baseline,
)
from .core import analyze_paths
from .report import render_json, render_rules, render_text
from .rules import ALL_RULES


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dynalint",
        description="async-safety, dataflow & lifetime static analyzer for "
        "dynamo_tpu (rules DYN001-007, DYN1xx-6xx; see docs/dynalint.md)",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        default=["dynamo_tpu"],
        help="files or directories to analyze (default: dynamo_tpu)",
    )
    ap.add_argument("--json", action="store_true", help="JSON report")
    ap.add_argument(
        "--rules",
        help="comma-separated rule subset (e.g. DYN001,DYN003)",
    )
    ap.add_argument(
        "--baseline",
        type=Path,
        default=DEFAULT_BASELINE,
        help=f"baseline file (default: {DEFAULT_BASELINE})",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline: every finding fails",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="rewrite the baseline from the current findings and exit 0",
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    ap.add_argument(
        "--changed-only",
        nargs="?",
        const="HEAD",
        default=None,
        metavar="REF",
        help="analyze only files changed vs REF (default HEAD) plus their "
        "one-hop reverse dependencies — the fast pre-commit mode, ~2s on "
        "a one-file change vs ~5s full (the whole corpus still feeds "
        "indexing and taint summaries; incompatible with "
        "--write-baseline)",
    )
    ap.add_argument(
        "--timings",
        action="store_true",
        help="print per-pass wall time (always included in --json)",
    )
    ap.add_argument(
        "-v", "--verbose", action="store_true", help="also list baselined"
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        print(render_rules())
        return 0

    if args.write_baseline and args.changed_only is not None:
        # A baseline written from a changed-file slice silently DROPS every
        # grandfathered finding in untouched files — they would all
        # resurface as gate-failing "new" findings on the next full run.
        print(
            "dynalint: --write-baseline requires a full-scope run; "
            "drop --changed-only",
            file=sys.stderr,
        )
        return 2

    rules = None
    if args.rules:
        rules = {r.strip().upper() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(ALL_RULES)
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}", file=sys.stderr)
            return 2

    # Anchor relative paths at the repo root (parent of tools/) so the tool
    # behaves the same from any cwd — fingerprints embed relative paths.
    root = Path(__file__).resolve().parents[2]
    timings: dict = {}
    try:
        findings = analyze_paths(
            args.paths,
            root=root,
            rules=rules,
            timings=timings,
            changed_only=args.changed_only,
        )
    except FileNotFoundError as e:
        print(f"dynalint: {e}", file=sys.stderr)
        return 2
    except RuntimeError as e:  # git failure in --changed-only
        print(f"dynalint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        save_baseline(args.baseline, findings)
        print(f"wrote {len(findings)} finding(s) to {args.baseline}")
        return 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    new, old = split_by_baseline(findings, baseline)
    if args.json:
        print(render_json(new, old, timings))
    else:
        print(render_text(new, old, args.verbose))
        if args.timings:
            per = ", ".join(
                f"{k}={v * 1e3:.0f}ms" for k, v in sorted(timings.items())
            )
            print(f"timings: {per}")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
