"""DYN5xx — resource-lifetime rules.

PagedAttention makes block *ownership* the central serving invariant, and
this repo's bug history shows the static classes that break it keep
recurring: the ``transfer.py`` leak-on-scatter-failure (PR 4/5), the
health-probe mux-slot leak (PR 9), the PR 11 device-lock split.  These
rules check the registry-declared acquire/release model
(``registry.LIFETIME_RESOURCES``) path-sensitively over each function:

- **DYN501** — every acquired handle must reach a release, a registered
  ownership TRANSFER (``seal_block``, ``os.replace``), or provably leave
  the function's custody (returned, stored on an object, handed to a
  callee) on ALL paths — including the exception edges: risky events
  (awaits, ``raise``, declared-blocking I/O, further allocations) between
  acquire and the nominal release must be covered by a ``finally`` or an
  ``except`` handler that releases the handle.  ``if handle is None:
  return`` guards are understood as the no-resource path; handle-less
  protocols (admission slots, adapter refcounts) pair by receiver and are
  only checked when acquire and release share a function (the DYN102
  scoping rule — cross-function protocols stay out of scope).
- **DYN502** — registered device-dispatch callees (``self._step_fn`` and
  friends, directly or through ``asyncio.to_thread``) must run under
  ``_device_lock``; concurrent dispatch over donated buffers is
  use-after-free on device memory.  ``warmup`` runs before the serving
  loop exists and is registry-exempt.
- **DYN503** — blocking host I/O must NOT run under ``_device_lock``
  (the PR 11 lock-split class): a disk write under the dispatch lock
  queues every decode step behind the disk.
- **DYN504** — registry staleness: a renamed acquire/release/dispatch
  symbol fails the lint instead of silently un-covering a resource class.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .callgraph import CorpusGraph, FunctionUnit
from .core import Finding, _walk_same_func, call_target, dotted_name, make_finding
from .registry import (
    CUSTODY_SINK_TAILS,
    DEVICE_DISPATCH_TAILS,
    DEVICE_LOCK_EXEMPT_FUNCS,
    DEVICE_LOCK_NAME,
    DEVICE_LOCK_REQUIRED_FUNCS,
    HOST_BLOCKING_BARE,
    HOST_BLOCKING_DOTTED,
    HOST_BLOCKING_TAILS,
    LIFETIME_RESOURCES,
    PURE_BUILTIN_TAILS,
)

LIFETIME_RULES = ("DYN501", "DYN502", "DYN503", "DYN504")

# Call tails that cannot meaningfully raise between acquire and release —
# kept out of the risk model so pure staging (padding arithmetic) between
# an allocation and its guarded dispatch does not demand a try block.
_RISK_EXEMPT_TAILS = PURE_BUILTIN_TAILS | {"bit_length"}

_ALL_ACQUIRE_TAILS = frozenset(
    t for spec in LIFETIME_RESOURCES.values() for t in spec["acquire"]
)


def _finding(
    rule: str, unit: FunctionUnit, node: ast.AST, message: str, lines: List[str]
) -> Finding:
    return make_finding(rule, unit.path, unit.qualname, node, message, lines)


def _receiver(call: ast.Call) -> Optional[str]:
    """'kv' for ``self.kv.allocate_sequence(...)``, 'conn' for
    ``conn.open_stream(...)``, None for bare-name calls."""
    d = dotted_name(call.func)
    if d is None:
        return None
    parts = d.split(".")
    return parts[-2] if len(parts) >= 2 else None


def _names_in(node: Optional[ast.AST]) -> Set[str]:
    if node is None:
        return set()
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _arg_names(call: ast.Call) -> Set[str]:
    out: Set[str] = set()
    for a in call.args:
        out |= _names_in(a)
    for kw in call.keywords:
        out |= _names_in(kw.value)
    return out


def _acquire_spec(call: ast.Call) -> Optional[Tuple[str, dict]]:
    _, tail = call_target(call)
    if tail is None or tail not in _ALL_ACQUIRE_TAILS:
        return None
    for key, spec in LIFETIME_RESOURCES.items():
        if tail in spec["acquire"]:
            recv = spec.get("receivers")
            if recv is None or _receiver(call) in recv:
                return key, spec
    return None


def _is_risky_call(call: ast.Call) -> bool:
    """Can this call plausibly raise with a handle held?  Suspension points
    are handled separately (awaits); here: declared-blocking I/O and
    further registered allocations (which fail under pressure)."""
    dotted, tail = call_target(call)
    if tail is None:
        return False
    if dotted in HOST_BLOCKING_DOTTED or tail in HOST_BLOCKING_TAILS:
        return True
    if dotted == tail and tail in HOST_BLOCKING_BARE:
        return True
    return tail in _ALL_ACQUIRE_TAILS


# ---------------------------------------------------------------------------
# DYN501: statement records
# ---------------------------------------------------------------------------


class _Rec:
    __slots__ = ("node", "kind", "calls", "has_await", "guards", "ctx", "lineno")

    def __init__(self, node, kind, calls, has_await, guards, ctx):
        self.node = node
        self.kind = kind  # "stmt" | "return" | "raise" | "for"
        self.calls = calls
        self.has_await = has_await
        self.guards = guards  # names read by enclosing if/while tests
        self.ctx = ctx  # ((try_id, where, lineno, end_lineno), ...)
        self.lineno = getattr(node, "lineno", 0)


def _own_calls(node: ast.AST) -> List[ast.Call]:
    return [n for n in _walk_same_func(node) if isinstance(n, ast.Call)]


def _has_await(node: ast.AST) -> bool:
    return any(
        isinstance(n, (ast.Await, ast.AsyncFor, ast.AsyncWith))
        for n in _walk_same_func(node)
    )


def _collect_records(fn: ast.AST) -> List[_Rec]:
    recs: List[_Rec] = []

    def header(node: ast.AST, expr: Optional[ast.AST], guards, ctx, kind="stmt"):
        calls = _own_calls(expr) if expr is not None else []
        has_aw = isinstance(node, (ast.AsyncFor, ast.AsyncWith))
        recs.append(_Rec(node, kind, calls, has_aw, guards, ctx))

    def walk(stmts: Iterable[ast.stmt], guards: frozenset, ctx: tuple) -> None:
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            if isinstance(s, (ast.If, ast.While)):
                header(s, s.test, guards, ctx)
                g = guards | _names_in(s.test)
                walk(s.body, g, ctx)
                walk(s.orelse, g, ctx)
            elif isinstance(s, (ast.For, ast.AsyncFor)):
                header(s, s.iter, guards, ctx, kind="for")
                walk(s.body, guards, ctx)
                walk(s.orelse, guards, ctx)
            elif isinstance(s, (ast.With, ast.AsyncWith)):
                for item in s.items:
                    header(s, item.context_expr, guards, ctx)
                walk(s.body, guards, ctx)
            elif isinstance(s, ast.Try):
                tid = id(s)
                span = (s.lineno, getattr(s, "end_lineno", s.lineno) or s.lineno)
                walk(s.body, guards, ctx + ((tid, "body") + span,))
                for h in s.handlers:
                    walk(h.body, guards, ctx + ((tid, "handler") + span,))
                walk(s.orelse, guards, ctx + ((tid, "orelse") + span,))
                walk(s.finalbody, guards, ctx + ((tid, "finally") + span,))
            elif isinstance(s, ast.Return):
                recs.append(
                    _Rec(s, "return", _own_calls(s), _has_await(s), guards, ctx)
                )
            elif isinstance(s, ast.Raise):
                recs.append(
                    _Rec(s, "raise", _own_calls(s), _has_await(s), guards, ctx)
                )
            else:
                recs.append(
                    _Rec(s, "stmt", _own_calls(s), _has_await(s), guards, ctx)
                )

    walk(fn.body, frozenset(), ())
    return recs


# ---------------------------------------------------------------------------
# DYN501: per-function lifetime analysis
# ---------------------------------------------------------------------------


class _Group:
    """One tracked acquisition: the handle's aliases and lifetime events."""

    __slots__ = ("key", "spec", "aliases", "recv", "acq_rec", "acq_call",
                 "events")

    def __init__(self, key, spec, aliases, recv, acq_rec, acq_call):
        self.key = key
        self.spec = spec
        self.aliases: Set[str] = set(aliases)
        self.recv = recv  # handleless pairing receiver, or None
        self.acq_rec = acq_rec
        self.acq_call = acq_call
        # (kind, lineno, ctx_class, span, node) where kind in
        # release/transfer/risky/return
        self.events: List[tuple] = []


def _ctx_class(rec: _Rec) -> Tuple[str, Tuple[int, int]]:
    """('finally'|'handler'|'plain', covering try span)."""
    for tid, where, ln, end in reversed(rec.ctx):
        if where == "finally":
            return "finally", (ln, end)
        if where == "handler":
            return "handler", (ln, end)
    return "plain", (rec.lineno, rec.lineno)


def _in_handler_of(rec: _Rec, body_tids: Set[int]) -> bool:
    return any(
        where == "handler" and tid in body_tids for tid, where, _l, _e in rec.ctx
    )


def _release_calls(rec: _Rec, g: _Group) -> List[Tuple[str, ast.Call]]:
    out = []
    for c in rec.calls:
        _, tail = call_target(c)
        if tail is None:
            continue
        kinds = []
        if tail in g.spec["release"]:
            kinds.append("release")
        if tail in g.spec["transfer"]:
            kinds.append("transfer")
        if not kinds:
            continue
        if g.recv is not None:
            if _receiver(c) == g.recv:
                out.append((kinds[0], c))
        elif g.aliases & _arg_names(c):
            out.append((kinds[0], c))
    return out


def _escapes(rec: _Rec, g: _Group) -> bool:
    if g.recv is not None:
        return False  # handleless: nothing to escape
    node = rec.node
    if rec.kind == "return":
        return bool(g.aliases & _names_in(node.value))
    for sub in _walk_same_func(node):
        if isinstance(sub, (ast.Yield, ast.YieldFrom)):
            if g.aliases & _names_in(sub):
                return True
    if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        value = getattr(node, "value", None)
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        vnames = _names_in(value)
        for tgt in targets:
            elts = tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
            for el in elts:
                if isinstance(el, ast.Attribute) and g.aliases & vnames:
                    return True
                if isinstance(el, ast.Subscript):
                    base = el.value
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    # storing into OBJECT state escapes; a scratch local
                    # (numpy staging buffer) does not change custody
                    if isinstance(base, ast.Attribute) and g.aliases & (
                        vnames | _names_in(el.slice)
                    ):
                        return True
    for c in rec.calls:
        if c is g.acq_call:
            continue
        _, tail = call_target(c)
        if tail is None:
            continue
        # Custody sinks and constructors (PascalCase: the object stores the
        # handle and owns its cleanup, the _RemoteStreamIter idiom) take
        # ownership; every other call BORROWS (scatter/ping/publish pass
        # block ids around while the function keeps the release obligation).
        ctor = tail.lstrip("_")[:1].isupper()
        if tail not in CUSTODY_SINK_TAILS and not ctor:
            continue
        if g.aliases & _arg_names(c):
            return True
    return False


def _extend_aliases(rec: _Rec, g: _Group) -> None:
    if g.recv is not None:
        return
    node = rec.node
    if rec.kind == "for":
        it = node.iter
        if g.aliases & _names_in(it) and all(
            (call_target(c)[1] or "?") in PURE_BUILTIN_TAILS
            for c in _own_calls(it)
        ):
            g.aliases |= _names_in_targets(node.target)
        return
    if isinstance(node, ast.Assign) and node.value is not None:
        if not (g.aliases & _names_in(node.value)):
            return
        if any(
            (call_target(c)[1] or "?") not in PURE_BUILTIN_TAILS
            for c in _own_calls(node.value)
        ):
            return
        for tgt in node.targets:
            g.aliases |= _names_in_targets(tgt)


def _names_in_targets(tgt: ast.AST) -> Set[str]:
    elts = tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
    return {el.id for el in elts if isinstance(el, ast.Name)}


def _try_acquire(rec: _Rec, findings, unit, lines) -> List[_Group]:
    groups: List[_Group] = []
    node = rec.node
    for c in rec.calls:
        m = _acquire_spec(c)
        if m is None:
            continue
        key, spec = m
        _, tail = call_target(c)
        if spec["handleless"]:
            groups.append(_Group(key, spec, (), _receiver(c), rec, c))
            continue
        if rec.kind == "return":
            continue  # ownership handed straight to the caller
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            tgt = targets[0] if len(targets) == 1 else None
            if tgt is None:
                continue
            if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                continue  # stored straight into object/container state
            names = _names_in_targets(tgt)
            if isinstance(tgt, ast.Tuple) and len(names) != len(tgt.elts):
                continue  # some element escapes into an attribute
            if names:
                groups.append(_Group(key, spec, names, None, rec, c))
            continue
        if isinstance(node, ast.Expr) and node.value is not None:
            # bare-statement acquire: is the call the whole statement (its
            # result discarded) or an argument to something else (handed
            # off)?
            top = node.value
            if isinstance(top, ast.Await):
                top = top.value
            if top is c and spec["flag_dropped"]:
                findings.append(
                    _finding(
                        "DYN501",
                        unit,
                        c,
                        f"result of `{tail}()` is discarded: the {key} "
                        "handle it returns is the only way to release the "
                        "resource — bind it and pair it with "
                        f"`{'`/`'.join(sorted(spec['release']))}`",
                        lines,
                    )
                )
    return groups


def _check_dyn501(unit: FunctionUnit, lines: List[str]) -> List[Finding]:
    recs = _collect_records(unit.node)
    findings: List[Finding] = []
    groups: List[_Group] = []

    for rec in recs:
        for g in groups:
            body_tids = {tid for tid, where, _l, _e in g.acq_rec.ctx
                         if where == "body"}
            if _in_handler_of(rec, body_tids):
                # handlers of the try the acquire sits in run on the
                # acquire-FAILED path: no handle is held there
                continue
            rels = _release_calls(rec, g)
            if rels:
                cls, span = _ctx_class(rec)
                for kind, c in rels:
                    g.events.append((kind, rec.lineno, cls, span, c))
                continue
            if _escapes(rec, g):
                # Custody moved out of the function (returned, stored on an
                # object, handed to a container/task/constructor): counts
                # exactly like a registered transfer — the nominal path is
                # discharged here, but risky points BEFORE it still need
                # exception-edge coverage.
                cls, span = _ctx_class(rec)
                g.events.append(("transfer", rec.lineno, cls, span, rec.node))
                continue
            _extend_aliases(rec, g)
            if (
                rec.has_await
                or rec.kind == "raise"
                or any(_is_risky_call(c) for c in rec.calls)
            ):
                cls, span = _ctx_class(rec)
                g.events.append(("risky", rec.lineno, cls, span, rec.node))
            if rec.kind == "return" and not (rec.guards & g.aliases):
                cls, span = _ctx_class(rec)
                g.events.append(("return", rec.lineno, cls, span, rec.node))
        groups.extend(_try_acquire(rec, findings, unit, lines))

    for g in groups:
        rels = [e for e in g.events if e[0] in ("release", "transfer")]
        what = f"{g.key} handle" if g.recv is None else f"{g.key} (via `{g.recv}`)"
        release_hint = "`" + "`/`".join(sorted(g.spec["release"])) + "`"
        if not rels:
            if g.recv is not None:
                continue  # handleless cross-function protocol: out of scope
            findings.append(
                _finding(
                    "DYN501",
                    unit,
                    g.acq_call,
                    f"{what} acquired here never reaches a release "
                    f"({release_hint}), a registered ownership transfer, or "
                    "a custody hand-off on any path — the resource leaks",
                    lines,
                )
            )
            continue
        finally_rels = [e for e in rels if e[2] == "finally"]
        handler_rels = [e for e in rels if e[2] == "handler"]
        plain_rels = [e for e in rels if e[2] == "plain"]
        if not plain_rels and not finally_rels:
            findings.append(
                _finding(
                    "DYN501",
                    unit,
                    handler_rels[0][4],
                    f"{what} is released only on the exception path — the "
                    "nominal path leaks it; release in a `finally` or on "
                    "the fall-through path too",
                    lines,
                )
            )
            continue
        covered = [e[3] for e in finally_rels + handler_rels]
        if plain_rels:
            bound = min(e[1] for e in plain_rels)
        else:
            bound = max(e[3][1] for e in finally_rels)
        bad_risky = [
            e
            for e in g.events
            if e[0] == "risky"
            and e[1] < bound
            and not any(lo <= e[1] <= hi for lo, hi in covered)
        ]
        if bad_risky:
            findings.append(
                _finding(
                    "DYN501",
                    unit,
                    bad_risky[0][4],
                    f"an exception here leaks the {what}: this point sits "
                    "between acquire and release with no `finally`/handler "
                    f"releasing it ({release_hint}) — cover the span (the "
                    "transfer.py idiom: `except BaseException: "
                    "free(...); raise`)",
                    lines,
                )
            )
            continue
        fin_spans = [e[3] for e in finally_rels]
        bad_ret = [
            e
            for e in g.events
            if e[0] == "return"
            and e[1] < bound
            and not any(lo <= e[1] <= hi for lo, hi in fin_spans)
        ]
        if bad_ret:
            findings.append(
                _finding(
                    "DYN501",
                    unit,
                    bad_ret[0][4],
                    f"early return between acquire and release leaks the "
                    f"{what} — release before returning or move the "
                    "release into a `finally`",
                    lines,
                )
            )
    return findings


# ---------------------------------------------------------------------------
# DYN502 / DYN503: device-lock discipline
# ---------------------------------------------------------------------------


def _is_device_lock(expr: ast.AST) -> bool:
    target = expr.func if isinstance(expr, ast.Call) else expr
    return DEVICE_LOCK_NAME in (dotted_name(target) or "")


def _dispatch_tail(call: ast.Call) -> Optional[str]:
    """The device-dispatch tail a call invokes: ``self._step_fn(...)``,
    ``asyncio.to_thread(self._step_fn, ...)``, or a registered
    lock-required callee (whose contract is "caller holds the lock")."""
    lockish = DEVICE_DISPATCH_TAILS | DEVICE_LOCK_REQUIRED_FUNCS
    dotted, tail = call_target(call)
    if tail in lockish:
        return tail
    if tail == "to_thread" and call.args:
        d = dotted_name(call.args[0]) or ""
        t = d.rsplit(".", 1)[-1]
        if t in lockish:
            return t
    return None


def _check_device(
    unit: FunctionUnit, lines: List[str], rules: Set[str]
) -> List[Finding]:
    findings: List[Finding] = []
    check_502 = "DYN502" in rules and unit.name not in DEVICE_LOCK_EXEMPT_FUNCS
    check_503 = "DYN503" in rules

    # Closures get the lock status of their USE sites, not their definition
    # site: the mirror/offload idiom is `async with self._device_lock:
    # await asyncio.to_thread(run_u)` with the dispatch inside `run_u`.
    nested_defs: Dict[str, ast.AST] = {
        n.name: n
        for n in ast.walk(unit.node)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        and n is not unit.node
    }
    ref_locked: Dict[str, List[bool]] = {}

    def walk(node: ast.AST, locked: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(node, ast.Name) and node.id in nested_defs:
            ref_locked.setdefault(node.id, []).append(locked)
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = locked or any(
                _is_device_lock(i.context_expr) for i in node.items
            )
            for i in node.items:
                walk(i.context_expr, locked)
            for s in node.body:
                walk(s, inner)
            return
        if isinstance(node, ast.Call):
            tail = _dispatch_tail(node)
            if check_502 and tail is not None and not locked:
                findings.append(
                    _finding(
                        "DYN502",
                        unit,
                        node,
                        f"device dispatch `{tail}` outside `async with "
                        f"self.{DEVICE_LOCK_NAME}`: a concurrent dispatch "
                        "can reuse donated buffers mid-flight — take the "
                        "lock (or register the function as startup-exempt)",
                        lines,
                    )
                )
            if check_503 and locked:
                dotted, t = call_target(node)
                if (
                    dotted in HOST_BLOCKING_DOTTED
                    or t in HOST_BLOCKING_TAILS
                    or (dotted == t and t in HOST_BLOCKING_BARE)
                ):
                    findings.append(
                        _finding(
                            "DYN503",
                            unit,
                            node,
                            f"blocking host I/O `{dotted or t}` under "
                            f"`{DEVICE_LOCK_NAME}`: every decode dispatch "
                            "queues behind it (the PR 11 lock-split class) "
                            "— do the I/O outside the lock",
                            lines,
                        )
                    )
        for child in ast.iter_child_nodes(node):
            walk(child, locked)

    for stmt in unit.node.body:
        walk(stmt, unit.name in DEVICE_LOCK_REQUIRED_FUNCS)
    # A closure every use of which is under the lock inherits it; one
    # unlocked use (or no visible use) and its dispatches must self-lock.
    done: Set[str] = set()
    progressed = True
    while progressed:
        progressed = False
        for name, dnode in nested_defs.items():
            if name in done or name not in ref_locked:
                continue
            done.add(name)
            progressed = True
            eff = all(ref_locked[name])
            for stmt in dnode.body:
                walk(stmt, eff)
    return findings


# ---------------------------------------------------------------------------
# DYN504: registry staleness
# ---------------------------------------------------------------------------

REGISTRY_PATH = "tools/dynalint/registry.py"


def _registry_finding(rule: str, symbol: str, message: str) -> Finding:
    return Finding(
        rule=rule,
        path=REGISTRY_PATH,
        line=1,
        col=0,
        message=message,
        symbol=symbol,
        snippet="",
    )


def corpus_symbols(graph: CorpusGraph) -> Tuple[Set[str], Set[str], Set[str]]:
    """(function names, attribute-store names, class names) across the
    corpus — the symbol universe registry entries must resolve against."""
    attrs: Set[str] = set()
    classes: Set[str] = set()
    for _path, _src, tree in graph.files:
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                classes.add(node.name)
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    elts = tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
                    for el in elts:
                        if isinstance(el, ast.Attribute):
                            attrs.add(el.attr)
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                if isinstance(node.target, ast.Attribute):
                    attrs.add(node.target.attr)
    return set(graph.by_name), attrs, classes


def _is_real_corpus(graph: CorpusGraph) -> bool:
    """Staleness only makes sense against the real tree — a synthetic test
    corpus defines almost none of the registered symbols by construction."""
    return any(p.startswith("dynamo_tpu/") for p, _s, _t in graph.files)


def _check_staleness(graph: CorpusGraph) -> List[Finding]:
    if not _is_real_corpus(graph):
        return []
    findings: List[Finding] = []
    funcs, attrs, _classes = corpus_symbols(graph)
    known = funcs | attrs
    for key, spec in LIFETIME_RESOURCES.items():
        tails = set(spec["acquire"]) | set(spec["release"]) | set(spec["transfer"])
        for tail in sorted(tails - set(spec.get("external", ()))):
            if tail not in known:
                findings.append(
                    _registry_finding(
                        "DYN504",
                        f"LIFETIME_RESOURCES[{key}].{tail}",
                        f"stale lifetime registry entry: `{tail}` (resource "
                        f"`{key}`) is defined nowhere in the corpus — the "
                        "resource class is silently un-covered; rename the "
                        "entry or the symbol",
                    )
                )
    for tail in sorted(DEVICE_DISPATCH_TAILS):
        if tail not in known:
            findings.append(
                _registry_finding(
                    "DYN504",
                    f"DEVICE_DISPATCH_TAILS.{tail}",
                    f"stale device-dispatch registry entry: `{tail}` is "
                    "never assigned in the corpus — the lock discipline no "
                    "longer covers it",
                )
            )
    for name in sorted(DEVICE_LOCK_REQUIRED_FUNCS):
        if name not in funcs:
            findings.append(
                _registry_finding(
                    "DYN504",
                    f"DEVICE_LOCK_REQUIRED_FUNCS.{name}",
                    f"stale lock-required registry entry: `{name}` is "
                    "defined nowhere in the corpus — its call sites are no "
                    "longer held to the caller-holds-the-lock contract",
                )
            )
    return findings


# ---------------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------------


def check_lifetime(
    graph: CorpusGraph,
    rules: Set[str],
    lines_of: Dict[str, List[str]],
    scope: Optional[Set[str]] = None,
) -> List[Finding]:
    findings: List[Finding] = []
    per_fn = {"DYN501", "DYN502", "DYN503"} & rules
    if per_fn:
        # Nested closures are separate FunctionUnits, but the device-lock
        # discipline resolves them from their ENCLOSING function (lock
        # status flows from the use site into the closure body), so skip
        # them here to avoid double-checking with a blank lock context.
        nested_ids = {
            id(n)
            for u in graph.functions
            for n in ast.walk(u.node)
            if n is not u.node
            and isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        for unit in graph.functions:
            if scope is not None and unit.path not in scope:
                continue
            lines = lines_of[unit.path]
            if "DYN501" in rules:
                findings.extend(_check_dyn501(unit, lines))
            if ("DYN502" in rules or "DYN503" in rules) and (
                id(unit.node) not in nested_ids
            ):
                findings.extend(_check_device(unit, lines, rules))
    if "DYN504" in rules:
        # Registry-anchored: reported on full runs; --changed-only scopes
        # it out (CI always runs the full corpus, so staleness still gates).
        findings.extend(_check_staleness(graph))
    return findings
