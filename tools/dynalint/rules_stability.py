"""DYN6xx — compile-stability & determinism rules.

DistServe's goodput math only holds while the decode hot path's latency
distribution is stationary — and on TPU the two ways it silently stops
being stationary are (a) a jit cache-key that varies per request (every
novel key is a multi-second XLA compile in the serving path) and (b)
decision logic that consults the wall clock or unseeded RNG (the PR 8
``TimedWindow`` bug: brownout rungs wedged because the window compared
``time.time()`` against a monotonic deadline).  Both are registry-scoped
(``registry.HOT_PATH_*`` / ``DETERMINISTIC_CORE_*``) so the rules state
project policy, not style:

- **DYN601** — dtype-ambiguous array constructors in registered hot-path
  functions: ``jnp.zeros(shape)`` picks its dtype from the x64 flag and
  weak-type promotion, so the same call site can key *different*
  executables across processes (and silently double the KV bytes).  Shape
  constructors always need an explicit dtype; ``array``/``asarray`` only
  when fed a Python literal — an ndarray argument carries its own dtype
  (that is the pipeline.py cache-key idiom).
- **DYN602** — raw per-request ``len(...)`` flowing into a registered
  traced-dispatch argument: every distinct length keys a fresh compile.
  Lengths must round through the power-of-two padding idiom
  (``1 << (n - 1).bit_length()``) or a registered bucket helper.
- **DYN603** — raw clock/RNG *calls* inside registered deterministic
  cores.  Referencing ``time.monotonic`` as an injectable default is the
  sanctioned idiom; *calling* it inside the core is the bug.  RNG must be
  seeded at construction (``random.Random(seed)``); module-level
  ``random.random()`` / bare ``Random()`` are findings.
- **DYN604** — stability-registry staleness, same contract as DYN504: a
  renamed hot-path function or deterministic-core class must fail the
  lint, not silently drop out of coverage.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .callgraph import CorpusGraph, FunctionUnit
from .core import Finding, _walk_same_func, call_target, dotted_name, make_finding
from .registry import (
    ARRAY_NAMESPACES,
    BUCKET_HELPER_TAILS,
    DETERMINISTIC_CORE_CLASSES,
    DETERMINISTIC_CORE_PATHS,
    DTYPE_NAME_TAILS,
    HOT_PATH_FUNCTIONS,
    HOT_PATH_PATHS,
    LITERAL_CONSTRUCTOR_TAILS,
    RAW_CLOCK_DOTTED,
    RAW_RNG_PREFIXES,
    SEEDED_RNG_TAILS,
    SHAPE_CONSTRUCTOR_TAILS,
    TRACED_DISPATCH_TAILS,
)
from .rules_lifetime import REGISTRY_PATH, _is_real_corpus, _registry_finding

STABILITY_RULES = ("DYN601", "DYN602", "DYN603", "DYN604")


def _finding(
    rule: str, unit: FunctionUnit, node: ast.AST, message: str, lines: List[str]
) -> Finding:
    return make_finding(rule, unit.path, unit.qualname, node, message, lines)


def _is_hot_path(unit: FunctionUnit) -> bool:
    return unit.path.startswith(HOT_PATH_PATHS) or unit.name in HOT_PATH_FUNCTIONS


def _is_deterministic_core(unit: FunctionUnit) -> bool:
    return (
        unit.class_name in DETERMINISTIC_CORE_CLASSES
        or unit.path in DETERMINISTIC_CORE_PATHS
    )


# ---------------------------------------------------------------------------
# DYN601
# ---------------------------------------------------------------------------


def _dtype_like(node: ast.AST) -> bool:
    d = dotted_name(node)
    if d is None:
        return False
    tail = d.rsplit(".", 1)[-1]
    return tail in DTYPE_NAME_TAILS or "dtype" in tail.lower()


def _has_explicit_dtype(call: ast.Call) -> bool:
    if any(kw.arg == "dtype" for kw in call.keywords):
        return True
    return any(_dtype_like(a) for a in call.args)


_LITERALISH = (ast.List, ast.Tuple, ast.Constant, ast.ListComp, ast.GeneratorExp)


def _check_dyn601(unit: FunctionUnit, lines: List[str]) -> List[Finding]:
    findings: List[Finding] = []
    for node in _walk_same_func(unit.node):
        if not isinstance(node, ast.Call):
            continue
        dotted, tail = call_target(node)
        if dotted is None or tail is None or "." not in dotted:
            continue
        ns = dotted.rsplit(".", 1)[0]
        if ns not in ARRAY_NAMESPACES:
            continue
        if tail in SHAPE_CONSTRUCTOR_TAILS:
            if not _has_explicit_dtype(node):
                findings.append(
                    _finding(
                        "DYN601",
                        unit,
                        node,
                        f"`{dotted}` without an explicit dtype on a "
                        "registered hot-path function: the result dtype "
                        "follows the x64 flag / weak-type promotion, so the "
                        "jit cache key (and KV bytes) can differ across "
                        "processes — pass dtype= explicitly",
                        lines,
                    )
                )
        elif tail in LITERAL_CONSTRUCTOR_TAILS:
            if (
                node.args
                and isinstance(node.args[0], _LITERALISH)
                and not _has_explicit_dtype(node)
            ):
                findings.append(
                    _finding(
                        "DYN601",
                        unit,
                        node,
                        f"`{dotted}` over a Python literal without a dtype "
                        "on a registered hot-path function: literal "
                        "promotion is flag-dependent and destabilizes the "
                        "jit cache key — pass dtype= (an ndarray argument "
                        "would carry its own dtype and is fine)",
                        lines,
                    )
                )
    return findings


# ---------------------------------------------------------------------------
# DYN602
# ---------------------------------------------------------------------------


def _dispatch_args(call: ast.Call) -> Optional[List[ast.AST]]:
    """Traced-call argument expressions, or None if not a dispatch site.
    Handles both ``self._step_fn(...)`` and the engine's
    ``asyncio.to_thread(self._step_fn, ...)`` indirection."""
    _, tail = call_target(call)
    if tail in TRACED_DISPATCH_TAILS:
        return list(call.args) + [kw.value for kw in call.keywords]
    if tail == "to_thread" and call.args:
        d = dotted_name(call.args[0]) or ""
        if d.rsplit(".", 1)[-1] in TRACED_DISPATCH_TAILS:
            return list(call.args[1:]) + [kw.value for kw in call.keywords]
    return None


def _bucketed(expr: ast.AST) -> bool:
    for sub in ast.walk(expr):
        if isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.LShift):
            return True
        if isinstance(sub, ast.Call):
            _, t = call_target(sub)
            if t in BUCKET_HELPER_TAILS:
                return True
    return False


def _check_dyn602(unit: FunctionUnit, lines: List[str]) -> List[Finding]:
    findings: List[Finding] = []
    for node in _walk_same_func(unit.node):
        if not isinstance(node, ast.Call):
            continue
        args = _dispatch_args(node)
        if args is None:
            continue
        for arg in args:
            if _bucketed(arg):
                continue
            for sub in ast.walk(arg):
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Name)
                    and sub.func.id == "len"
                ):
                    findings.append(
                        _finding(
                            "DYN602",
                            unit,
                            sub,
                            "raw `len(...)` flows into a traced dispatch "
                            "argument: every distinct length keys a fresh "
                            "XLA compile in the serving path — round "
                            "through `1 << (n - 1).bit_length()` or a "
                            "registered bucket helper first",
                            lines,
                        )
                    )
                    break
    return findings


# ---------------------------------------------------------------------------
# DYN603
# ---------------------------------------------------------------------------


def _check_dyn603(unit: FunctionUnit, lines: List[str]) -> List[Finding]:
    findings: List[Finding] = []
    for node in _walk_same_func(unit.node):
        if not isinstance(node, ast.Call):
            continue
        dotted, tail = call_target(node)
        if dotted is None:
            continue
        if dotted in RAW_CLOCK_DOTTED:
            findings.append(
                _finding(
                    "DYN603",
                    unit,
                    node,
                    f"`{dotted}()` called inside a registered deterministic "
                    "core: decisions stop being a function of their inputs "
                    "(the PR 8 TimedWindow wall-clock class) — inject the "
                    "clock (`clock=time.monotonic` default param, call "
                    "`self._clock()`)",
                    lines,
                )
            )
            continue
        if dotted.startswith(RAW_RNG_PREFIXES):
            if tail in SEEDED_RNG_TAILS and node.args:
                continue  # random.Random(seed) / default_rng(seed): sanctioned
            findings.append(
                _finding(
                    "DYN603",
                    unit,
                    node,
                    f"`{dotted}()` inside a registered deterministic core "
                    "draws from process-global/unseeded RNG: replay and "
                    "sim diverge run-to-run — construct a seeded generator "
                    "(`random.Random(seed)` / `np.random.default_rng(seed)`)"
                    " and draw from it",
                    lines,
                )
            )
    return findings


# ---------------------------------------------------------------------------
# DYN604: stability-registry staleness
# ---------------------------------------------------------------------------


def _check_staleness(graph: CorpusGraph) -> List[Finding]:
    if not _is_real_corpus(graph):
        return []
    findings: List[Finding] = []
    corpus_paths = {p for p, _s, _t in graph.files}
    classes: Set[str] = set()
    for _p, _s, tree in graph.files:
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                classes.add(node.name)
    for name in sorted(HOT_PATH_FUNCTIONS):
        if name not in graph.by_name:
            findings.append(
                _registry_finding(
                    "DYN604",
                    f"HOT_PATH_FUNCTIONS.{name}",
                    f"stale hot-path registry entry: `{name}` is defined "
                    "nowhere in the corpus — dtype/shape discipline "
                    "silently stopped covering it",
                )
            )
    for path in sorted(DETERMINISTIC_CORE_PATHS):
        if path not in corpus_paths:
            findings.append(
                _registry_finding(
                    "DYN604",
                    f"DETERMINISTIC_CORE_PATHS.{path}",
                    f"stale deterministic-core registry entry: `{path}` is "
                    "not in the corpus — the module moved out of clock/RNG "
                    "coverage",
                )
            )
    for cls in sorted(DETERMINISTIC_CORE_CLASSES):
        if cls not in classes:
            findings.append(
                _registry_finding(
                    "DYN604",
                    f"DETERMINISTIC_CORE_CLASSES.{cls}",
                    f"stale deterministic-core registry entry: class "
                    f"`{cls}` is defined nowhere in the corpus — rename "
                    "the entry or the class",
                )
            )
    return findings


# ---------------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------------


def check_stability(
    graph: CorpusGraph,
    rules: Set[str],
    lines_of: Dict[str, List[str]],
    scope: Optional[Set[str]] = None,
) -> List[Finding]:
    findings: List[Finding] = []
    for unit in graph.functions:
        if scope is not None and unit.path not in scope:
            continue
        lines = lines_of[unit.path]
        if _is_hot_path(unit):
            if "DYN601" in rules:
                findings.extend(_check_dyn601(unit, lines))
        if "DYN602" in rules:
            findings.extend(_check_dyn602(unit, lines))
        if "DYN603" in rules and _is_deterministic_core(unit):
            findings.extend(_check_dyn603(unit, lines))
    if "DYN604" in rules:
        findings.extend(_check_staleness(graph))
    return findings
