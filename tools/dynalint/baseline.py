"""Baseline file: grandfathered findings that do not fail the gate.

The baseline is a committed JSON file of finding fingerprints (rule + path +
enclosing symbol + normalized snippet hash — no line numbers, so unrelated
edits don't churn it).  New findings fail; baselined ones are reported but
exit 0.  `--write-baseline` regenerates it from the current tree; the gate
test additionally caps its size so the debt can only shrink.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Sequence, Tuple

from .core import Finding

BASELINE_VERSION = 1
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def load_baseline(path: Path) -> Dict[str, Dict[str, str]]:
    """fingerprint -> entry dict; missing file means an empty baseline."""
    if not path.exists():
        return {}
    data = json.loads(path.read_text())
    return {e["fingerprint"]: e for e in data.get("findings", [])}


def save_baseline(path: Path, findings: Sequence[Finding]) -> None:
    entries = [
        {
            "fingerprint": f.fingerprint(),
            "rule": f.rule,
            "path": f.path,
            "symbol": f.symbol,
            "snippet": f.snippet,
        }
        for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule))
    ]
    path.write_text(
        json.dumps(
            {"version": BASELINE_VERSION, "findings": entries}, indent=2
        )
        + "\n"
    )


def split_by_baseline(
    findings: Sequence[Finding], baseline: Dict[str, Dict[str, str]]
) -> Tuple[List[Finding], List[Finding]]:
    """(new, grandfathered) — only `new` fails the run."""
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        (old if f.fingerprint() in baseline else new).append(f)
    return new, old
