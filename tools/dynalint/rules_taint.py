"""DYN2xx — wire-taint rules.

PR 8's review pass caught an API key one hop away from a Prometheus label;
the fix (hash at resolution, escape at render) was manual.  These rules
make the class mechanical: wire-controlled values (HTTP headers, ``nvext``
fields, the OpenAI ``model`` field, hub-delivered payloads) must pass a
sanitizer (``escape_label`` / ``hash_credential`` / hashing / numeric
coercion — registry.py SANITIZER_TAILS) before reaching:

- **DYN201** — a Prometheus label: ``metric.labels(...)`` arguments and
  f-string label positions (``…{name="{value}"}…``) in exposition text.
  Unescaped labels are cardinality bombs and exposition-injection vectors.
- **DYN202** — a log call, when the taint is CREDENTIAL-grade (API key /
  bearer token).  Model names in logs are fine; secrets are not.
- **DYN203** — a hub key/subject (``kv_put``/``queue_push``/…, first
  argument): un-escaped wire data in a shared-namespace key can escape its
  prefix.
- **DYN204** — label hygiene, the dataflow-free backstop: EVERY f-string
  label interpolation must be a sanitizer call / numeric expression,
  whether or not taint can be proven (render methods typically read from
  dicts the dataflow cannot see through).  The fix is to escape at the
  render site — exactly once: helpers must hand RAW values to the render
  (escape_label is not idempotent; double-wrapping corrupts the label).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import CorpusGraph, FunctionUnit
from .core import Finding, call_target, make_finding
from .dataflow import CREDENTIAL, TaintEvaluator, TaintModel, real_tags
from .registry import (
    HUB_KEY_SINK_TAILS,
    LABEL_HYGIENE_EXEMPT,
    LABEL_SAFE_CALLS,
    LABEL_SINK_TAILS,
    LOG_RECEIVERS,
    LOG_SINK_TAILS,
)

TAINT_RULES = ("DYN201", "DYN202", "DYN203", "DYN204")


def _finding(
    rule: str, unit: FunctionUnit, node: ast.AST, message: str, lines: List[str]
) -> Finding:
    return make_finding(rule, unit.path, unit.qualname, node, message, lines)


# ---------------------------------------------------------------------------
# label-position detection in f-strings
# ---------------------------------------------------------------------------


def label_values(js: ast.JoinedStr) -> List[ast.FormattedValue]:
    """FormattedValues sitting in a Prometheus label position: the literal
    chunk immediately before ends with ``="`` and the exposition shape
    (``{`` earlier in the literal text) is present.  ``f'..._total{{t="{x}"}} …'``
    parses to chunks ``…_total{t="`` / ``"}} …`` — the ``{{`` escape is
    already unescaped in the Constant."""
    out: List[ast.FormattedValue] = []
    seen_brace = False
    prev_literal: Optional[str] = None
    for v in js.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            if "{" in v.value:
                seen_brace = True
            prev_literal = v.value
        elif isinstance(v, ast.FormattedValue):
            if seen_brace and prev_literal is not None and prev_literal.endswith('="'):
                out.append(v)
            prev_literal = None
    return out


def _is_label_safe(expr: ast.AST, ev: Optional[TaintEvaluator]) -> bool:
    """Sanitizer call / numeric / constant — safe in a label position."""
    if isinstance(expr, ast.Constant):
        return True
    if isinstance(expr, ast.Call):
        _, tail = call_target(expr)
        return tail in LABEL_SAFE_CALLS
    if isinstance(expr, (ast.BinOp, ast.UnaryOp, ast.Compare)):
        return True  # arithmetic/boolean — numbers, not wire strings
    if isinstance(expr, ast.Name) and ev is not None:
        src = ev.sanitized_names.get(expr.id)
        if src:
            return True
    return False


# ---------------------------------------------------------------------------
# sink pass
# ---------------------------------------------------------------------------


class _SinkVisitor:
    def __init__(
        self,
        unit: FunctionUnit,
        rules: Set[str],
        lines: List[str],
        findings: List[Finding],
    ):
        self.unit = unit
        self.rules = rules
        self.lines = lines
        self.findings = findings

    def __call__(self, stmt: ast.stmt, ev: TaintEvaluator) -> None:
        # Only this statement's OWN expressions: nested statements get
        # their own visit after the walker has processed the assignments
        # between here and there (a sink inside a loop body must see the
        # loop body's sanitizer assignments in env).
        stack = [
            c
            for c in ast.iter_child_nodes(stmt)
            if not isinstance(c, (ast.stmt, ast.excepthandler))
        ]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Call):
                self._call(node, ev)
            elif isinstance(node, ast.JoinedStr):
                self._fstring(node, ev)
            stack.extend(
                c
                for c in ast.iter_child_nodes(node)
                if not isinstance(c, (ast.stmt, ast.excepthandler))
            )

    def _call(self, call: ast.Call, ev: TaintEvaluator) -> None:
        dotted, tail = call_target(call)
        if tail in LABEL_SINK_TAILS and "DYN201" in self.rules:
            for arg in list(call.args) + [kw.value for kw in call.keywords]:
                tags = real_tags(ev.tags(arg))
                if tags:
                    self.findings.append(
                        _finding(
                            "DYN201",
                            self.unit,
                            arg,
                            "wire-controlled value reaches a Prometheus "
                            "label via .labels(...) without a sanitizer — "
                            "escape_label()/hash_credential() it first "
                            f"(taint: {', '.join(sorted(tags))})",
                            self.lines,
                        )
                    )
        if (
            tail in LOG_SINK_TAILS
            and "DYN202" in self.rules
            and isinstance(call.func, ast.Attribute)
            and isinstance(call.func.value, ast.Name)
            and call.func.value.id in LOG_RECEIVERS
        ):
            for arg in list(call.args) + [kw.value for kw in call.keywords]:
                if CREDENTIAL in ev.tags(arg):
                    self.findings.append(
                        _finding(
                            "DYN202",
                            self.unit,
                            arg,
                            "credential-grade wire value (API key / bearer "
                            "token) reaches a log call — hash_credential() "
                            "at resolution; raw secrets must never be "
                            "logged",
                            self.lines,
                        )
                    )
        if tail in HUB_KEY_SINK_TAILS and "DYN203" in self.rules and call.args:
            tags = real_tags(ev.tags(call.args[0]))
            if tags:
                self.findings.append(
                    _finding(
                        "DYN203",
                        self.unit,
                        call.args[0],
                        f"wire-controlled value formatted into a hub "
                        f"key/subject (`{tail}`) without a sanitizer — a "
                        "crafted id ('../', spaces) escapes its namespace "
                        "prefix; hash or escape it first "
                        f"(taint: {', '.join(sorted(tags))})",
                        self.lines,
                    )
                )

    def _fstring(self, js: ast.JoinedStr, ev: TaintEvaluator) -> None:
        for fv in label_values(js):
            tags = real_tags(ev.tags(fv.value))
            if tags and "DYN201" in self.rules:
                self.findings.append(
                    _finding(
                        "DYN201",
                        self.unit,
                        fv.value,
                        "wire-controlled value interpolated into a "
                        "Prometheus label position without a sanitizer — "
                        "wrap in escape_label() "
                        f"(taint: {', '.join(sorted(tags))})",
                        self.lines,
                    )
                )
                continue
            if "DYN204" not in self.rules:
                continue
            if fv.format_spec is not None:
                continue  # numeric format specs ({p:.4f}) render numbers
            if _is_label_safe(fv.value, ev):
                continue
            if (self.unit.path, self.unit.qualname) in LABEL_HYGIENE_EXEMPT:
                continue
            self.findings.append(
                _finding(
                    "DYN204",
                    self.unit,
                    fv.value,
                    "f-string Prometheus label interpolation is not "
                    "provably sanitized — escape_label() it HERE at the "
                    "render site (exactly once: upstream helpers must hand "
                    "raw values; registry LABEL_HYGIENE_EXEMPT for the "
                    "rare provably-internal case)",
                    self.lines,
                )
            )


def check_taint(
    graph: CorpusGraph,
    model: TaintModel,
    rules: Set[str],
    lines_of: Dict[str, List[str]],
    scope: Optional[Set[str]] = None,
) -> List[Finding]:
    findings: List[Finding] = []
    for unit in graph.functions:
        if scope is not None and unit.path not in scope:
            continue
        visitor = _SinkVisitor(unit, rules, lines_of[unit.path], findings)
        model.walk_function(unit, symbolic_params=False, visit=visitor)
    return findings
