# dynalint-fixture: expect=DYN501
"""Exception-edge leak: blocks are allocated, then an awaited wire call
sits between acquire and release with no try/finally — a raise mid-wire
leaves the handle held forever."""


class Stager:
    async def stage(self, seq, payload):
        bids = self.pool.allocate_sequence(seq.num_blocks)
        await self.wire.scatter(bids, payload)  # can raise: blocks leak
        self.pool.free_sequence(bids)
