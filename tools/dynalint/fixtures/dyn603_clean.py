# dynalint-fixture: expect=none
"""The sanctioned shape: the clock is injected (referencing
``time.monotonic`` as a default is the idiom — only CALLS are raw), and
RNG is seeded."""

import random
import time


class BrownoutLadder:
    def __init__(self, clock=time.monotonic, seed=0):
        self._clock = clock
        self._rng = random.Random(seed)

    def maybe_step(self):
        now = self._clock()
        if now - self._last_step < self.dwell_s:
            return self._rung
        return self._rung + self._rng.choice((0, 1))
