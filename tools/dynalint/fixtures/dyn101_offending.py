# dynalint-fixture: expect=DYN101
"""Refcount read-modify-write spanning an await: the value captured before
the suspension point is stale by the time the write lands."""


class Registry:
    async def bump(self, slot):
        refs = self._refs[slot]  # read shared state
        await self._apply(slot)  # suspension: peers can run
        self._refs[slot] = refs + 1  # stale write clobbers their update
