# dynalint-fixture: expect=DYN102
"""Manual acquire/release without a finally: an exception in flush leaks
the lock and wedges every waiter."""


class Pump:
    async def drain(self):
        await self._lock.acquire()
        await self._flush()
        self._lock.release()  # skipped when _flush raises
