# dynalint-fixture: expect=none
"""Suppressed: a one-block debug dump to a diagnostics subject — reviewed
as sub-threshold (single block, test-only path, never on the hot path)."""


class Donor:
    async def debug_dump(self, req):
        payload = await self.engine.export_prompt_blocks(req.token_ids, max_blocks=1)
        await self.hub.publish(self.subj, payload)  # dynalint: disable=DYN402
