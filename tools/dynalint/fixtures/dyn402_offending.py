# dynalint-fixture: expect=DYN402
"""Bulk payload shipped over the control plane: the full KV block export
is published through a hub subject, so every byte rides the shard's
publish path and head-of-line-blocks lease renewals and watches on it."""


class Donor:
    async def export(self, req):
        payload = await self.engine.export_prompt_blocks(
            req.token_ids, salt=req.salt
        )
        await self.hub.publish(self.subj, payload)

    async def export_inline(self, req):
        await self.hub.publish(
            self.subj, await self.engine.export_prompt_blocks(req.token_ids)
        )

    async def stash_block(self, key, block):
        await self.hub.kv_put(key, {"k": block.k_bytes, "v": block.v_bytes})
