# dynalint-fixture: expect=none


def shape(body):
    nvext = body.get("nvext")
    if not isinstance(nvext, dict):
        nvext = {}
        body["nvext"] = nvext
    nvext["spec_decode"] = False
    return body
