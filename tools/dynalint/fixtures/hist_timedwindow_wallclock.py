# dynalint-fixture: expect=DYN603
"""PR 8 review finding, minimized: TimedWindow stamped samples with
``time.time()``.  An NTP step made the rate window jump backwards, the
brownout ladder reading it oscillated, and no test could reproduce the
incident.  The fix injects ``clock=time.monotonic`` and lets the sim
drive a fake clock."""


class TimedWindow:
    def observe(self, value):
        self._samples.append((time.time(), value))  # NTP step skews the window
        self._evict(time.time() - self.window_s)
