# dynalint-fixture: expect=none


class WorkerMetrics:
    def render(self, lines, escape_label):
        for wid, m in self._metrics.items():
            lines.append(
                f'worker_active_slots{{worker_id="{escape_label(wid)}"}} {m}'
            )
