# dynalint-fixture: expect=none
from dataclasses import dataclass
from typing import Optional


@dataclass
class WireMsg:
    kind: str
    payload: dict
    trace_id: Optional[str] = None

    def to_dict(self):
        out = {"kind": self.kind, "payload": self.payload}
        if self.trace_id is not None:
            out["trace_id"] = self.trace_id
        return out

    @classmethod
    def from_dict(cls, d):
        return cls(
            kind=d["kind"],
            payload=dict(d.get("payload") or {}),
            trace_id=d.get("trace_id"),
        )
