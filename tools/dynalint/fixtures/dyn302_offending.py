# dynalint-fixture: expect=DYN302
"""The class adopted omit-when-absent (grammar is conditional) but ships
the newer optional field unconditionally — old consumers now see a key
they predate."""
from dataclasses import dataclass
from typing import Optional


@dataclass
class WireReq:
    token_ids: list
    grammar: Optional[dict] = None
    priority: Optional[str] = None

    def to_dict(self):
        out = {"token_ids": self.token_ids, "priority": self.priority}
        if self.grammar is not None:
            out["grammar"] = self.grammar
        return out

    @classmethod
    def from_dict(cls, d):
        return cls(
            token_ids=list(d["token_ids"]),
            grammar=d.get("grammar"),
            priority=d.get("priority"),
        )
