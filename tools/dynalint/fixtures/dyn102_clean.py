# dynalint-fixture: expect=none
"""Exception-safe shapes: finally-release and async with."""


class Pump:
    async def drain(self):
        await self._lock.acquire()
        try:
            await self._flush()
        finally:
            self._lock.release()

    async def drain_ctx(self):
        async with self._lock:
            await self._flush()
