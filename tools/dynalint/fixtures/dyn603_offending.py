# dynalint-fixture: expect=DYN603
"""Raw wall clock inside a registered deterministic core: the brownout
ladder's rung decisions become a function of real time, so sim/replay and
tests can never reproduce a traffic incident exactly."""


class BrownoutLadder:
    def maybe_step(self):
        now = time.monotonic()  # raw clock: replay diverges
        if now - self._last_step < self.dwell_s:
            return self._rung
        return self._rung + 1
