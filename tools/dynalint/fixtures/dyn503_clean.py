# dynalint-fixture: expect=none
"""The sanctioned split: dispatch under the lock, disk I/O after it."""

import os


class Engine:
    async def offload(self, batch, fd):
        async with self._device_lock:
            out = self._step_fn(batch)
        os.fsync(fd)  # outside the lock: decode keeps dispatching
        return out
