# dynalint-fixture: expect=DYN203
"""Wire-controlled name formatted into a hub key: 'a/b' escapes the
store's prefix."""


async def register(hub, body):
    name = body.get("metadata").get("name")
    await hub.kv_put("deployments/" + name, body)
