# dynalint-fixture: expect=none
"""Clean: the block payload rides the bulk data plane (transports/bulk.py);
the hub carries only the rendezvous descriptor and a completion marker."""


class Donor:
    async def export(self, req):
        blob = await self.engine.export_prompt_blocks(req.token_ids)
        prep = await self.rendezvous.prepare(req.worker_id, budget=len(blob))
        await bulk_push(prep[0], "kv_export", prep[1], blob)
        await self.hub.publish(self.subj, {"done": req.request_id})
