# dynalint-fixture: expect=DYN101
"""PR 8 review finding, minimized: WfqQueue.remove() advanced the queue's
virtual time from a cancelled entry's far-future finish stamp.  In the
synchronous scheduler the review caught it by hand; transplanted into the
async hub-coordinated drain, the same idiom is a stale-fairness-state
write the moment a publish sits between read and write."""


class WfqDrain:
    async def remove(self, seq):
        vt = self._vt  # read the fairness clock
        await self._hub.publish("cancel", seq.request_id)
        # Stale: admissions during the publish already advanced _vt; this
        # write rolls the clock back (or jumps it past the backlog).
        self._vt = max(vt, seq.vft)
