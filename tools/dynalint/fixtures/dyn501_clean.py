# dynalint-fixture: expect=none
"""The sanctioned shape: every risky point between acquire and release is
covered by a ``finally`` that frees the handle."""


class Stager:
    async def stage(self, seq, payload):
        bids = self.pool.allocate_sequence(seq.num_blocks)
        try:
            await self.wire.scatter(bids, payload)
        finally:
            self.pool.free_sequence(bids)
