# dynalint-fixture: expect=DYN503
"""Blocking host I/O under the device lock: every decode dispatch queues
behind the disk write (the PR 11 lock-split class)."""

import os


class Engine:
    async def offload(self, batch, fd):
        async with self._device_lock:
            out = self._step_fn(batch)
            os.fsync(fd)  # disk latency serializes the decode plane
        return out
