# dynalint-fixture: expect=none
"""Suppressed: an offline benchmark entry point that runs before the
serving loop exists — single task, no concurrent dispatch possible."""


class Bench:
    async def bench_once(self, batch):
        # offline: the serving loop (and its peers) never started
        return self._step_fn(batch)  # dynalint: disable=DYN502
