# dynalint-fixture: expect=none
from typing import NamedTuple


class SamplingParams(NamedTuple):
    seeds: object
    steps: object
    temperature: object
    top_k: object
    top_p: object
    freq_penalty: object
    pres_penalty: object
    counts: object
    need_logprobs: object
    mask_words: object = None  # appended, defaulted: treedef-stable
