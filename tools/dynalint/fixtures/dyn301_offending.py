# dynalint-fixture: expect=DYN301
"""A wire dataclass whose newest field never makes it into to_dict: it
silently stops traveling."""
from dataclasses import dataclass
from typing import Optional


@dataclass
class WireMsg:
    kind: str
    payload: dict
    trace_id: Optional[str] = None

    def to_dict(self):
        return {"kind": self.kind, "payload": self.payload}

    @classmethod
    def from_dict(cls, d):
        return cls(kind=d["kind"], payload=dict(d.get("payload") or {}))
