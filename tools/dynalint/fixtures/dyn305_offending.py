# dynalint-fixture: expect=DYN305
"""setdefault on a nullable wire key: a client-sent '"nvext": null'
satisfies it and the rewrite is silently skipped."""


def shape(body):
    body.setdefault("nvext", {})["spec_decode"] = False
    return body
