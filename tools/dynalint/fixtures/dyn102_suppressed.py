# dynalint-fixture: expect=none


class Pump:
    async def drain(self):
        await self._lock.acquire()
        await self._flush()  # reviewed: flush cannot raise
        self._lock.release()  # dynalint: disable=DYN102
