# dynalint-fixture: expect=none
from dataclasses import dataclass
from typing import Optional


@dataclass
class WireReq:
    token_ids: list
    grammar: Optional[dict] = None
    priority: Optional[str] = None

    def to_dict(self):
        out = {"token_ids": self.token_ids}
        if self.grammar is not None:
            out["grammar"] = self.grammar
        if self.priority is not None:
            out["priority"] = self.priority
        return out

    @classmethod
    def from_dict(cls, d):
        return cls(
            token_ids=list(d["token_ids"]),
            grammar=d.get("grammar"),
            priority=d.get("priority"),
        )
