# dynalint-fixture: expect=DYN306
"""A field inserted into SamplingParams' frozen prefix: every cached jit
program recompiles and wire'd tuples unpack shifted."""
from typing import NamedTuple


class SamplingParams(NamedTuple):
    seeds: object
    steps: object
    mask_words: object  # inserted mid-prefix — breaks treedef stability
    temperature: object
    top_k: object
    top_p: object
    freq_penalty: object
    pres_penalty: object
    counts: object
    need_logprobs: object
