# dynalint-fixture: expect=DYN304
"""PR 6 review finding, minimized: SequenceState grew tenancy fields
(grammar/adapter here: a hypothetical reasoning_budget) without a
SequenceSnapshot counterpart or an explicit exemption — a migrated
sequence silently resumed without the state and the spliced stream
diverged.  The field lists mirror the real classes so only the GAP field
trips the registry."""
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class SequenceState:
    request_id: str
    prompt: List[int]
    block_seq: Any
    sampling_temperature: float = 0.0
    sampling_top_k: int = 0
    sampling_top_p: float = 1.0
    sampling_seed: int = 0
    freq_penalty: float = 0.0
    pres_penalty: float = 0.0
    logprobs: Optional[int] = None
    max_new_tokens: Optional[int] = None
    min_new_tokens: Optional[int] = None
    stop_token_ids: frozenset = frozenset()
    ignore_eos: bool = False
    output: List[int] = field(default_factory=list)
    pin_ids: Optional[List[int]] = None
    awaiting_fetch: bool = False
    frozen: bool = False
    orig_prompt_len: int = 0
    block_ids: List[int] = field(default_factory=list)
    num_computed: int = 0
    num_cached_prompt: int = 0
    finished: bool = False
    num_sealed_blocks: int = 0
    enqueue_t: float = 0.0
    spec_enabled: bool = True
    spec_k: int = -1
    spec_ewma: float = 1.0
    spec_bench_until: int = -1
    spec_next_try: int = 0
    spec_miss: int = 0
    kv_salt: Optional[str] = None
    adapter: Optional[str] = None
    adapter_slot: int = -1
    adapter_released: bool = False
    grammar: Any = None
    grammar_state: int = 0
    tenant: str = ""
    priority: str = "interactive"
    # THE GAP: consumed by the sampler, absent from the snapshot AND from
    # both registry tables — the PR 6 bug shape.
    reasoning_budget: int = 0


@dataclass
class SequenceSnapshot:
    request_id: str
    token_ids: List[int]
    orig_prompt_len: int
    sampling: Dict[str, Any] = field(default_factory=dict)
    stop: Dict[str, Any] = field(default_factory=dict)
    spec: Dict[str, Any] = field(default_factory=dict)
    deadline_s: Optional[float] = None
    detok: Optional[Dict[str, Any]] = None
    adapter: Optional[str] = None
    kv_salt: Optional[str] = None
    tenant: Optional[str] = None
    priority: Optional[str] = None
    grammar: Optional[Dict[str, Any]] = None
    version: int = 1
