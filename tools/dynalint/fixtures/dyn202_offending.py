# dynalint-fixture: expect=DYN202
"""Credential-grade wire value (API key) reaching a log line."""


def admit(headers, logger):
    key = headers.get("x-api-key")
    logger.warning(f"quota exceeded for {key}")
