# dynalint-fixture: expect=DYN101
"""PR 6/8 idiom, minimized: AdapterRegistry promotion decided by a
pre-await residency check.  The real registry holds _claim_lock across the
span — remove the lock (as the first draft did) and two concurrent
acquires double-promote into the same slot."""


class AdapterSlots:
    async def ensure_resident(self, name):
        if self._slot_of.get(name) is None:  # decision from pre-await state
            await self._promote(name)  # suspension: a peer can promote too
            self._slot_of[name] = self._pick_slot()  # double-claim
        return self._slot_of[name]
