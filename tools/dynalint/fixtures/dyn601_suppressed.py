# dynalint-fixture: expect=none
"""Suppressed: the weak-typed scalar is deliberate — it must promote to
whatever dtype the cache arrays carry at the update site."""


def write_kv_ragged(kv, new_kv, slots):
    # weak type on purpose: promotes to kv's dtype at the scatter
    pad = jnp.zeros((8,))  # dynalint: disable=DYN601
    return kv, pad
