# dynalint-fixture: expect=none
"""The sanctioned shape: lengths are padded to power-of-two buckets
before they reach the traced signature."""


class Engine:
    async def step(self, batch, tokens):
        async with self._device_lock:
            return self._step_fn(batch, 1 << (len(tokens) - 1).bit_length())
