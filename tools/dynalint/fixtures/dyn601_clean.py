# dynalint-fixture: expect=none
"""The sanctioned shape: every constructor on the hot path pins its
dtype, so the traced signature is flag-independent."""


def ragged_decode_attention(q, kv_pages, lens):
    mask_val = jnp.full((1, 1), -1e9, dtype=jnp.float32)
    ids = jnp.arange(lens.shape[0], dtype=jnp.int32)
    return q, mask_val, ids
