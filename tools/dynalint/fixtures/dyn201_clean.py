# dynalint-fixture: expect=none
"""Sanitized at the sink: escape_label for wire strings, hash_credential
for secrets."""


def render_sheds(body, headers, lines, escape_label, hash_credential):
    tenant = body.get("tenant")
    lines.append(f'qos_shed_by_tenant_total{{tenant="{escape_label(tenant)}"}} 1')
    key = hash_credential(headers.get("x-api-key") or "")
    lines.append(f'qos_keys_total{{key="{key}"}} 1')
