# dynalint-fixture: expect=DYN201
"""Wire-controlled tenant id interpolated into a Prometheus label."""


def render_sheds(body, lines):
    tenant = body.get("tenant")
    lines.append(f'qos_shed_by_tenant_total{{tenant="{tenant}"}} 1')
