# dynalint-fixture: expect=DYN204
"""Label value of unprovable provenance: the dataflow cannot see through
the dict, so hygiene demands the escape anyway."""


class WorkerMetrics:
    def render(self, lines):
        for wid, m in self._metrics.items():
            lines.append(f'worker_active_slots{{worker_id="{wid}"}} {m}')
