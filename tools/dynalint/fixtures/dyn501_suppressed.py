# dynalint-fixture: expect=none
"""Suppressed: the reviewed claim is that this wire call cannot raise
after the handshake completes, so the bare span is safe."""


class Stager:
    async def stage(self, seq, payload):
        bids = self.pool.allocate_sequence(seq.num_blocks)
        # post-handshake scatter is infallible per the wire contract
        await self.wire.scatter(bids, payload)  # dynalint: disable=DYN501
        self.pool.free_sequence(bids)
