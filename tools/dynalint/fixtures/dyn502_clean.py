# dynalint-fixture: expect=none
"""The sanctioned shape: dispatch runs under ``_device_lock`` — including
through the ``asyncio.to_thread`` indirection."""

import asyncio


class Engine:
    async def step(self, batch):
        async with self._device_lock:
            return await asyncio.to_thread(self._step_fn, batch)
