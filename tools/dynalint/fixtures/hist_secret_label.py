# dynalint-fixture: expect=DYN201
"""PR 8 review finding, minimized: the tenant resolver returned the RAW
API key on the credential path, and the QoS metrics rendered tenant ids
as labels — a secret one hop from /metrics.  The interprocedural summary
carries the credential taint through the resolver into the sink."""


def resolve_tenant_id(headers, body):
    key = headers.get("x-api-key")
    if key:
        return key  # the bug: raw credential becomes the tenant id
    return body.get("model") or "anonymous"


def render(headers, body, lines):
    tenant = resolve_tenant_id(headers, body)
    lines.append(f'qos_shed_by_tenant_total{{tenant="{tenant}"}} 1')
