# dynalint-fixture: expect=none


async def register(hub, body, safe_key_component):
    name = safe_key_component(body.get("metadata").get("name"))
    await hub.kv_put("deployments/" + name, body)
