# dynalint-fixture: expect=DYN501
"""PR 9 review finding, minimized: the health prober opened a mux stream
per probe and released it after the ping round-trip.  A dead worker made
the ping raise, the release never ran, and the per-connection stream-id
pool drained until every subsequent probe failed with "no free stream" —
the prober marked healthy workers dead."""


class HealthProbe:
    async def probe_once(self, worker):
        sid = self.mux.open_stream(worker.addr)
        rtt = await self.mux.ping(sid, timeout=self.timeout_s)  # dead peer raises
        self.mux.release(sid)
        return rtt
