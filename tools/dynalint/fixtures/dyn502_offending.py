# dynalint-fixture: expect=DYN502
"""Device dispatch outside the device lock: a concurrent dispatch can
reuse the donated buffers of this one mid-flight."""


class Engine:
    async def step(self, batch):
        return self._step_fn(batch)  # no _device_lock held
