# dynalint-fixture: expect=none
"""Suppressed: an operator-facing report stamp that never feeds a
decision — reviewed as harmless wall-clock use."""


class DecisionEngine:
    def snapshot_id(self):
        # report watermark only; no decision reads it
        return time.time_ns()  # dynalint: disable=DYN603
