# dynalint-fixture: expect=none
"""Suppressed: the owner guarantees single-task access (reviewed claim)."""


class Guard:
    async def swap(self, slot):
        refs = self._refs[slot]
        await self._apply(slot)
        # task-confined object: no peer can interleave here
        self._refs[slot] = refs + 1  # dynalint: disable=DYN101
