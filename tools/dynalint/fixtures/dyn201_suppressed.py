# dynalint-fixture: expect=none


def render_sheds(body, lines):
    tenant = body.get("tenant")
    # reviewed: tenant already validated against a closed allowlist
    lines.append(f'qos_shed_total{{tenant="{tenant}"}} 1')  # dynalint: disable=DYN201,DYN204
