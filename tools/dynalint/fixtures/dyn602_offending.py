# dynalint-fixture: expect=DYN602
"""Per-request ``len()`` fed straight into a traced dispatch: every new
length keys a fresh executable — compile storms under real traffic."""


class Engine:
    async def step(self, batch, tokens):
        async with self._device_lock:
            return self._step_fn(batch, len(tokens))  # unbucketed length
