# dynalint-fixture: expect=none


def admit(headers, logger, hash_credential):
    key = hash_credential(headers.get("x-api-key") or "")
    logger.warning(f"quota exceeded for {key}")
