# dynalint-fixture: expect=DYN305
"""PR 8 review finding, minimized: the brownout rung-2 spec stand-down
used setdefault, so a request carrying an explicit '"nvext": null' kept
its speculative drafts during overload — and a batch row could launder
into the protected class the same way on the priority-threading path."""


def apply_rung(body, rung):
    if rung >= 2:
        body.setdefault("nvext", {})["spec_decode"] = False
    return body
