# dynalint-fixture: expect=DYN601
"""Dtype-ambiguous constructor on a registered hot path: the result dtype
follows jax's weak-type/x64 defaults, so the jit cache key (and kernel
numerics) silently depend on process-global flags."""


def ragged_decode_attention(q, kv_pages, lens):
    mask_val = jnp.full((1, 1), -1e9)  # dtype depends on the x64 flag
    return q, mask_val
