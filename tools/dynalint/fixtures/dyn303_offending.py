# dynalint-fixture: expect=DYN303
"""from_dict KeyErrors on old-wire dicts: the defaulted field must be
read with .get()."""
from dataclasses import dataclass
from typing import Optional


@dataclass
class WireStop:
    max_tokens: Optional[int] = None

    def to_dict(self):
        return {"max_tokens": self.max_tokens}

    @classmethod
    def from_dict(cls, d):
        return cls(max_tokens=d["max_tokens"])
