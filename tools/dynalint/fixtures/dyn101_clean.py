# dynalint-fixture: expect=none
"""The three sanctioned shapes: a lock covering the span, a re-check after
the await, and the stop()-teardown None-clear."""


class Registry:
    async def bump_locked(self, slot):
        async with self._claim_lock:
            refs = self._refs[slot]
            await self._apply(slot)
            self._refs[slot] = refs + 1  # lock held across the span

    async def lazy_init(self):
        if self._server is None:
            server = await self._start()
            if self._server is None:  # re-check after the await
                self._server = server
        return self._server

    async def stop(self):
        if self._task is not None:
            self._task.cancel()
            await self._gather(self._task)
            self._task = None  # teardown clear: derives from nothing stale
