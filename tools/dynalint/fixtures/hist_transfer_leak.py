# dynalint-fixture: expect=DYN501
"""PR 4/5 review finding, minimized: the KV transfer receive path
allocated destination blocks, then awaited the chunked wire scatter.  A
peer death mid-scatter raised out of the loop with the blocks still
allocated — pinned forever, shrinking the pool until the worker starved.
The fix wrapped the scatter span in ``except BaseException: free; raise``."""


class KvReceiver:
    async def inject_blocks(self, seq, chunks):
        bids = self.pool.allocate_sequence(seq.num_blocks)
        for payload in chunks:
            await self.wire.scatter(bids, payload)  # dies with the peer
        self.pool.free_sequence(bids)
        return True
