# dynalint-fixture: expect=none
from dataclasses import dataclass
from typing import Optional


@dataclass
class WireMsg:
    kind: str
    # in-memory handle, never serialized (reviewed)
    trace_id: Optional[str] = None  # dynalint: disable=DYN301

    def to_dict(self):
        return {"kind": self.kind}

    @classmethod
    def from_dict(cls, d):
        return cls(kind=d["kind"])
