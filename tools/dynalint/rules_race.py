"""DYN1xx — async-race rules.

The scheduler, hub and adapter registry are single-threaded but NOT
single-flow: every ``await`` is a scheduling point where another task can
observe and mutate the same object.  Rust's ``&mut`` makes this class of
bug unrepresentable in the reference Dynamo; here the linter encodes the
two shapes that actually bite:

- **DYN101** — a read-modify-write of ``self.<attr>`` / a declared global
  that *spans* an await without a shared lock: the value (or branch
  decision) captured before the await is stale by the time the write
  lands.  The WfqQueue virtual-time and AdapterRegistry refcount idioms
  are the motivating sites — both are only correct because nothing awaits
  between read and write (WfqQueue) or because a claim lock covers the
  span (AdapterRegistry).
- **DYN102** — ``lock.acquire()`` in async code whose ``release()`` is not
  in a ``finally``: an exception (or an early return added later) between
  them leaks the lock and every other task wedges.  ``async with`` makes
  the hazard unrepresentable; the rule only fires when both calls are in
  the same function, so cross-function acquire/release protocols (the
  admission controller) stay out of scope.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import CorpusGraph, FunctionUnit, linearize
from .core import Finding, call_target, dotted_name, make_finding
from .registry import LOCKISH

RACE_RULES = ("DYN101", "DYN102")


def _finding(
    rule: str, unit: FunctionUnit, node: ast.AST, message: str, lines: List[str]
) -> Finding:
    return make_finding(rule, unit.path, unit.qualname, node, message, lines)


# ---------------------------------------------------------------------------
# DYN101
# ---------------------------------------------------------------------------


def _check_dyn101(unit: FunctionUnit, lines: List[str]) -> List[Finding]:
    events = linearize(unit.node)
    if not any(e.kind == "await" for e in events):
        return []
    findings: List[Finding] = []
    await_indices = [e.index for e in events if e.kind == "await"]
    # last read index per key, and local provenance:
    # local -> (origin state keys, assign index)
    reads: Dict[str, List[Tuple[int, frozenset]]] = {}
    provenance: Dict[str, Tuple[Set[str], int]] = {}
    flagged: Set[Tuple[str, int]] = set()

    def awaits_between(a: int, b: int) -> bool:
        return any(a < j < b for j in await_indices)

    for e in events:
        if e.kind == "read" and e.key:
            reads.setdefault(e.key, []).append((e.index, e.locks))
        elif e.kind == "assign" and e.key:
            origins: Set[str] = set()
            for r in e.value_reads:
                if "." in r or r.isupper():
                    origins.add(r)
                prev = provenance.get(r)
                if prev is not None:
                    origins |= prev[0]
            # state keys read directly by the RHS
            origins |= {r for r in e.value_reads if r.startswith("self.")}
            provenance[e.key] = (origins, e.index)
        elif e.kind == "write" and e.key:
            key = e.key
            stale_at: Optional[int] = None
            why = ""
            # (a) value provenance: a local derived from `key` assigned
            #     before an await that precedes this write
            for r in e.value_reads:
                prev = provenance.get(r)
                if (
                    prev is not None
                    and key in prev[0]
                    and awaits_between(prev[1], e.index)
                ):
                    stale_at, why = prev[1], f"via local `{r}`"
                    break
            # (b) guard provenance: the write sits under an if/while that
            #     tested `key` before an await.  Writing the CONSTANT None
            #     is exempt — `if self._task: …cancel(); await …;
            #     self._task = None` is the project's stop() teardown idiom
            #     (DYN003's stop-pattern sibling): the cleared value derives
            #     from nothing stale.  Claims/sets of real values
            #     (refcounts, lazy-created handles) stay flagged.
            if stale_at is None and not (
                isinstance(e.node, ast.Assign)
                and isinstance(e.node.value, ast.Constant)
                and e.node.value.value is None
            ):
                # A guard on the same key with NO await between it and the
                # write is a RE-CHECK — the fix idiom the finding itself
                # recommends ("re-read after the await") — and clears the
                # hazard for this write.
                recheck = any(
                    key in gk and not awaits_between(gi, e.index)
                    for gk, gi in e.guards
                )
                if not recheck:
                    for guard_keys, guard_idx in e.guards:
                        if key in guard_keys and awaits_between(
                            guard_idx, e.index
                        ):
                            stale_at, why = guard_idx, "via the guarding test"
                            break
            if stale_at is None:
                continue
            # shared lock covering both the stale read and the write?
            read_locks = frozenset()
            for idx, locks in reads.get(key, []):
                if idx <= stale_at:
                    read_locks = locks
            if read_locks & e.locks:
                continue
            dedupe = (key, getattr(e.node, "lineno", 0))
            if dedupe in flagged:
                continue
            flagged.add(dedupe)
            findings.append(
                _finding(
                    "DYN101",
                    unit,
                    e.node,
                    f"read-modify-write of `{key}` spans an await "
                    f"({why}): another task can mutate it at the "
                    "suspension point and this write clobbers the update "
                    "(TOCTOU) — hold one asyncio.Lock across the span or "
                    "re-read after the await",
                    lines,
                )
            )
    return findings


# ---------------------------------------------------------------------------
# DYN102
# ---------------------------------------------------------------------------


def _lock_name(call: ast.Call) -> Optional[str]:
    if not isinstance(call.func, ast.Attribute):
        return None
    base = dotted_name(call.func.value)
    if base and any(tok in base.lower() for tok in LOCKISH):
        return base
    return None


def _check_dyn102(unit: FunctionUnit, lines: List[str]) -> List[Finding]:
    acquires: Dict[str, ast.Call] = {}
    releases: List[Tuple[str, ast.Call, bool]] = []  # (name, node, in_finally)

    def walk(node: ast.AST, in_finally: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(node, ast.Try):
            for s in node.body:
                walk(s, in_finally)
            for h in node.handlers:
                for s in h.body:
                    walk(s, in_finally)
            for s in node.orelse:
                walk(s, in_finally)
            for s in node.finalbody:
                walk(s, True)
            return
        if isinstance(node, ast.Call):
            _, tail = call_target(node)
            name = _lock_name(node)
            if name is not None:
                if tail == "acquire":
                    acquires.setdefault(name, node)
                elif tail == "release":
                    releases.append((name, node, in_finally))
        for child in ast.iter_child_nodes(node):
            walk(child, in_finally)

    for stmt in unit.node.body:
        walk(stmt, False)

    findings: List[Finding] = []
    for name, rel, in_finally in releases:
        if name in acquires and not in_finally:
            findings.append(
                _finding(
                    "DYN102",
                    unit,
                    rel,
                    f"`{name}.release()` is not in a `finally`: any "
                    "exception (or a later early return) between acquire "
                    "and release leaks the lock and wedges every waiter — "
                    f"use `async with {name}` or move the release into "
                    "a finally block",
                    lines,
                )
            )
    return findings


# ---------------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------------


def check_race(
    graph: CorpusGraph,
    rules: Set[str],
    lines_of: Dict[str, List[str]],
    scope: Optional[Set[str]] = None,
) -> List[Finding]:
    findings: List[Finding] = []
    for unit in graph.functions:
        if not unit.is_async:
            continue
        if scope is not None and unit.path not in scope:
            continue
        lines = lines_of[unit.path]
        if "DYN101" in rules:
            findings.extend(_check_dyn101(unit, lines))
        if "DYN102" in rules:
            findings.extend(_check_dyn102(unit, lines))
    return findings
