"""Fault-point sweep: run every injectable fault against the real stack and
emit the failure matrix (fault point × observed behaviour × status code).

Usage:
    JAX_PLATFORMS=cpu python tools/fault_matrix.py [--json OUT.json] [--md OUT.md]
        [--engine]   # include the kv_pressure sweep (builds a real engine)

Each row is produced by actually arming the fault (runtime/faultinject.py)
against a live HubServer + ServiceServer worker set or an HttpService edge —
the same machinery tests/test_resilience.py asserts on — so the tables in
docs/resilience.md and docs/chaos.md are generated evidence, not prose.

The JSON artifact carries ``fault_kinds`` (every point swept) and is
consumable by ``benchmarks/goodput.py --fault-matrix``: the chaos ladder
cross-checks that each fault kind its rungs inject has a swept row, so a
new fault point cannot silently enter the ladder unevidenced.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from dynamo_tpu.runtime import (  # noqa: E402
    Client,
    Context,
    DistributedRuntime,
    HubServer,
    RemoteEngineError,
    RetryPolicy,
    collect,
    faults,
)
from dynamo_tpu.runtime.resilience import (  # noqa: E402
    BreakerState,
    Deadline,
    DeadlineExceededError,
    metrics as resilience_metrics,
)


async def _serve_echo(runtime, n_items=3):
    async def echo(request: Context):
        for i in range(n_items):
            yield {"i": i, "worker": runtime.worker_id}

    ep = runtime.namespace("sweep").component("worker").endpoint("generate")
    await ep.serve_endpoint(echo)
    return ep


async def _client(rt):
    ep = rt.namespace("sweep").component("worker").endpoint("generate")
    c = Client(
        rt.hub,
        ep.instance_prefix,
        retry_policy=RetryPolicy(max_attempts=4, base_delay_s=0.01),
        breaker_reset_s=0.3,
    )
    await c.start()
    await c.wait_for_instances(5)
    return c


async def sweep_runtime() -> list:
    """Runtime-plane faults through the routed Client (3 workers)."""
    rows = []
    hub = await HubServer().start()
    workers = [await DistributedRuntime.connect(hub.address) for _ in range(3)]
    crt = await DistributedRuntime.connect(hub.address)
    try:
        for w in workers:
            await _serve_echo(w)
        client = await _client(crt)
        while len(client.instance_ids) < 3:
            await asyncio.sleep(0.02)
        dead_addr = (await workers[0].service_server()).address

        # connect_error → transparent failover, breaker opens
        faults.arm("connect_error", match=dead_addr)
        ok = 0
        for _ in range(20):
            items = await collect(await client.generate(Context({})))
            ok += len(items) == 3
        breaker = client._breakers[dead_addr].state
        faults.reset()
        rows.append({
            "fault": "connect_error",
            "injected_at": "MuxConnection dial (client → worker TCP)",
            "observed": f"{ok}/20 requests completed via failover; "
                        f"dead worker breaker={breaker.value}",
            "status": "200 (transparent)",
        })

        # error_prologue → failover before first token
        faults.arm("error_prologue", count=1)
        items = await collect(await client.generate(Context({})))
        faults.reset()
        rows.append({
            "fault": "error_prologue",
            "injected_at": "ServiceServer stream setup (prologue ok=false)",
            "observed": f"failed over before first token; "
                        f"{len(items)} items delivered",
            "status": "200 (transparent)",
        })

        # drop_mid_stream → clean error, NO retry (not idempotent)
        faults.arm("drop_mid_stream", count=1)
        got, err = 0, None
        try:
            async for _ in await client.generate(Context({})):
                got += 1
        except RemoteEngineError as e:
            err = type(e).__name__
        faults.reset()
        rows.append({
            "fault": "drop_mid_stream",
            "injected_at": "ServiceServer (transport aborted after an item)",
            "observed": f"{got} tokens delivered, then {err}; no replay "
                        "(post-first-token is not idempotent)",
            "status": "stream error (5xx at edge)",
        })

        # delay + deadline → DeadlineExceeded (504 at edge)
        faults.arm("delay", delay_s=1.0)
        ctx = Context({})
        ctx.ctx.deadline = Deadline.after(0.15)
        try:
            await collect(await client.generate(ctx))
            observed = "UNEXPECTED success"
        except DeadlineExceededError:
            observed = "DeadlineExceededError within budget"
        faults.reset()
        rows.append({
            "fault": "delay (worker stall)",
            "injected_at": "ServiceServer before prologue",
            "observed": observed,
            "status": "504",
        })

        # watch_error → watch restarts, instance set resyncs
        before = resilience_metrics.watch_restarts_total
        faults.arm("watch_error", count=1)
        extra = await DistributedRuntime.connect(hub.address)
        await _serve_echo(extra)
        deadline = asyncio.get_event_loop().time() + 5.0
        while (
            resilience_metrics.watch_restarts_total <= before
            or len(client.instance_ids) < 4
        ) and asyncio.get_event_loop().time() < deadline:
            await asyncio.sleep(0.05)
        faults.reset()
        recovered = len(client.instance_ids) >= 4
        rows.append({
            "fault": "watch_error",
            "injected_at": "hub Watcher stream (client discovery)",
            "observed": "watch re-established + instance set resynced"
                        if recovered else "NOT RECOVERED",
            "status": "none (self-healing)",
        })
        await extra.close()
        # let the extra worker's delete event drain before partitioning
        deadline = asyncio.get_event_loop().time() + 15.0
        while (
            len(client.instance_ids) != 3
            and asyncio.get_event_loop().time() < deadline
        ):
            await asyncio.sleep(0.05)

        # watch_stall → hub partition: stale view, lease expiry still bounds it
        faults.arm("watch_stall")
        stale_view = len(client.instance_ids)
        partition_rt = await DistributedRuntime.connect(hub.address)
        await _serve_echo(partition_rt)
        await asyncio.sleep(0.3)
        unseen = len(client.instance_ids) == stale_view
        faults.reset()
        rows.append({
            "fault": "watch_stall (hub partition)",
            "injected_at": "HubState watcher fanout",
            "observed": ("new instance invisible during partition; "
                         "requests keep flowing to known-live workers"
                         if unseen else "UNEXPECTED: delta leaked"),
            "status": "200 on live workers",
        })
        await partition_rt.close()

        await client.close()
    finally:
        faults.reset()
        for rt in (*workers, crt):
            await rt.close()
        await hub.close()
    return rows


async def sweep_chaos() -> list:
    """Chaos-ladder fault kinds (ISSUE 7): worker_crash / slow_stream /
    hub_outage against a fresh echo fleet, plus the hub restart path."""
    import time as _time

    from dynamo_tpu.runtime.health import probe_address, worker_latency
    from dynamo_tpu.runtime.resilience import metrics as res

    rows = []
    hub = await HubServer().start()
    workers = [await DistributedRuntime.connect(hub.address) for _ in range(3)]
    crt = await DistributedRuntime.connect(hub.address)
    try:
        for w in workers:
            await _serve_echo(w)
        client = await _client(crt)
        while len(client.instance_ids) < 3:
            await asyncio.sleep(0.02)

        # worker_crash → transport aborted + listener closed; traffic
        # reroutes; the health probe sees the corpse.
        target = await workers[0].service_server()
        dead_addr = target.address
        faults.arm("worker_crash", match=dead_addr, count=1)
        ok = 0
        for _ in range(12):
            try:
                items = await collect(await client.generate(Context({})))
                ok += len(items) == 3
            except RemoteEngineError:
                pass  # the stream that triggered the crash dies mid-flight
        alive = await probe_address(dead_addr, 0.5)
        faults.reset()
        rows.append({
            "fault": "worker_crash",
            "injected_at": "ServiceServer dispatch (aborts every connection, "
                           "stops accepting)",
            "observed": f"{ok}/12 requests completed around the corpse; "
                        f"health probe now {'UNEXPECTEDLY alive' if alive else 'dead'}",
            "status": "200 on survivors",
        })

        # slow_stream → straggler: items delayed, stream completes, and the
        # client-side latency tracker flags the outlier ITL.
        straggler = (await workers[1].service_server()).address
        worker_latency.reset()
        faults.arm("slow_stream", match=straggler, delay_s=0.08)
        t0 = _time.perf_counter()
        for _ in range(6):
            await collect(await client.generate(Context({})))
        elapsed = _time.perf_counter() - t0
        lat = worker_latency.snapshot()
        outlier = max(
            (row.get("itl_p50_ms") or 0.0 for row in lat.values()),
            default=0.0,
        )
        faults.reset()
        rows.append({
            "fault": "slow_stream",
            "injected_at": "ServiceServer response loop (per-item stall)",
            "observed": f"6/6 streams completed in {elapsed:.2f}s; worst "
                        f"per-worker ITL p50 {outlier:.0f}ms (watchdog "
                        "straggler-scan input)",
            "status": "200 (degraded latency)",
        })

        # hub_outage (armed flavour) → connections dropped; reconnect with
        # backoff; KV ops park then succeed once the outage clears.
        before = res.hub_reconnects_total
        faults.arm("hub_outage")
        await asyncio.sleep(0.3)
        put = asyncio.ensure_future(crt.hub.kv_put("sweep/outage", 1))
        await asyncio.sleep(0.4)
        faults.disarm("hub_outage")
        try:
            await asyncio.wait_for(put, 10.0)
            survived = (await crt.hub.kv_get("sweep/outage")) == 1
        except Exception:  # noqa: BLE001 — observation, not assertion
            put.cancel()
            survived = False
        rows.append({
            "fault": "hub_outage",
            "injected_at": "HubServer connection plane (accept+drop while "
                           "armed)",
            "observed": ("kv_put parked through the outage and landed after; "
                         if survived else "kv_put DID NOT survive; ")
                        + f"{res.hub_reconnects_total - before} reconnect(s)",
            "status": "paused, then 200",
        })
    finally:
        faults.reset()
        for rt in (*workers, crt):
            await rt.close()
        await hub.close()
    return rows


async def sweep_shards() -> list:
    """``hub_shard_kill`` against a real 2-shard hub (ISSUE 16 / ladder L8):
    a warm ``HubStandby`` follows the shard that owns the ``instances``
    routing token (the same victim the L8 rung picks), the victim's primary
    is actually closed mid-put, and the standby promotes onto the same
    address.  Bars: the owner-shard put parks and lands, the sibling shard
    never blips, the lease floor carries across the handoff, and the
    composite lease breaks (the owner must re-grant, like a hub restart)."""
    from dynamo_tpu.runtime import HubStandby, ShardMap, hub_key
    from dynamo_tpu.runtime.transports.shard import ShardedHubClient

    rows = []
    hubs = [await HubServer().start() for _ in range(2)]
    smap = ShardMap([h.address for h in hubs])
    victim = smap.shard_of_token("instances")
    sibling = 1 - victim
    standby = await HubStandby(hubs[victim].address).start()
    client = await ShardedHubClient(smap.spec).connect()
    try:
        # One key owned by each shard (crc32 routing is stable per spec).
        keys: dict = {}
        i = 0
        while len(keys) < 2:
            k = hub_key(f"sweep{i}", "x")
            keys.setdefault(smap.shard_for_key(k), k)
            i += 1
        await client.kv_put(keys[victim], "before")
        await client.kv_put(keys[sibling], "before")
        lease = await client.lease_grant(ttl=30.0)
        floor_before = hubs[victim].state._next_lease_id

        await hubs[victim].close()  # the shard's primary really dies
        put = asyncio.ensure_future(client.kv_put(keys[victim], "after"))
        await asyncio.sleep(0.3)
        parked = not put.done()
        # The sibling shard owns its keys outright: reads mid-outage.
        sibling_ok = (await client.kv_get(keys[sibling])) == "before"

        hubs[victim] = await standby.promote()
        standby = None
        try:
            await asyncio.wait_for(put, 10.0)
            landed = (await client.kv_get(keys[victim])) == "after"
        except Exception:  # noqa: BLE001 — observation, not assertion
            put.cancel()
            landed = False
        floor_after = hubs[victim].state._next_lease_id
        # Leases are deliberately NOT replicated (only the floor is): the
        # promoted shard must report the composite lease dead so the owner
        # re-grants, and must never re-issue an id below the floor.
        broken = not await client.lease_keepalive(lease)
        observed = (
            ("owner-shard kv_put parked through the kill" if parked
             else "UNEXPECTED: kv_put completed against a dead shard")
            + ("; sibling shard served reads mid-outage" if sibling_ok
               else "; UNEXPECTED: sibling shard blipped")
            + ("; put landed after standby promotion" if landed
               else "; UNEXPECTED: put did not land after failover")
            + (f"; lease floor carried ({floor_before}->{floor_after})"
               if floor_after >= floor_before else
               f"; UNEXPECTED: lease floor regressed "
               f"({floor_before}->{floor_after})")
            + ("; composite lease broken (owner re-grants)" if broken
               else "; UNEXPECTED: composite lease outlived the shard's "
                    "lease state")
        )
        rows.append({
            "fault": "hub_shard_kill",
            "injected_at": "one hub shard's primary (real HubServer close + "
                           "HubStandby promotion onto the same address; the "
                           "ChaosFleet L8 flavour)",
            "observed": observed,
            "status": "paused on the dead shard, then 200",
        })
    finally:
        await client.close()
        if standby is not None:
            await standby.close()
        for hub in hubs:
            try:
                await hub.close()
            except Exception:  # noqa: BLE001 — already-dead primary
                pass
    return rows


async def sweep_engine() -> list:
    """kv_pressure against a real (tiny) engine: admission stalls while the
    pool is squeezed and drains after.  Costs one XLA compile; opt-in."""
    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import TpuEngine

    rows = []
    engine = TpuEngine(EngineConfig(
        model="debug-tiny", block_size=4, num_blocks=32, max_batch=2,
        max_model_len=128, prefill_chunk=32, dtype="float32",
        decode_steps=2, pipeline_depth=2,
    ))
    try:
        req = {
            "token_ids": list(range(1, 17)),
            "stop_conditions": {"max_tokens": 4, "ignore_eos": True},
            "sampling_options": {"temperature": 0.0, "seed": 1},
        }
        await collect(await engine.generate(Context(dict(req))))  # warm
        faults.arm("kv_pressure", delay_s=0.95)
        task = asyncio.ensure_future(
            collect(await engine.generate(Context(dict(req, token_ids=list(range(20, 44))))))
        )
        await asyncio.sleep(0.4)
        stalled = not task.done()
        faults.reset()
        items = await asyncio.wait_for(task, 30.0)
        rows.append({
            "fault": "kv_pressure",
            "injected_at": "scheduler admission (free-block view squeezed)",
            "observed": ("admission stalled under pressure, "
                         if stalled else "UNEXPECTED: admitted under pressure, ")
                        + f"drained to {len(items)} items after release",
            "status": "delayed TTFT, then 200",
        })

        # tenant_flood → WFQ noisy-neighbor isolation (llm/qos.py): a
        # flooding tenant's backlog must not push another tenant's request
        # to the back of admission — the victim completes before the flood
        # tail (FIFO would finish it strictly last).
        faults.arm("tenant_flood", delay_s=3.0)
        order: list = []

        async def run_one(tenant: str, i: int) -> None:
            r = dict(req, token_ids=list(range(50 + i * 29, 50 + i * 29 + 12)),
                     annotations={"tenant": tenant})
            await collect(await engine.generate(Context(r)))
            order.append(tenant)

        flood_tasks = [
            asyncio.ensure_future(run_one("flood", i)) for i in range(5)
        ]
        await asyncio.sleep(0)  # flood enqueues first
        victim = asyncio.ensure_future(run_one("victim", 7))
        await asyncio.wait_for(
            asyncio.gather(*flood_tasks, victim), 60.0
        )
        faults.reset()
        victim_pos = order.index("victim")
        rows.append({
            "fault": "tenant_flood",
            "injected_at": "trace driver (benchmarks/goodput.py L6 rung; "
                           "armed level = flood rate multiplier)",
            "observed": f"victim tenant finished at position {victim_pos} "
                        f"of {len(order)} behind a 5-request flood backlog "
                        "(WFQ admission; FIFO would finish it last)",
            "status": "200 (fair shares)",
        })
    finally:
        faults.reset()
        await engine.close()
    return rows


async def sweep_integrity() -> list:
    """``kv_corrupt`` per plane against the real integrity boundaries
    (engine/integrity.py; docs/kv_tiering.md §integrity) — store-level, so
    the sweep needs no engine build and runs in every matrix:

    - ``disk``: the ARMED fault flips a payload byte inside
      ``DiskKvStore.read`` (the real hook site); the envelope checksum
      must turn it into a recorded miss, never an array.
    - ``host``: a host-tier entry is bit-flipped in RAM; the offload
      stamp (``HostKvStore.checksum``) must disagree — the check
      ``_restore_pass`` runs before every scatter.
    - ``wire``: a transfer payload's K bytes are flipped; the per-block
      ``payload_block_checksums`` must localize the corrupt block — the
      check ``inject_blocks`` runs before sealing.

    The engine-level consequences (descendant drop, negative cache,
    byte-identical recompute, donor quarantine) are gated by
    tests/test_kv_integrity.py and the goodput L7 rung."""
    import tempfile

    import numpy as np

    from dynamo_tpu.engine.disk_cache import DiskKvStore
    from dynamo_tpu.engine.host_cache import HostKvStore
    from dynamo_tpu.engine.integrity import (
        block_checksum,
        flip_array_byte,
        payload_block_checksums,
    )

    rows = []
    blk = np.arange(2 * 4 * 4 * 8, dtype=np.float32).reshape(2, 4, 4, 8)
    with tempfile.TemporaryDirectory() as d:
        store = DiskKvStore(1 << 20, d)
        assert store.put(7, blk, checksum=block_checksum(blk))
        faults.arm("kv_corrupt", match="disk", count=1)
        arr, _chk, corrupt = store.read(7)
        faults.reset()
        dropped = not store.contains(7)
        rows.append({
            "fault": "kv_corrupt disk",
            "injected_at": "DiskKvStore.read (payload byte flipped after "
                           "the OS read; armed fault point)",
            "observed": (
                "envelope checksum caught the flip, file deleted + loss "
                "recorded" if arr is None and corrupt and dropped
                else "UNEXPECTED: corrupt payload survived validation"
            ),
            "status": "tier miss -> recompute",
        })
    host = HostKvStore(1 << 20)
    host.put(5, blk.copy())
    entry = host.peek(5)
    flipped = flip_array_byte(entry)
    caught = block_checksum(flipped) != host.checksum(5)
    rows.append({
        "fault": "kv_corrupt host",
        "injected_at": "host tier entry (bit flipped in RAM; "
                       "_restore_pass verifies before every scatter)",
        "observed": ("offload stamp disagreed with the flipped bytes"
                     if caught else "UNEXPECTED: flip not detected"),
        "status": "tier drop -> recompute",
    })
    k = blk.reshape(2, 1, 4, 4, 8).repeat(3, axis=1).copy()
    v = k + 1.0
    sums = payload_block_checksums(k, v)
    sums2 = payload_block_checksums(flip_array_byte(k), v)
    bad = [i for i in range(3) if sums[i] != sums2[i]]
    rows.append({
        "fault": "kv_corrupt wire",
        "injected_at": "transfer payload K bytes (inject_blocks verifies "
                       "per block before sealing; covers pull + migration "
                       "push + disagg import)",
        "observed": (
            f"per-block checksums localized the flip to block {bad[0]} "
            "(verified prefix still seals)" if len(bad) == 1
            else "UNEXPECTED: flip not localized"
        ),
        "status": "truncated import -> recompute",
    })
    return rows


async def sweep_http() -> list:
    """HTTP-edge behaviours: admission shed + deadline + no instances."""
    from aiohttp import ClientSession

    from dynamo_tpu.llm.http_service import HttpService
    from dynamo_tpu.runtime.client import NoInstancesError
    from dynamo_tpu.runtime.engine import AsyncEngine, ResponseStream

    rows = []

    class SlowEngine(AsyncEngine):
        async def generate(self, request):
            async def gen():
                await asyncio.sleep(0.3)
                yield {
                    "id": "c", "object": "chat.completion.chunk", "created": 0,
                    "model": "m",
                    "choices": [{"index": 0,
                                 "delta": {"role": "assistant", "content": "x"},
                                 "finish_reason": "stop"}],
                }

            return ResponseStream(gen(), request.ctx)

    class NoWorkers(AsyncEngine):
        async def generate(self, request):
            raise NoInstancesError("no instances", prefix="instances/sweep/")

    service = HttpService(
        host="127.0.0.1", port=0,
        max_inflight=2, admission_queue=0, default_deadline_s=2.0,
    )
    service.models.add_chat_model("slow", SlowEngine())
    service.models.add_chat_model("none", NoWorkers())
    await service.start()
    base = f"http://127.0.0.1:{service.port}"
    try:
        async with ClientSession() as http:
            async def post(model, **extra):
                async with http.post(
                    f"{base}/v1/chat/completions",
                    json={"model": model,
                          "messages": [{"role": "user", "content": "x"}],
                          **extra},
                ) as r:
                    return r.status

            statuses = await asyncio.gather(*[post("slow") for _ in range(8)])
            rows.append({
                "fault": "burst past in-flight cap",
                "injected_at": "HTTP edge (AdmissionController)",
                "observed": f"{statuses.count(200)}×200 (the cap), "
                            f"{statuses.count(429)}/8 shed with Retry-After, "
                            f"{statuses.count(500)}×500",
                "status": "429",
            })
            # per-request budget (0.05s) far below the engine's 0.3s stall
            status = await post("slow", deadline_s=0.05)
            rows.append({
                "fault": "deadline exceeded at edge",
                "injected_at": "HTTP edge (Deadline on response drain)",
                "observed": f"got {status} from a stalled engine",
                "status": str(status),
            })
            status = await post("none")
            rows.append({
                "fault": "no live instances",
                "injected_at": "Client instance set empty",
                "observed": f"got {status} + Retry-After (was a bare 500)",
                "status": str(status),
            })
    finally:
        await service.close()
    return rows


async def sweep_bulk() -> list:
    """``bulk_conn_drop`` / ``bulk_slow_peer`` against a real BulkServer +
    ``bulk_fetch`` client pair (transports/bulk.py; docs/bulk_plane.md).
    The system under test is resume-from-last-verified-chunk plus the
    fallback ladder the goodput L9 chaos rung drives fleet-wide."""
    from dynamo_tpu.llm.metrics import bulk_metrics
    from dynamo_tpu.runtime.transports.bulk import (
        BulkServer,
        BulkTransferError,
        bulk_fetch,
        mint_ticket,
    )

    rows = []
    blob = bytes(range(256)) * 24  # 6 KiB -> 6 chunks at chunk_bytes=1024
    server = BulkServer(chunk_bytes=1024)

    async def source(meta):
        return blob

    server.register_source("kv_export", source)
    await server.start()
    try:
        bulk_metrics.reset()
        faults.arm("bulk_conn_drop", count=2)
        got = await bulk_fetch(server.address, "kv_export", mint_ticket("w1"))
        faults.reset()
        resumes = int(bulk_metrics.snapshot()["resumes_total"])
        rows.append({
            "fault": "bulk_conn_drop",
            "injected_at": "BulkServer fetch loop (connection aborted after "
                           "a chunk shipped; armed fault point, count=2)",
            "observed": (
                f"client resumed from the last verified chunk ({resumes} "
                "resumes), stream byte-identical"
                if got == blob and resumes >= 1
                else "UNEXPECTED: resume did not reproduce the stream"
            ),
            "status": "resumed -> byte-identical",
        })

        faults.arm("bulk_slow_peer", delay_s=0.2)
        fell_back = False
        try:
            await bulk_fetch(server.address, "kv_export", mint_ticket("w1"),
                             timeout_s=0.3, max_resumes=1)
        except BulkTransferError as exc:
            fell_back = exc.retryable  # the producers' cue for the hub path
        faults.reset()
        rows.append({
            "fault": "bulk_slow_peer",
            "injected_at": "BulkServer chunk loop (0.2s stall before each "
                           "chunk; armed fault point)",
            "observed": (
                "per-attempt timeout converted the straggler into a "
                "retryable error; the caller falls back to the hub path "
                "(then local recompute), stream stays byte-identical"
                if fell_back
                else "UNEXPECTED: straggler not converted to fallback"
            ),
            "status": "fallback -> hub path",
        })
    finally:
        await server.close()
    return rows


def to_markdown(rows: list) -> str:
    lines = [
        "| fault point | injected at | observed behaviour | client status |",
        "|---|---|---|---|",
    ]
    for r in rows:
        lines.append(
            f"| `{r['fault']}` | {r['injected_at']} | {r['observed']} "
            f"| {r['status']} |"
        )
    return "\n".join(lines) + "\n"


async def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", default=None, help="write JSON artifact here")
    ap.add_argument("--md", default=None, help="write markdown matrix here")
    ap.add_argument("--engine", action="store_true",
                    help="include the kv_pressure sweep (builds a real engine)")
    args = ap.parse_args()

    rows = (await sweep_runtime() + await sweep_chaos() + await sweep_shards()
            + await sweep_http() + await sweep_integrity() + await sweep_bulk())
    if args.engine:
        rows += await sweep_engine()
    md = to_markdown(rows)
    print(md)
    if args.json:
        Path(args.json).write_text(json.dumps({
            "schema": "dynamo-tpu-fault-matrix-v2",
            "fault_kinds": sorted({r["fault"].split(" ")[0] for r in rows}),
            "fault_matrix": rows,
        }, indent=2))
        print(f"wrote {args.json}")
    if args.md:
        Path(args.md).write_text(md)
        print(f"wrote {args.md}")
    bad = [r for r in rows if "UNEXPECTED" in r["observed"] or "NOT RECOVERED"
           in r["observed"]]
    return 1 if bad else 0


if __name__ == "__main__":
    raise SystemExit(asyncio.run(main()))
