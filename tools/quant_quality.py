"""Quantization quality evidence on the real-checkpoint stack
(VERDICT r4 next #1 "accuracy tables" + weak #5 "KV drift on
non-degenerate logits").

Builds the golden HF-format checkpoint (the same builder the golden-token
serving tests use — tests/test_real_checkpoint.py), then measures, prompt
by prompt, last-token distributions against the bf16 forward of the SAME
weights:

  weight-int8      W8A8-dynamic execution of per-channel int8 weights
                   (models/quant.py) vs the f32 dequantized reference
  kv-int8 / kv-fp8 bf16 weights with quantized KV pages (per-layer
                   auto-calibrated scales) vs the bf16-KV forward

Reported per config: mean KL divergence, top-1 agreement overall, and
top-1 agreement on DECISIVE positions (reference top-2 margin > 3x the
observed max logit error — random-init logits are near-ties, so raw
agreement under-reports; decisive agreement is the honest gate).

Writes benchmarks/results/r5_quant_quality.json; render_results.py
renders the RESULTS.md table from it.  Run on CPU:
    JAX_PLATFORMS=cpu python tools/quant_quality.py
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

N_PROMPTS = 16
PROMPT_LEN = 24


def _forward(params, cfg, prompt, cache_dtype, kv_scale):
    import jax.numpy as jnp

    from dynamo_tpu.models.llama import PagedKVCache, RaggedBatch, forward_ragged

    T = len(prompt)
    bs = 4
    nb = (T + bs - 1) // bs + 1
    cache = PagedKVCache.create(cfg, nb, bs, dtype=jnp.dtype(cache_dtype))
    rb = RaggedBatch(
        token_ids=jnp.asarray(prompt, jnp.int32),
        positions=jnp.arange(T, dtype=jnp.int32),
        slot_mapping=jnp.arange(T, dtype=jnp.int32),
        kv_lens=jnp.asarray([T], jnp.int32),
        page_indices=jnp.arange(nb, dtype=jnp.int32)[None],
        cu_q_lens=jnp.asarray([0, T], jnp.int32),
        num_seqs=jnp.asarray([1], jnp.int32),
    )
    logits, _ = forward_ragged(
        params, cfg, rb, cache, attn_impl="xla", kv_scale=kv_scale
    )
    return np.asarray(logits[0], np.float32)


def _calibrate(params, cfg, probe_prompt, dtype_name):
    """Per-layer KV scales from a bf16 probe (engine._calibrate_kv_scales
    logic at module level)."""
    import jax.numpy as jnp

    from dynamo_tpu.models.llama import PagedKVCache, RaggedBatch, forward_ragged

    T = len(probe_prompt)
    bs = 4
    nb = (T + bs - 1) // bs + 1
    cache = PagedKVCache.create(cfg, nb, bs, dtype=jnp.float32)
    rb = RaggedBatch(
        token_ids=jnp.asarray(probe_prompt, jnp.int32),
        positions=jnp.arange(T, dtype=jnp.int32),
        slot_mapping=jnp.arange(T, dtype=jnp.int32),
        kv_lens=jnp.asarray([T], jnp.int32),
        page_indices=jnp.arange(nb, dtype=jnp.int32)[None],
        cu_q_lens=jnp.asarray([0, T], jnp.int32),
        num_seqs=jnp.asarray([1], jnp.int32),
    )
    _, probe = forward_ragged(params, cfg, rb, cache, attn_impl="xla")
    maxabs = np.asarray(
        jnp.max(jnp.abs(probe.pages.astype(jnp.float32)), axis=(1, 2, 3, 4))
    )
    if dtype_name == "int8":
        qmax = 127.0
    else:
        import jax.numpy as jnp

        qmax = float(jnp.finfo(jnp.float8_e4m3fn).max)  # 448
    return np.maximum(maxabs / qmax, 1e-6).astype(np.float32)


def _stats(ref_logits, got_logits):
    kls, agree, decisive, agree_all = [], 0, 0, 0
    for lr, lq in zip(ref_logits, got_logits):
        pr = np.exp(lr - lr.max()); pr /= pr.sum()
        pq = np.exp(lq - lq.max()); pq /= pq.sum()
        kls.append(float(np.sum(pr * (np.log(pr + 1e-12) - np.log(pq + 1e-12)))))
        agree_all += int(np.argmax(lq) == np.argmax(lr))
        err = np.max(np.abs(lq - lr))
        top2 = np.partition(lr, -2)[-2:]
        if top2[1] - top2[0] > 3 * err:
            decisive += 1
            agree += int(np.argmax(lq) == np.argmax(lr))
    n = len(ref_logits)
    return {
        "mean_kl": round(float(np.mean(kls)), 6),
        "top1_agree": f"{agree_all}/{n}",
        "decisive": decisive,
        "decisive_agree": f"{agree}/{decisive}" if decisive else "0/0",
    }


def main() -> None:
    from test_real_checkpoint import build_checkpoint

    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.models.loader import load_params
    from dynamo_tpu.models.quant import dequantize_params, quantize_params

    out_path = os.path.join(REPO, "benchmarks", "results", "r5_quant_quality.json")
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "model")
        build_checkpoint(path)
        cfg = ModelConfig.from_local_path(path).with_overrides(
            name="golden-tiny", dtype="float32"
        )
        params = load_params(cfg, path)
        qp = quantize_params(load_params(cfg, path))
        deq = dequantize_params(qp)

        rng = np.random.default_rng(17)
        prompts = [
            rng.integers(3, cfg.vocab_size, size=PROMPT_LEN).tolist()
            for _ in range(N_PROMPTS)
        ]
        kv_scales = {
            name: _calibrate(params, cfg, prompts[0], name)
            for name in ("int8", "float8_e4m3fn")
        }

        ref_deq = [_forward(deq, cfg, p, "float32", None) for p in prompts]
        ref_bf16kv = [_forward(params, cfg, p, "float32", None) for p in prompts]

        rows = []
        got = [_forward(qp, cfg, p, "float32", None) for p in prompts]
        rows.append({"config": "weights int8 (W8A8-dynamic) vs dequantized ref",
                     **_stats(ref_deq, got)})
        for name, label in (("int8", "kv int8 + per-layer auto scales"),
                            ("float8_e4m3fn", "kv fp8-e4m3 + per-layer auto scales")):
            got = [
                _forward(params, cfg, p, name, kv_scales[name]) for p in prompts
            ]
            rows.append({"config": f"{label} vs bf16-KV ref", **_stats(ref_bf16kv, got)})
        got = [_forward(qp, cfg, p, "int8", kv_scales["int8"]) for p in prompts]
        rows.append({"config": "weights int8 + kv int8 (full serving config)",
                     **_stats(ref_deq, got)})

    doc = {
        "n_prompts": N_PROMPTS,
        "prompt_len": PROMPT_LEN,
        "checkpoint": "golden-tiny (tests/test_real_checkpoint.py builder)",
        "rows": rows,
    }
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps(doc, indent=1))
    print(f"wrote {out_path}", file=sys.stderr)


if __name__ == "__main__":
    main()
