"""Decide the int8 weight-quant matmul strategy on real hardware.

Compares, at decode geometry (B rows x [D, F] weights, chained like an FFN
stack so HBM prefetch behavior shows up):

  bf16      x(bf16) @ w(bf16)                      — today's baseline
  w8a16     (x @ w_q.astype(bf16)) * s             — weight-only; fast ONLY
            if XLA fuses the int8->bf16 convert into the dot's operand read
            instead of materializing a bf16 copy of the weights
  w8a8dyn   per-row dynamic act quant; int8 x int8 dot -> int32; scale out
            — native MXU int8 path (v5e int8 peak ~2x bf16), the closest
            analog of the reference baseline's FP8-dynamic checkpoint

Prints per-variant ms/iter and device memory. Run on the TPU:
    python tools/quant_microbench.py
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

B = 256  # decode batch rows
D = 4096
F = 14336
LAYERS = 8  # chain length: enough for prefetch behavior to matter


def _run(fn, args, iters):
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    float(jnp.sum(out))  # force a real device->host fetch (tunnel RTT ~110ms)
    return time.perf_counter() - t0


def timeit(fn, *args, iters=20, repeats=3):
    out = fn(*args)
    float(jnp.sum(out))  # compile + warm
    best = float("inf")
    for _ in range(repeats):
        # Difference two iteration counts so the constant fetch RTT cancels.
        lo = _run(fn, args, 2)
        hi = _run(fn, args, 2 + iters)
        best = min(best, (hi - lo) / iters * 1e3)
    return best  # ms


def mem_mb():
    try:
        s = jax.devices()[0].memory_stats()
        return s.get("bytes_in_use", 0) / 1e6
    except Exception:
        return 0.0


def main():
    key = jax.random.PRNGKey(0)
    print(f"backend={jax.default_backend()} B={B} D={D} F={F} layers={LAYERS}")
    x = jax.random.normal(key, (B, D), jnp.bfloat16)

    # --- bf16 baseline ----------------------------------------------------
    w_bf = jax.random.normal(key, (LAYERS, D, F), jnp.bfloat16) * 0.02
    w2_bf = jax.random.normal(key, (LAYERS, F, D), jnp.bfloat16) * 0.02

    @jax.jit
    def chain_bf16(x, w, w2):
        for l in range(LAYERS):
            h = x @ w[l]
            x = (h @ w2[l]).astype(jnp.bfloat16)
        return x

    ms = timeit(chain_bf16, x, w_bf, w2_bf)
    # bytes: weights dominate (2 * L * D * F * 2B)
    gb = 2 * LAYERS * D * F * 2 / 1e9
    print(f"bf16   : {ms:8.3f} ms/iter  ({gb/ (ms/1e3):.0f} GB/s wts)  mem={mem_mb():.0f}MB")

    # --- int8 weights -----------------------------------------------------
    s1 = (jnp.max(jnp.abs(w_bf), axis=1) / 127.0).astype(jnp.float32)  # [L, F]
    w_q = jnp.round(w_bf / s1[:, None, :]).astype(jnp.int8)
    s2 = (jnp.max(jnp.abs(w2_bf), axis=1) / 127.0).astype(jnp.float32)  # [L, D]
    w2_q = jnp.round(w2_bf / s2[:, None, :]).astype(jnp.int8)

    @jax.jit
    def chain_w8a16(x, w, s1, w2, s2):
        for l in range(LAYERS):
            h = ((x @ w[l].astype(jnp.bfloat16)).astype(jnp.float32) * s1[l]).astype(
                jnp.bfloat16
            )
            x = ((h @ w2[l].astype(jnp.bfloat16)).astype(jnp.float32) * s2[l]).astype(
                jnp.bfloat16
            )
        return x

    ms = timeit(chain_w8a16, x, w_q, s1, w2_q, s2)
    gb = 2 * LAYERS * D * F * 1 / 1e9
    print(f"w8a16  : {ms:8.3f} ms/iter  ({gb/ (ms/1e3):.0f} GB/s wts)  mem={mem_mb():.0f}MB")

    # --- w8a8 dynamic ------------------------------------------------------
    @jax.jit
    def chain_w8a8(x, w, s1, w2, s2):
        for l in range(LAYERS):
            ax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=1, keepdims=True) / 127.0
            xq = jnp.round(x.astype(jnp.float32) / jnp.maximum(ax, 1e-9)).astype(jnp.int8)
            h32 = jax.lax.dot_general(
                xq, w[l], (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32
            )
            h = (h32.astype(jnp.float32) * ax * s1[l]).astype(jnp.bfloat16)
            ah = jnp.max(jnp.abs(h.astype(jnp.float32)), axis=1, keepdims=True) / 127.0
            hq = jnp.round(h.astype(jnp.float32) / jnp.maximum(ah, 1e-9)).astype(jnp.int8)
            x32 = jax.lax.dot_general(
                hq, w2[l], (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32
            )
            x = (x32.astype(jnp.float32) * ah * s2[l]).astype(jnp.bfloat16)
        return x

    ms = timeit(chain_w8a8, x, w_q, s1, w2_q, s2)
    print(f"w8a8dyn: {ms:8.3f} ms/iter  ({gb/ (ms/1e3):.0f} GB/s wts)  mem={mem_mb():.0f}MB")

    # --- w8a8 static act scale (no serialized max-abs reduction) -----------
    @jax.jit
    def chain_w8a8s(x, w, s1, w2, s2):
        for l in range(LAYERS):
            xq = jnp.round(x.astype(jnp.float32) * 32.0).astype(jnp.int8)
            h32 = jax.lax.dot_general(
                xq, w[l], (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32
            )
            h = (h32.astype(jnp.float32) * (s1[l] / 32.0)).astype(jnp.bfloat16)
            hq = jnp.round(h.astype(jnp.float32) * 32.0).astype(jnp.int8)
            x32 = jax.lax.dot_general(
                hq, w2[l], (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32
            )
            x = (x32.astype(jnp.float32) * (s2[l] / 32.0)).astype(jnp.bfloat16)
        return x

    ms = timeit(chain_w8a8s, x, w_q, s1, w2_q, s2)
    print(f"w8a8sta: {ms:8.3f} ms/iter  ({gb/ (ms/1e3):.0f} GB/s wts)  mem={mem_mb():.0f}MB")

    # --- mixed dot: bf16 activations x int8 weights directly ---------------
    @jax.jit
    def chain_mixed(x, w, s1, w2, s2):
        for l in range(LAYERS):
            h32 = jax.lax.dot_general(
                x, w[l], (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
            )
            h = (h32 * s1[l]).astype(jnp.bfloat16)
            x32 = jax.lax.dot_general(
                h, w2[l], (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
            )
            x = (x32 * s2[l]).astype(jnp.bfloat16)
        return x

    try:
        ms = timeit(chain_mixed, x, w_q, s1, w2_q, s2)
        print(f"mixed  : {ms:8.3f} ms/iter  ({gb/ (ms/1e3):.0f} GB/s wts)  mem={mem_mb():.0f}MB")
    except Exception as e:
        print(f"mixed  : unsupported ({type(e).__name__})")

    # --- prefill geometry (compute-bound): chained big matmuls --------------
    xp = jax.random.normal(key, (2048, D), jnp.bfloat16)

    @jax.jit
    def pchain_bf16(x, w, w2):
        for l in range(LAYERS):
            h = x @ w[l]
            x = (h @ w2[l]).astype(jnp.bfloat16)
        return x

    @jax.jit
    def pchain_w8a8(x, w, s1, w2, s2):
        for l in range(LAYERS):
            ax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=1, keepdims=True) / 127.0
            xq = jnp.round(x.astype(jnp.float32) / jnp.maximum(ax, 1e-9)).astype(jnp.int8)
            h32 = jax.lax.dot_general(
                xq, w[l], (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32
            )
            h = (h32.astype(jnp.float32) * ax * s1[l]).astype(jnp.bfloat16)
            ah = jnp.max(jnp.abs(h.astype(jnp.float32)), axis=1, keepdims=True) / 127.0
            hq = jnp.round(h.astype(jnp.float32) / jnp.maximum(ah, 1e-9)).astype(jnp.int8)
            x32 = jax.lax.dot_general(
                hq, w2[l], (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32
            )
            x = (x32.astype(jnp.float32) * ah * s2[l]).astype(jnp.bfloat16)
        return x

    flops = 2 * 2048 * D * F * 2 * LAYERS
    ms = timeit(pchain_bf16, xp, w_bf, w2_bf, iters=40)
    print(f"prefill bf16   : {ms:7.3f} ms  ({flops/(ms/1e3)/1e12:.0f} TFLOP/s)")
    ms = timeit(pchain_w8a8, xp, w_q, s1, w2_q, s2, iters=40)
    print(f"prefill w8a8dyn: {ms:7.3f} ms  ({flops/(ms/1e3)/1e12:.0f} TFLOP/s)")


if __name__ == "__main__":
    main()
