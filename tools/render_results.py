"""Render benchmarks/RESULTS.md tables MECHANICALLY from committed JSON
artifacts (VERDICT r4 weak #1: a hand-edited TTFT-p99 column diverged from
its artifact on 8 of 9 rows — tables must be generated, never typed).

Usage:
    python tools/render_results.py benchmarks/results/r5_agg_ladder.json
        -> prints the markdown table for a ladder artifact
    python tools/render_results.py --inject
        -> rewrites every  <!-- TABLE:<relpath> --> ... <!-- /TABLE -->
           block in benchmarks/RESULTS.md from its named artifact
    python tools/render_results.py --check
        -> same scan, but only verifies; exit 1 on any drift (CI-able,
           tests/test_driver_contracts.py runs this)
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RESULTS_MD = os.path.join(REPO, "benchmarks", "RESULTS.md")

_MARK = re.compile(
    r"(<!-- TABLE:(?P<path>[^ ]+) -->\n)(?P<body>.*?)(<!-- /TABLE -->)",
    re.DOTALL,
)


def _fmt_ms(v: float) -> str:
    return f"{v:.0f}ms"


def ladder_table(doc: dict) -> str:
    """Markdown table for a loadgen sweep artifact ({isl, osl, rows})."""
    lines = [
        "| conc | reqs | ok | out tok/s | req/s | TTFT p50 | TTFT p99 | ITL p50 | ITL p99 |",
        "|---|---|---|---|---|---|---|---|---|",
    ]
    for r in doc["rows"]:
        lines.append(
            "| {conc} | {reqs} | {ok} | {tps} | {rps} | {t50} | {t99} | {i50} | {i99} |".format(
                conc=r["concurrency"],
                reqs=r["requests"],
                ok=r["ok"],
                tps=r["output_tok_s"],
                rps=r["req_s"],
                t50=_fmt_ms(r["ttft_p50_ms"]),
                t99=_fmt_ms(r["ttft_p99_ms"]),
                i50=f"{r['itl_p50_ms']}ms",
                i99=f"{r['itl_p99_ms']}ms",
            )
        )
    return "\n".join(lines) + "\n"


def scaling_table(doc: dict) -> str:
    """Markdown table for a bench batch-scaling artifact ({rows: [{max_batch,
    tok_s, mfu_pct}]})."""
    lines = ["| max_batch | tok/s | decode MFU |", "|---|---|---|"]
    for r in doc["rows"]:
        lines.append(
            f"| {r['max_batch']} | {r['tok_s']} | {r.get('mfu_pct', '—')}% |"
        )
    return "\n".join(lines) + "\n"


def quality_table(doc: dict) -> str:
    """Markdown table for a tools/quant_quality.py artifact."""
    lines = [
        "| config | mean KL | top-1 | decisive top-1 |",
        "|---|---|---|---|",
    ]
    for r in doc["rows"]:
        lines.append(
            f"| {r['config']} | {r['mean_kl']} | {r['top1_agree']} "
            f"| {r['decisive_agree']} |"
        )
    return "\n".join(lines) + "\n"


def sweep_table(doc: dict) -> str:
    """Markdown table for a config-sweep artifact (prefill_chunk rows)."""
    lines = [
        "| prefill_chunk | decode slots | conc | out tok/s | TTFT p50 | TTFT p99 |",
        "|---|---|---|---|---|---|",
    ]
    for r in doc["rows"]:
        lines.append(
            f"| {r['prefill_chunk']} | {r['max_batch']} | {r['concurrency']} "
            f"| {r['output_tok_s']} | {_fmt_ms(r['ttft_p50_ms'])} "
            f"| {_fmt_ms(r['ttft_p99_ms'])} |"
        )
    return "\n".join(lines) + "\n"


def render(path: str) -> str:
    with open(path) as f:
        doc = json.load(f)
    if "rows" in doc and doc["rows"] and "prefill_chunk" in doc["rows"][0]:
        return sweep_table(doc)
    if "rows" in doc and doc["rows"] and "concurrency" in doc["rows"][0]:
        return ladder_table(doc)
    if "rows" in doc and doc["rows"] and "max_batch" in doc["rows"][0]:
        return scaling_table(doc)
    if "rows" in doc and doc["rows"] and "mean_kl" in doc["rows"][0]:
        return quality_table(doc)
    raise SystemExit(f"unrecognized artifact shape: {path}")


def inject(check_only: bool) -> int:
    with open(RESULTS_MD) as f:
        text = f.read()
    drift = []

    def repl(m: re.Match) -> str:
        rel = m.group("path")
        table = render(os.path.join(REPO, rel))
        if m.group("body") != table:
            drift.append(rel)
        return m.group(1) + table + m.group(4)

    new = _MARK.sub(repl, text)
    if check_only:
        if drift:
            print(f"RESULTS.md tables drifted from artifacts: {drift}")
            return 1
        print("RESULTS.md tables match their artifacts")
        return 0
    if new != text:
        with open(RESULTS_MD, "w") as f:
            f.write(new)
        print(f"rewrote {len(drift)} table(s): {drift}")
    else:
        print("RESULTS.md already up to date")
    return 0


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("artifact", nargs="?", help="print one artifact's table")
    ap.add_argument("--inject", action="store_true")
    ap.add_argument("--check", action="store_true")
    args = ap.parse_args()
    if args.artifact:
        sys.stdout.write(render(args.artifact))
        return
    if args.inject or args.check:
        raise SystemExit(inject(check_only=args.check))
    ap.error("need an artifact path, --inject, or --check")


if __name__ == "__main__":
    main()
