"""Decode-kernel block-hint autotuner (``python -m tools.tune_decode``).

Sweeps the decode attention grid/block knobs at a given (model, batch,
page-size) geometry and writes the winner into a small JSON table that
engine init loads (``ops/decode_attention.install_tuned_hints``) instead
of the hardcoded ``_decode_block_hints`` defaults — falling back to them
when no entry matches.  Two knob families:

- **fused** (``DYN_DECODE_KERNEL=pallas_fused``,
  ops/decode_attention.py): ``splits`` (KV-split grid width) and ``ppcb``
  (pages per compute block) — swept by calling the kernel with explicit
  overrides, one jit trace per combo.
- **stock** (the jax pallas ragged kernel, TPU only): ``nq`` query block
  and ``nkv_mb`` KV VMEM budget — swept through the env vars the hint
  function reads at trace time.
- **prefill** (``DYN_PREFILL_KERNEL=pallas``, ops/prefill_attention.py):
  ``prefill_qb`` (query tokens per block), ``prefill_splits`` (KV-split
  grid width) and ``prefill_ppcb`` (pages per compute block) — swept by
  calling the kernel with explicit overrides at a chunked-prefill
  geometry (every row one ``--prefill-chunk`` tail against a full-chain
  paged prefix).

On CPU the fused kernel runs in interpret mode, so absolute timings are
meaningless — the sweep is a smoke (it still exercises every combo and
the table write path); run on the v5e for numbers of record.  Resolution
order stays: explicit env var > tuned table > default, so a sweep never
overrides an operator's pin.

Example:
    python -m tools.tune_decode --model llama-3.1-8b --batch 256 \
        --page-size 32 --pages-per-seq 64 --cache-dtype int8 \
        --out ~/.cache/dynamo_tpu/decode_tune.json
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

import jax


def _build_case(model: str, batch: int, page_size: int, pages_per_seq: int,
                cache_dtype: str, seed: int):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.models.config import get_config

    c = get_config(model)
    H, KV, D = c.num_heads, c.num_kv_heads, c.head_dim
    P = batch * pages_per_seq + 1
    keys = jax.random.split(jax.random.PRNGKey(seed), 2)
    q = jax.random.normal(keys[0], (batch, H, D), jnp.bfloat16)
    dt = jnp.dtype(cache_dtype)
    vals = jax.random.normal(keys[1], (P, page_size, 2 * KV, D), jnp.float32)
    if dt.itemsize == 1 and jnp.issubdtype(dt, jnp.integer):
        pages = jnp.clip(jnp.round(vals * 40.0), -127, 127).astype(dt)
        kv_scale = 1.0 / 40.0
    else:
        pages = vals.astype(dt)
        kv_scale = None
    rng = np.random.default_rng(seed)
    # Full chains: the sweep times the worst (longest-context) geometry.
    kv_lens = jnp.full((batch,), pages_per_seq * page_size, jnp.int32)
    tables = jnp.asarray(
        rng.permutation(batch * pages_per_seq).reshape(batch, pages_per_seq),
        jnp.int32,
    )
    num = jnp.asarray([batch], jnp.int32)
    return q, pages, kv_lens, tables, num, D**-0.5, kv_scale


def _build_prefill_case(model: str, batch: int, page_size: int,
                        pages_per_seq: int, cache_dtype: str, chunk: int,
                        seed: int):
    """Chunked-prefill geometry: every row is computing its LAST ``chunk``
    prompt tokens against a full paged chain (prefix + own chunk already
    in cache) — the worst-case prefix read the kernel exists to speed."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.models.config import get_config

    c = get_config(model)
    H, KV, D = c.num_heads, c.num_kv_heads, c.head_dim
    P = batch * pages_per_seq + 1
    chunk = min(chunk, pages_per_seq * page_size)
    keys = jax.random.split(jax.random.PRNGKey(seed), 2)
    q = jax.random.normal(keys[0], (batch * chunk, H, D), jnp.bfloat16)
    dt = jnp.dtype(cache_dtype)
    vals = jax.random.normal(keys[1], (P, page_size, 2 * KV, D), jnp.float32)
    if dt.itemsize == 1 and jnp.issubdtype(dt, jnp.integer):
        pages = jnp.clip(jnp.round(vals * 40.0), -127, 127).astype(dt)
        kv_scale = 1.0 / 40.0
    else:
        pages = vals.astype(dt)
        kv_scale = None
    rng = np.random.default_rng(seed)
    kv_lens = jnp.full((batch,), pages_per_seq * page_size, jnp.int32)
    tables = jnp.asarray(
        rng.permutation(batch * pages_per_seq).reshape(batch, pages_per_seq),
        jnp.int32,
    )
    cu = jnp.arange(batch + 1, dtype=jnp.int32) * chunk
    num = jnp.asarray([batch], jnp.int32)
    return q, pages, kv_lens, tables, cu, num, D**-0.5, kv_scale


def sweep_prefill(case, qb_list: List[int], splits_list: List[int],
                  ppcb_list: List[int],
                  iters: int) -> Tuple[Optional[Dict[str, Any]], List[Dict]]:
    from dynamo_tpu.ops.prefill_attention import fused_prefill_attention

    q, pages, kv_lens, tables, cu, num, sm, kv_scale = case
    results = []
    for qb in qb_list:
        for s in splits_list:
            for p in ppcb_list:
                if p > tables.shape[1]:
                    continue
                fn = jax.jit(
                    lambda q, pages, kv_lens, tables, cu, num,
                           _qb=qb, _s=s, _p=p:
                    fused_prefill_attention(
                        q, pages, kv_lens, tables, cu, num, sm_scale=sm,
                        kv_scale=kv_scale, q_block=_qb, num_kv_splits=_s,
                        pages_per_block=_p,
                    )
                )
                try:
                    us = _time_fn(
                        fn, (q, pages, kv_lens, tables, cu, num), iters
                    )
                except Exception as e:
                    print(f"tune: prefill qb={qb} splits={s} ppcb={p} "
                          f"rejected: {e}", file=sys.stderr)
                    continue
                results.append(
                    {"qb": qb, "splits": s, "ppcb": p, "us": round(us, 1)}
                )
                print(f"tune: prefill qb={qb} splits={s} ppcb={p}: "
                      f"{us:.1f}us", file=sys.stderr)
    best = min(results, key=lambda r: r["us"]) if results else None
    return best, results


def _time_fn(fn, args, iters: int) -> float:
    """Median wall microseconds per call (after one warmup/compile)."""
    out = fn(*args)
    jax.block_until_ready(out)
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        samples.append((time.perf_counter() - t0) * 1e6)
    return statistics.median(samples)


def sweep_fused(case, splits_list: List[int], ppcb_list: List[int],
                iters: int) -> Tuple[Optional[Dict[str, Any]], List[Dict]]:
    from dynamo_tpu.ops.decode_attention import fused_decode_attention

    q, pages, kv_lens, tables, num, sm, kv_scale = case
    results = []
    for s in splits_list:
        for p in ppcb_list:
            if p > tables.shape[1]:
                continue
            fn = jax.jit(
                lambda q, pages, kv_lens, tables, num, _s=s, _p=p:
                fused_decode_attention(
                    q, pages, kv_lens, tables, num, sm_scale=sm,
                    kv_scale=kv_scale, num_kv_splits=_s, pages_per_block=_p,
                )
            )
            try:
                us = _time_fn(fn, (q, pages, kv_lens, tables, num), iters)
            except Exception as e:
                print(f"tune: fused splits={s} ppcb={p} rejected: {e}",
                      file=sys.stderr)
                continue
            results.append({"splits": s, "ppcb": p, "us": round(us, 1)})
            print(f"tune: fused splits={s} ppcb={p}: {us:.1f}us",
                  file=sys.stderr)
    best = min(results, key=lambda r: r["us"]) if results else None
    return best, results


def sweep_stock(case, nq_list: List[int], nkv_mb_list: List[int],
                iters: int) -> Tuple[Optional[Dict[str, Any]], List[Dict]]:
    """TPU only: the stock kernel's hints are env-read at trace time, so
    each combo re-jits under its own env.  Skipped on CPU (the stock path
    there is the XLA fallback, which ignores the hints entirely)."""
    from dynamo_tpu.ops.ragged_attention import on_tpu, ragged_decode_attention

    if not on_tpu():
        print("tune: stock sweep skipped (not on TPU — XLA fallback has "
              "no block hints)", file=sys.stderr)
        return None, []
    q, pages, kv_lens, tables, num, sm, kv_scale = case
    results = []
    for nq in nq_list:
        for mb in nkv_mb_list:
            os.environ["DYN_DECODE_NQ"] = str(nq)
            os.environ["DYN_DECODE_NKV_MB"] = str(mb)
            fn = jax.jit(
                lambda q, pages, kv_lens, tables, num:
                ragged_decode_attention(
                    q, pages, kv_lens, tables, num, sm_scale=sm,
                    impl="tpu", kv_scale=kv_scale, kernel="stock",
                )
            )
            try:
                us = _time_fn(fn, (q, pages, kv_lens, tables, num), iters)
            except Exception as e:
                print(f"tune: stock nq={nq} nkv_mb={mb} rejected: {e}",
                      file=sys.stderr)
                continue
            finally:
                os.environ.pop("DYN_DECODE_NQ", None)
                os.environ.pop("DYN_DECODE_NKV_MB", None)
            results.append({"nq": nq, "nkv_mb": mb, "us": round(us, 1)})
            print(f"tune: stock nq={nq} nkv_mb={mb}: {us:.1f}us",
                  file=sys.stderr)
    best = min(results, key=lambda r: r["us"]) if results else None
    return best, results


def write_entry(path: str, key: str, entry: Dict[str, Any]) -> None:
    """Merge one geometry's entry into the table (other keys preserved)."""
    table: Dict[str, Any] = {}
    try:
        with open(path) as f:
            table = json.load(f)
    except (OSError, ValueError):
        pass
    if not isinstance(table, dict):
        table = {}
    table[key] = entry
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(table, f, indent=2, sort_keys=True)
    os.replace(tmp, path)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", default="debug-tiny")
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--page-size", type=int, default=32)
    ap.add_argument("--pages-per-seq", type=int, default=64)
    ap.add_argument("--cache-dtype", default="int8")
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--splits", default="1,2,4,8",
                    help="fused KV-split candidates (comma list)")
    ap.add_argument("--ppcb", default="1,2,4,8",
                    help="fused pages-per-compute-block candidates")
    ap.add_argument("--nq", default="8,16,32",
                    help="stock query-block candidates (TPU only)")
    ap.add_argument("--nkv-mb", default="2,4,8",
                    help="stock KV VMEM budget candidates in MB (TPU only)")
    ap.add_argument("--prefill-chunk", type=int, default=512,
                    help="prompt tokens per row in the prefill sweep case")
    ap.add_argument("--prefill-qb", default="64,128,256",
                    help="prefill query-block candidates (comma list)")
    ap.add_argument("--prefill-splits", default="1,2,4",
                    help="prefill KV-split candidates")
    ap.add_argument("--prefill-ppcb", default="1,2,4,8",
                    help="prefill pages-per-compute-block candidates")
    ap.add_argument("--out", default=None,
                    help="table path (default: DYN_DECODE_TUNE_TABLE or "
                         "~/.cache/dynamo_tpu/decode_tune.json)")
    args = ap.parse_args(argv)

    from dynamo_tpu.ops.decode_attention import default_table_path, hint_key

    ints = lambda s: [int(x) for x in str(s).split(",") if x.strip()]
    case = _build_case(args.model, args.batch, args.page_size,
                       args.pages_per_seq, args.cache_dtype, args.seed)
    fused_best, fused_all = sweep_fused(
        case, ints(args.splits), ints(args.ppcb), args.iters
    )
    stock_best, stock_all = sweep_stock(
        case, ints(args.nq), ints(args.nkv_mb), args.iters
    )
    prefill_case = _build_prefill_case(
        args.model, args.batch, args.page_size, args.pages_per_seq,
        args.cache_dtype, args.prefill_chunk, args.seed,
    )
    prefill_best, prefill_all = sweep_prefill(
        prefill_case, ints(args.prefill_qb), ints(args.prefill_splits),
        ints(args.prefill_ppcb), args.iters,
    )
    if fused_best is None and stock_best is None and prefill_best is None:
        print("tune: no combo survived — nothing written", file=sys.stderr)
        return 1

    entry: Dict[str, Any] = {
        "geometry": {
            "model": args.model, "batch": args.batch,
            "page_size": args.page_size, "pages_per_seq": args.pages_per_seq,
            "cache_dtype": args.cache_dtype,
        },
        "backend": jax.default_backend(),
        "iters": args.iters,
    }
    if fused_best:
        entry.update(splits=fused_best["splits"], ppcb=fused_best["ppcb"],
                     fused_us=fused_best["us"])
    if stock_best:
        entry.update(nq=stock_best["nq"], nkv_mb=stock_best["nkv_mb"],
                     stock_us=stock_best["us"])
    if prefill_best:
        # Keys match resolve_hint's tuned_key names in
        # ops/prefill_attention.py, so install_tuned_hints serves them
        # with zero extra plumbing.
        entry.update(prefill_qb=prefill_best["qb"],
                     prefill_splits=prefill_best["splits"],
                     prefill_ppcb=prefill_best["ppcb"],
                     prefill_us=prefill_best["us"])
    path = args.out or default_table_path()
    key = hint_key(args.model, args.batch, args.page_size)
    write_entry(path, key, entry)
    print(json.dumps({"key": key, "path": path, "entry": entry,
                      "fused_sweep": fused_all, "stock_sweep": stock_all,
                      "prefill_sweep": prefill_all}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
