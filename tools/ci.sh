#!/usr/bin/env bash
# CI entrypoint: dynalint gate first (cheap, fails fast), then the tier-1
# pytest command from ROADMAP.md.  Run from anywhere; works from repo root.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== dynalint 3.0 (async-safety, JAX invariants, async-race, taint,"
echo "   wire-schema, resource lifetime, compile stability;"
echo "   artifact: /tmp/dynalint_report.json) =="
python -m tools.dynalint dynamo_tpu --json > /tmp/dynalint_report.json \
  || { cat /tmp/dynalint_report.json; exit 1; }
python - <<'PYEOF'
# Budget + debt-cap enforcement over the --json artifact: full-corpus
# analysis must stay under the 60s CI budget (per-pass timings in the
# artifact attribute any regression), the baseline must hold ZERO entries
# for the 2.0/3.0 families (DYN1xx/2xx/3xx/5xx/6xx true positives are
# fixed, never baselined — and the full run also re-validates the
# lifetime/stability registries against the tree via DYN504/DYN604, so a
# renamed helper goes stale loudly), and total grandfathered debt stays
# under the ISSUE 2 cap.
import json, sys
r = json.load(open("/tmp/dynalint_report.json"))
t = r["timings"]
assert r["ok"], "dynalint reported new findings"
assert t["total"] < 60, f"dynalint exceeded the 60s CI budget: {t['total']:.1f}s ({t})"
fam = [e for e in r["baselined"]
       if e["rule"].startswith(("DYN1", "DYN2", "DYN3", "DYN4", "DYN5", "DYN6"))]
assert not fam, f"2.0/3.0-family findings may not be baselined: {fam}"
assert len(r["baselined"]) <= 10, f"baseline debt cap exceeded: {len(r['baselined'])}"
per = ", ".join(f"{k}={v*1e3:.0f}ms" for k, v in sorted(t.items()))
print(f"dynalint: clean in {t['total']:.2f}s ({per})")
PYEOF

echo "== planner sim smoke (closed-loop acceptance, no TPU) =="
env JAX_PLATFORMS=cpu python -m dynamo_tpu.planner sim --smoke

echo "== live-migration suite (exact-stream + drain acceptance) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_migration.py -q -m migration \
  -p no:cacheprovider -p no:xdist -p no:randomly

echo "== tenancy suite (structured output + multi-LoRA correctness gates) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_tenancy.py -q -m tenancy \
  -p no:cacheprovider -p no:xdist -p no:randomly

echo "== chaos suite (hub session resume + watchdog + ladder determinism) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py -q -m chaos \
  -p no:cacheprovider -p no:xdist -p no:randomly

echo "== qos suite (WFQ fairness + priority + brownout determinism) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_qos.py -q -m chaos \
  -p no:cacheprovider -p no:xdist -p no:randomly

echo "== kv-tiering suite (disk tier, tier events, discounted scoring,"
echo "   cross-worker pull exactness) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_kv_tiering.py -q -m tiering \
  -p no:cacheprovider -p no:xdist -p no:randomly

echo "== kv-integrity suite (checksummed blocks on every tier + wire plane:"
echo "   corruption plane matrix, descendant drop, negative cache,"
echo "   byte-identical recompute, donor quarantine) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_kv_integrity.py -q -m integrity \
  -p no:cacheprovider -p no:xdist -p no:randomly

echo "== prefix-reuse smoke (BENCH_PREFIX=1: tiers off/host/disk/pull;"
echo "   bars: >=90% prefill skipped on 2nd occurrence, pull serves a"
echo "   never-computed prefix, byte-identical streams, stable compiles) =="
env JAX_PLATFORMS=cpu BENCH_PREFIX=1 python bench.py > /tmp/_prefix_smoke.json
python - <<'PYEOF'
import json
r = json.loads(open("/tmp/_prefix_smoke.json").read().strip().splitlines()[-1])
assert r["metric"] == "prefix_reuse_skip_frac", r
assert r["identical"] is True, "tiered streams diverged from control"
assert r["compile_stable"] is True, "tier paths compiled after warmup"
assert r["modes"]["host"]["skip_frac"] >= 0.9, r["modes"]["host"]
assert r["modes"]["disk"]["skip_frac"] >= 0.9, r["modes"]["disk"]
assert r["pull_served_blocks"] >= 1, "cross-worker pull never served blocks"
assert r["modes"]["off"]["skip_frac"] < 0.5, (
    "control mode reused prefixes — the smoke lost its eviction pressure")
print(f"prefix smoke ok: skip host={r['modes']['host']['skip_frac']} "
      f"disk={r['modes']['disk']['skip_frac']} "
      f"pull_blocks={r['pull_served_blocks']}")
PYEOF

echo "== fused decode kernel parity (interpret-mode pallas vs XLA oracle"
echo "   on ragged int8/fp32 page tables; ops/decode_attention.py) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_decode_kernel.py -q \
  -k "parity or traced_scale or routed or resolve" \
  -p no:cacheprovider -p no:xdist -p no:randomly

echo "== chunked prefill kernel gate (interpret-mode pallas vs XLA oracle:"
echo "   ragged parity + traced scale + routing/selector + chunk-boundary"
echo "   byte identity, and the dynamo_tpu_prefill_chunk_seconds summary"
echo "   asserted on the /metrics render; ops/prefill_attention.py) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_prefill_kernel.py -q \
  -k "parity or traced_scale or routed or resolve or byte_identity or metric" \
  -p no:cacheprovider -p no:xdist -p no:randomly

echo "== continuous-decode churn smoke (CPU bench: staggered finishes +"
echo "   late arrivals, FUSED decode kernel; bars: fewer rebuilds than"
echo "   forced-rebuild control, exact streams, zero new compiles,"
echo "   pallas_fused actually served the run, dispatch metrics parseable) =="
env JAX_PLATFORMS=cpu DYN_DECODE_KERNEL=pallas_fused BENCH_CHURN=1 \
  python bench.py > /tmp/_churn_smoke.json
python - <<'PYEOF'
import json, math
r = json.loads(open("/tmp/_churn_smoke.json").read().strip().splitlines()[-1])
assert r["metric"] == "continuous_decode_rebuilds", r
assert r["decode_kernel"] == "pallas_fused", (
    f"churn smoke did not run on the fused kernel: {r['decode_kernel']}")
# The hot-path guards: continuous batching must absorb the churn the
# forced-rebuild control drains for, without compiling anything new, and
# the dispatch summary the planner/bench consume must be well-formed.
assert r["rebuilds"]["continuous"] < r["rebuilds"]["forced"], r["rebuilds"]
assert r["compile_counts_stable"] is True, "compile count grew under churn"
assert r["continuous_admissions"] >= 1, "no in-loop admission exercised"
assert r["continuous_retired"] >= 1, "no in-loop retirement exercised"
g = r["host_gap_frac"]
assert isinstance(g, float) and math.isfinite(g) and 0.0 <= g <= 1.0, g
d = r["dispatch"]["decode_dispatch"]
assert d["dispatches"] >= 1 and math.isfinite(d["p99_ms"]), d
print(f"churn smoke ok: kernel={r['decode_kernel']} "
      f"rebuilds {r['rebuilds']} "
      f"admissions={r['continuous_admissions']} "
      f"retired={r['continuous_retired']} host_gap={g}")
PYEOF

echo "== tracing suite (span plane: propagation across disagg/pull/"
echo "   migration, sampling, aggregator, byte-identity + zero-compile"
echo "   overhead contract, /traces endpoints) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_tracing.py -q -m tracing \
  -p no:cacheprovider -p no:xdist -p no:randomly

echo "== bulk data-plane suite (direct worker-to-worker transport: codec"
echo "   framing at chunk boundaries, one-shot ticket lifecycle, resume"
echo "   from verified chunk, A/B byte-identity vs hub path, fallback"
echo "   ladder, hub publish byte counters) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_bulk.py -q -m bulk \
  -p no:cacheprovider -p no:xdist -p no:randomly

echo "== chaos ladder L0-L2 + L5 respawn + L6 overload + L7 corruption"
echo "   storm + L8 shard kill + L9 bulk peer kill + L10 objstore"
echo "   scale-from-zero (seeded goodput smoke; bars: 0 dropped,"
echo "   byte-identity incl. unseeded streams, respawn on L5, non-flooding"
echo "   tenants >= 0.9x isolated on L6, every injected kv_corrupt flip"
echo "   detected before scatter on L7, standby promoted + >=0.85x goodput"
echo "   on L8, bulk resume + hub-path fallback + recovery with"
echo "   byte-identical streams on L9, >=90% warm prefill skip +"
echo "   byte-identity from the durable object tier on L10) =="
env JAX_PLATFORMS=cpu python benchmarks/goodput.py \
  --levels 0,1,2,5,6,7,8,9,10 \
  --seed 7 --duration 5 --rate 2.5 --check --json /tmp/_goodput_smoke.json

echo "== tier-1 tests =="
set -o pipefail
rm -f /tmp/_t1.log
rc=0
# `|| rc=$?` keeps a red test run from tripping `set -e` before the
# pass-count summary below is printed.
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
  2>&1 | tee /tmp/_t1.log || rc=$?
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
