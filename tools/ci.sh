#!/usr/bin/env bash
# CI entrypoint: dynalint gate first (cheap, fails fast), then the tier-1
# pytest command from ROADMAP.md.  Run from anywhere; works from repo root.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== dynalint (async-safety & JAX invariants) =="
python -m tools.dynalint dynamo_tpu --json

echo "== planner sim smoke (closed-loop acceptance, no TPU) =="
env JAX_PLATFORMS=cpu python -m dynamo_tpu.planner sim --smoke

echo "== live-migration suite (exact-stream + drain acceptance) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_migration.py -q -m migration \
  -p no:cacheprovider -p no:xdist -p no:randomly

echo "== tenancy suite (structured output + multi-LoRA correctness gates) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_tenancy.py -q -m tenancy \
  -p no:cacheprovider -p no:xdist -p no:randomly

echo "== chaos suite (hub session resume + watchdog + ladder determinism) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py -q -m chaos \
  -p no:cacheprovider -p no:xdist -p no:randomly

echo "== qos suite (WFQ fairness + priority + brownout determinism) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_qos.py -q -m chaos \
  -p no:cacheprovider -p no:xdist -p no:randomly

echo "== chaos ladder L0-L2 + L5 respawn + L6 overload (seeded goodput"
echo "   smoke; bars: 0 dropped, byte-identity incl. unseeded streams,"
echo "   respawn on L5, non-flooding tenants >= 0.9x isolated on L6) =="
env JAX_PLATFORMS=cpu python benchmarks/goodput.py --levels 0,1,2,5,6 \
  --seed 7 --duration 5 --rate 2.5 --check --json /tmp/_goodput_smoke.json

echo "== tier-1 tests =="
set -o pipefail
rm -f /tmp/_t1.log
rc=0
# `|| rc=$?` keeps a red test run from tripping `set -e` before the
# pass-count summary below is printed.
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
  2>&1 | tee /tmp/_t1.log || rc=$?
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
