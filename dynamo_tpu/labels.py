"""Wire-value hygiene helpers: Prometheus label escaping, credential
hashing, hub-key component validation.

One module, importable from every layer (no dependencies beyond hashlib/re
— the runtime transports and the llm edge both render ``/metrics`` text
and build hub keys, and neither may import the other).  These are the
sanitizers dynalint's DYN2xx taint rules recognize (tools/dynalint
registry.py SANITIZER_TAILS): wire-controlled values — HTTP headers,
``nvext`` fields, the OpenAI ``model`` field, hub-delivered metadata —
must pass through one of them before reaching a label, a log line, or a
hub key.

PR 8 fixed each occurrence ad hoc (hash in ``resolve_tenant``, manual
escaping in ``QosMetrics.render``); this centralizes the policy so every
``/metrics`` family handles labels the same way and the linter can verify
it mechanically.
"""

from __future__ import annotations

import hashlib
import re

# Prometheus exposition label values escape exactly three characters:
# backslash, double-quote, and newline (in that order — the escape
# character must be escaped first).  NOT idempotent: escape exactly ONCE,
# at the final render site, never in helpers that feed a render.
_LABEL_ESCAPES = (("\\", r"\\"), ('"', r"\""), ("\n", r"\n"))

# Hub key path components: conservative DNS-1123-adjacent charset.  No
# separators — a component must not be able to escape its prefix — and no
# whitespace/control characters that would corrupt line-oriented dumps.
_KEY_COMPONENT_RE = re.compile(r"^[A-Za-z0-9]([A-Za-z0-9._-]{0,253})$")


def escape_label(value: object) -> str:
    """Prometheus-escape a label value (any type; always returns str).

    For clean strings it is the identity, so internal values pass through
    unharmed; the project rule is: EVERY interpolated label value goes
    through here exactly ONCE, at the render site (dynalint DYN204
    enforces presence; double-wrapping a pre-escaped value corrupts it —
    helpers should hand RAW values to the render)."""
    out = str(value)
    for raw, esc in _LABEL_ESCAPES:
        out = out.replace(raw, esc)
    return out


def hash_credential(secret: str, prefix: str = "key") -> str:
    """Stable non-secret identity for a credential: ``key:<sha256[:12]>``.

    Raw API keys / bearer tokens must never become tenant strings — tenant
    ids reach logs, ``/metrics`` labels and scheduler annotations, none of
    which may carry a secret.  The digest keys quota buckets and fairness
    flows just as well, and 12 hex chars keep collision odds negligible at
    fleet scale (2^48)."""
    return f"{prefix}:{hashlib.sha256(secret.encode()).hexdigest()[:12]}"


def bounded_label(value: str) -> str:
    """Identity marker: the caller has JUST verified ``value`` against a
    closed server-side set (e.g. the served-model registry), so it is not
    a cardinality hazard.  No escaping happens here on purpose — this is
    for ``prometheus_client`` ``.labels(...)`` sinks, where the client
    library escapes at exposition and pre-escaping would double-escape
    AND split the series against raw-labeled paths.  Registered as a
    dynalint sanitizer: the call is the auditable claim of boundedness;
    use ``escape_label`` instead for hand-rendered exposition text."""
    return value


def safe_key_component(value: str) -> str:
    """Validate a wire-controlled string for use as ONE hub-key path
    component.  Returns the value unchanged or raises ``ValueError`` —
    callers map the error to their 400/reject path.

    Hub keys are a shared namespace (``instances/…``, ``planner/…``,
    ``health/quarantine/…``); a crafted id containing ``/`` or whitespace
    could escape its prefix and shadow another subsystem's keys."""
    if not isinstance(value, str) or not _KEY_COMPONENT_RE.match(value):
        raise ValueError(
            f"invalid key component {value!r}: must match "
            "[A-Za-z0-9][A-Za-z0-9._-]{0,253}"
        )
    return value
