"""CLI launcher — reference: launch/dynamo-run (``dynamo-run in=… out=…``),
components/http (standalone frontend), plus the hub (docker-compose
etcd+NATS replacement).

Usage:
  python -m dynamo_tpu.cli hub  [--host H] [--port P]
  python -m dynamo_tpu.cli run  in=http out=echocore [--port 8000] [--model echo]
  python -m dynamo_tpu.cli run  in=text out=tpu --checkpoint DIR    # chat REPL
  python -m dynamo_tpu.cli run  in=stdin out=tpu ...                # one prompt
  python -m dynamo_tpu.cli run  in=batch:FILE.jsonl out=tpu ...     # batch eval
  python -m dynamo_tpu.cli run  in=dyn://ns.comp.ep out=echocore --hub HOST:PORT \
        [--model NAME]            # worker: serve engine at endpoint + register model
  python -m dynamo_tpu.cli http --hub HOST:PORT [--port 8000]   # discovery frontend
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import signal
import sys
from typing import Optional

from .llm.backend import Backend
from .llm.discovery import ModelWatcher, make_tokenizer, register_model
from .llm.engines import EchoEngineCore, EchoEngineFull
from .llm.http_service import HttpService
from .llm.preprocessor import OpenAIPreprocessor
from .runtime.component import DistributedRuntime, parse_endpoint_path
from .runtime.config import RuntimeConfig
from .runtime.pipeline import build_pipeline
from .runtime.transports.hub import HubServer

logger = logging.getLogger(__name__)


def _build_engine(out: str, args):
    """out= engine factory.  TPU JAX engine registers here as out=tpu."""
    if out == "echocore":
        return EchoEngineCore(), "core"
    if out == "echofull":
        return EchoEngineFull(), "full"
    if out == "tpu":
        from .engine import build_tpu_engine  # deferred: imports jax

        return build_tpu_engine(args), "core"
    raise SystemExit(f"unknown out= engine: {out!r}")


def _tokenizer_spec(args) -> dict:
    tok = getattr(args, "tokenizer", None)
    if tok:
        if tok.endswith(".gguf"):
            return {"kind": "gguf", "file": tok}
        if tok.endswith(".model"):
            # Explicit sentencepiece file (the pre-r5 error message pointed
            # sp-only checkpoints at --tokenizer).
            return {"kind": "sp", "file": tok}
        if os.path.isdir(tok):
            return {"kind": "hf", "dir": tok}
        return {"kind": "hf", "file": tok}
    ckpt = getattr(args, "checkpoint", None)
    if ckpt:
        # build_tpu_engine resolved the checkpoint spec to a local path;
        # serve its own tokenizer + chat template when it ships one.  The
        # ORIGINAL spec rides along so a frontend on another host (which
        # cannot see this worker's filesystem) can re-resolve it.
        from .models.hub import tokenizer_spec

        spec = tokenizer_spec(ckpt)
        if spec is not None:
            source = getattr(args, "checkpoint_source", None)
            if source:
                spec["source"] = source
            return spec
    return {"kind": "byte"}


async def _run_hub(args) -> None:
    server = await HubServer(
        host=args.host, port=args.port, persist_path=args.persist
    ).start()
    print(f"hub listening on {server.address}", flush=True)
    await _wait_forever()


def _edge_tracing():
    """Edge-side tracing surfaces (runtime/tracing.py, docs/tracing.md):
    the TraceSampler (head + forced + tail-keep sampling decisions) and a
    TraceAggregator serving /traces.  Returns (sampler, aggregator, cfg)
    — (None, None, cfg) when the ``tracing`` config section disables the
    plane, which removes every per-request cost at the edge."""
    from .llm.trace_service import TraceAggregator
    from .runtime.tracing import TraceSampler, TracingConfig

    cfg = TracingConfig.from_config(RuntimeConfig.from_layers().tracing)
    if not cfg.enabled:
        return None, None, cfg
    return TraceSampler(cfg), TraceAggregator(ttl_s=cfg.ttl_s), cfg


def _edge_qos(args):
    """QosController for the HTTP edge from the layered ``qos`` config
    section under explicit --qos-*/--brownout flags (llm/qos.py).  Returns
    None when neither quotas nor the brownout ladder are enabled — zero
    behaviour change by default."""
    from .llm.qos import QosConfig, QosController

    section = dict(RuntimeConfig.from_layers().qos)
    for key in ("tenant_weights", "default_weight", "batch_every"):
        section.pop(key, None)  # scheduler half (engine/__init__.py)
    if getattr(args, "qos_rate", None) is not None:
        section["rate"] = args.qos_rate
    if getattr(args, "qos_burst", None) is not None:
        section["burst"] = args.qos_burst
    if getattr(args, "brownout", False) and not section.get("brownout"):
        # The explicit flag wins over an absent/disabled config value, but
        # a configured brownout DICT (custom thresholds) is kept as-is.
        section["brownout"] = True
    cfg = QosConfig.from_dict(section)
    if cfg.rate is None and cfg.brownout is None:
        return None
    return QosController(cfg)


async def _run_http_frontend(args) -> None:
    from .runtime.client import RouterMode

    runtime = await DistributedRuntime.connect(args.hub)
    # CLI flags win over the layered config's `resilience` section
    # (DYN_RESILIENCE__HTTP_MAX_INFLIGHT=64 etc.), which wins over defaults.
    res = RuntimeConfig.from_layers().resilience
    raw_inflight = res.get("http_max_inflight")
    qos_ctl = _edge_qos(args)
    sampler, aggregator, tracing_cfg = _edge_tracing()
    service = HttpService(
        host=args.host,
        port=args.port,
        max_inflight=(
            args.max_inflight
            if args.max_inflight is not None
            else int(raw_inflight) if raw_inflight else None
        ),
        admission_queue=(
            args.admission_queue
            if args.admission_queue
            else int(res.get("http_admission_queue", 0))
        ),
        admission_timeout_s=(
            args.admission_timeout_s
            if args.admission_timeout_s != 1.0
            else float(res.get("http_admission_timeout_s", 1.0))
        ),
        default_deadline_s=(
            args.deadline_s
            if args.deadline_s is not None
            else res.get("request_deadline_s")
        ),
        qos=qos_ctl,
        tracing=sampler,
        trace_aggregator=aggregator,
        hub=runtime.hub,
    )
    mode = RouterMode(getattr(args, "router", "round_robin"))
    watcher = await ModelWatcher(runtime, service.models, router_mode=mode).start()
    await service.start()
    # Publish the edge's rolling TTFT/ITL percentiles on the namespace's
    # slo_metrics subject — the planner's SLO input (planner/signals.py).
    from .planner.signals import EdgeSloPublisher

    ns = RuntimeConfig.from_layers().namespace
    slo_pub = await EdgeSloPublisher(
        runtime.namespace(ns), service.metrics, qos=qos_ctl
    ).start()
    exporter = None
    bulk_ingest = None
    if aggregator is not None:
        # Span plane (docs/tracing.md): workers publish span batches on the
        # namespace's ``traces`` subject — the aggregator subscribes and
        # assembles them with the edge's own spans (client.route, the
        # edge.request root), which export straight into it in-process.
        from .runtime.tracing import SpanExporter

        await aggregator.start(runtime.namespace(ns))
        from .runtime.transports.bulk import bulk_enabled

        if bulk_enabled():
            # Bulk span ingest (docs/bulk_plane.md): worker exporters push
            # batches straight here instead of fanning through the hub's
            # pub/sub plane; the subscription above stays live as the
            # fallback path (and the A/B oracle).
            from .llm.trace_service import start_bulk_ingest

            bulk_ingest = await start_bulk_ingest(aggregator, runtime)
        exporter = await SpanExporter(
            [aggregator],
            interval_s=tracing_cfg.export_interval_s,
            proc="edge",
        ).start()
    print(f"OpenAI frontend on http://{service.host}:{service.port}", flush=True)
    try:
        await _wait_forever()
    finally:
        if exporter is not None:
            await exporter.stop()
        if bulk_ingest is not None:
            await bulk_ingest.close()
        if aggregator is not None:
            await aggregator.stop()
        await slo_pub.stop()
        await watcher.stop()
        await service.close()
        await runtime.close()


async def _run(args) -> None:
    inp = args.inp
    engine, level = _build_engine(args.out, args)
    tokenizer = make_tokenizer(_tokenizer_spec(args))

    # Multi-host: followers only replay the leader's dispatch stream; the
    # leader broadcasts every dispatch before enqueueing its own.
    nnodes = getattr(args, "nnodes", 1)
    if nnodes > 1:
        from .engine.multihost import StepPublisher, follower_serve

        if not hasattr(engine, "mirror_step"):
            raise SystemExit("--nnodes > 1 requires out=tpu")
        if getattr(args, "node_rank", 0) > 0:
            leader_host = args.coordinator.rsplit(":", 1)[0]
            print(
                f"follower node {args.node_rank}/{nnodes} replaying "
                f"{leader_host}:{args.step_port}",
                flush=True,
            )
            await follower_serve(engine, f"{leader_host}:{args.step_port}")
            return
        # Bind to the coordinator's interface, not 0.0.0.0: the step plane
        # carries pickled frames, so exposure must stay inside the
        # deployment's trust domain (plus DYN_STEP_TOKEN auth — multihost.py).
        # The advertised coordinator name may not be locally bindable (VIP /
        # NAT / port-forward); fall back to 0.0.0.0 then — auth still holds.
        # OSError: the name isn't locally bindable (VIP/NAT).  TimeoutError:
        # it bound, but to an interface followers can't reach (e.g. a
        # 127.0.1.1 /etc/hosts alias) — followers keep retrying for 120s
        # (follower_serve), so the 0.0.0.0 retry still catches them.
        step_host = args.coordinator.rsplit(":", 1)[0] if args.coordinator else "0.0.0.0"
        first = StepPublisher(step_host, args.step_port, nnodes - 1)
        try:
            publisher = await first.start(timeout=60.0)
        except (OSError, asyncio.TimeoutError):
            # abort, not close: a 'close' broadcast would make any
            # already-connected follower exit permanently instead of
            # reconnecting to the rebound publisher.
            await first.abort()
            # NB: with no DYN_STEP_TOKEN this wildcard rebind refuses to
            # start (StepPublisher.start) — the fallback is only available
            # to authenticated deployments.
            print(
                f"step plane: cannot serve followers on {step_host}, "
                "falling back to 0.0.0.0 (firewall the port; requires "
                "DYN_STEP_TOKEN)",
                flush=True,
            )
            publisher = await StepPublisher(
                "0.0.0.0", args.step_port, nnodes - 1
            ).start()
        engine.attach_publisher(publisher)

    if getattr(args, "record", None):
        # Tap every request/response stream to JSONL (reference:
        # recorder.rs) — replayable via runtime.recorder.replay_into.
        # Wrapped HERE so every input mode records (in=http included).
        from .runtime.recorder import RecordingEngine, StreamRecorder

        recorder = StreamRecorder(args.record)
        engine = RecordingEngine(engine, recorder)
        print(f"recording streams to {args.record}", flush=True)

    # One grammar compile cache for EVERY core-level pipeline on this
    # tokenizer (base model and adapter aliases alike): constraint →
    # automaton indexing is the expensive step (llm/tenancy/grammar.py),
    # and per-pipeline caches would recompile the same schema per name.
    grammar_compiler = None
    if level == "core":
        from .llm.tenancy.grammar import GrammarCompiler

        grammar_compiler = GrammarCompiler(tokenizer)

    def _console_pipeline():
        if level == "core":
            return build_pipeline(
                [
                    OpenAIPreprocessor(
                        tokenizer, args.model,
                        grammar_compiler=grammar_compiler,
                    ),
                    Backend(tokenizer),
                ],
                engine,
            )
        return engine

    if inp == "http":
        # Colocated engine: feed its live KV usage to the brownout ladder.
        kv_usage_fn = (
            (lambda: engine.metrics().gpu_cache_usage_perc)
            if hasattr(engine, "metrics")
            else None
        )
        # ... and its decode-dispatch health to /metrics
        # (dynamo_tpu_engine_dispatch_*; llm/metrics.py).
        if hasattr(engine, "dispatch_summary"):
            from .llm.metrics import engine_dispatch_metrics

            engine_dispatch_metrics.set_source(engine.dispatch_summary)
        # ... and its KV tier gauges (dynamo_tpu_kv_tier_*; also rides the
        # edge SLO publication as the fleet prefix-hit-rate signal).
        if hasattr(engine, "kv_tier_summary"):
            from .llm.metrics import kv_tier_metrics

            kv_tier_metrics.set_source(engine.kv_tier_summary)
        # Colocated tracing (docs/tracing.md): edge and engine share this
        # process, so the exporter feeds the aggregator directly — no hub
        # hop; /traces serves assembled timelines immediately.
        sampler, aggregator, _tcfg = _edge_tracing()
        exporter = None
        if aggregator is not None:
            from .runtime.tracing import SpanExporter

            exporter = await SpanExporter(
                [aggregator], interval_s=_tcfg.export_interval_s
            ).start()
        service = HttpService(
            host=args.host, port=args.port,
            qos=_edge_qos(args), kv_usage_fn=kv_usage_fn,
            tracing=sampler, trace_aggregator=aggregator,
        )
        pipeline = _console_pipeline()
        service.models.add_chat_model(args.model, pipeline)
        service.models.add_completion_model(args.model, pipeline)
        # LoRA adapters (llm/tenancy) serve as additional MODEL NAMES on
        # the same resident engine: each gets its own preprocessor that
        # stamps the adapter id + KV salt (one grammar compile cache shared
        # across all of them — same tokenizer).
        adapters = (
            engine.adapter_names() if hasattr(engine, "adapter_names") else []
        )
        if adapters and level == "core":
            for name in adapters:
                apipe = build_pipeline(
                    [
                        OpenAIPreprocessor(
                            tokenizer, name, adapter=name,
                            grammar_compiler=grammar_compiler,
                        ),
                        Backend(tokenizer),
                    ],
                    engine,
                )
                service.models.add_chat_model(name, apipe)
                service.models.add_completion_model(name, apipe)
        print(
            f"serving {args.model!r}"
            + (f" + adapters {adapters}" if adapters else "")
            + f" on http://{args.host}:{args.port}",
            flush=True,
        )
        try:
            await service.run()
        finally:
            if exporter is not None:
                await exporter.stop()
    elif inp == "none":
        # Start the engine with no input surface (reference Input::None,
        # opt.rs:40-43: externally-coordinated deployments — here, e.g., a
        # warm spare or a follower-style process someone attaches to later).
        print(f"engine up (in=none), model {args.model!r}; ctrl-C to exit", flush=True)
        try:
            await _wait_forever()
        finally:
            close = getattr(engine, "close", None)
            if close is not None:
                await close()
    elif inp in ("text", "stdin") or inp.startswith("batch:"):
        # Console modes (reference: dynamo-run in=text|stdin|batch:FILE,
        # launch/dynamo-run/src/opt.rs:23-38) — same pipeline as in=http.
        from .llm.console import run_batch, run_stdin_prompt, run_text_chat

        pipeline = _console_pipeline()
        try:
            if inp == "text":
                await run_text_chat(pipeline, args.model, args)
            elif inp == "stdin":
                await run_stdin_prompt(pipeline, args.model, args)
            else:
                await run_batch(
                    pipeline, args.model, inp[len("batch:"):], args
                )
        finally:
            close = getattr(engine, "close", None)
            if close is not None:
                await close()
    elif inp.startswith("dyn://"):
        if not args.hub:
            raise SystemExit("worker mode requires --hub HOST:PORT")
        role = getattr(args, "disagg", None)
        if role and not hasattr(engine, "inject_blocks"):
            raise SystemExit(
                f"--disagg {role} requires the native TPU engine (out=tpu), "
                f"not out={args.out}"
            )
        runtime = await DistributedRuntime.connect(args.hub)
        ns, comp, ep = parse_endpoint_path(inp)
        endpoint = runtime.namespace(ns).component(comp).endpoint(ep)
        # Span plane (docs/tracing.md): ONE exporter per worker process —
        # the process-global collector holds every role's spans (engine
        # queue/prefill/decode, disagg, migration, kv donor), and batches
        # publish on the namespace's ``traces`` subject for the edge-side
        # aggregator.  Nothing to drain when tracing is disabled or no
        # request is sampled; the hub client re-arms publishes across hub
        # restarts like every other publisher.
        from .runtime.tracing import TRACES_TOPIC, SpanExporter, TracingConfig

        trace_exporter = None
        tcfg = TracingConfig.from_config(RuntimeConfig.from_layers().tracing)
        if tcfg.enabled:
            # Honor ``tracing.ring`` here too: workers are the span-heaviest
            # processes (decode chunks), and only the edge's TraceSampler
            # otherwise applies the capacity.
            from .runtime.tracing import collector as trace_collector

            if tcfg.ring != trace_collector._ring.maxlen:
                trace_collector.set_capacity(tcfg.ring)
            namespace = runtime.namespace(ns)

            async def _publish_spans(payload):
                await namespace.publish(TRACES_TOPIC, payload)

            span_sink = _publish_spans
            from .runtime.transports.bulk import BulkRendezvous, bulk_enabled

            if bulk_enabled():
                # Bulk span export (docs/bulk_plane.md): batches push
                # directly to the edge aggregator's bulk sink; the hub
                # publish above stays wired as the fallback rung.
                from .llm.trace_service import make_bulk_span_sink

                span_sink = make_bulk_span_sink(
                    BulkRendezvous(runtime.hub, lease=runtime.primary_lease),
                    _publish_spans,
                )
            trace_exporter = await SpanExporter(
                [span_sink],
                interval_s=tcfg.export_interval_s,
                proc=f"worker-{runtime.worker_id}",
            ).start()
        roles = WorkerRoles(args, runtime, endpoint, engine, _tokenizer_spec(args))
        if role == "prefill":
            await roles.start_prefill()
        else:
            await roles.start_decode(disagg=role == "decode")
        flipper = None
        if role in ("decode", "prefill"):
            # Planner role flips (planner/actuate.py LocalActuator →
            # planner/roles/{worker_id}) work BOTH directions on the same
            # resident engine: decode→prefill migrates live sequences out
            # then starts a queue-drain loop; prefill→decode finishes the
            # in-flight queue item then brings up the full decode surface
            # (kv_import endpoint included).
            from .planner.actuate import RoleFlipWatcher

            async def _switch_decode() -> None:
                await roles.start_decode(disagg=True)

            flipper = await RoleFlipWatcher(
                runtime.hub,
                runtime.worker_id,
                role,
                drain={
                    "decode": roles.stop_decode,
                    "prefill": roles.stop_prefill,
                },
                switch={
                    "prefill": roles.start_prefill,
                    "decode": _switch_decode,
                },
            ).start()
        print(
            f"worker serving {inp} (model {args.model!r}"
            + (f", disagg={role}" if role else "")
            + ")",
            flush=True,
        )
        try:
            await _wait_forever()
        finally:
            if flipper is not None:
                await flipper.stop()
            await roles.shutdown()
            if trace_exporter is not None:
                # Final flush ships the last spans before the hub client
                # closes (best-effort: a dead hub just counts an error).
                await trace_exporter.stop()
            await runtime.close()
    else:
        raise SystemExit(f"unknown in= input: {inp!r}")


class WorkerRoles:
    """Role lifecycle for one dyn:// worker: start/stop the decode and
    prefill roles on a single resident engine (weights never reload across
    flips).  The decode role's stop hook drains via LIVE MIGRATION first
    (llm/migration): sequences move to a peer in O(KV transfer) instead of
    being waited out in O(sequence length), which is what makes planner
    scale-down/flip actuation cheap."""

    def __init__(self, args, runtime, endpoint, engine, tokenizer_spec):
        self.args = args
        self.runtime = runtime
        self.endpoint = endpoint
        self.engine = engine
        self.tokenizer_spec = tokenizer_spec
        self._handles: dict = {}
        # The decode role's MigratableWorker (None while in prefill role).
        self.migratable = None

    # -- decode role --------------------------------------------------------

    async def start_decode(self, disagg: bool) -> None:
        args, runtime, endpoint, engine = (
            self.args, self.runtime, self.endpoint, self.engine,
        )
        h: dict = {"serveds": []}
        served_engine = engine
        metadata: dict = {"role": "decode"} if disagg else {}
        if disagg:
            from .llm.disagg import (
                KV_IMPORT_ENDPOINT,
                DisaggConfig,
                DisaggDecodeWorker,
                DisaggregatedRouter,
                PrefillQueue,
            )

            server = await runtime.service_server()
            import_ep = endpoint.component.endpoint(KV_IMPORT_ENDPOINT)
            disagg_router = await DisaggregatedRouter(
                args.model,
                DisaggConfig(
                    max_local_prefill_length=args.max_local_prefill,
                ),
            ).watch_config(runtime.hub)
            h["router"] = disagg_router
            worker = DisaggDecodeWorker(
                engine,
                PrefillQueue(runtime.hub, args.model),
                disagg_router,
                import_address=server.address,
                import_path=import_ep.path,
            )
            h["serveds"].append(
                await import_ep.serve_endpoint(worker.kv_import_handler)
            )
            stats_ep = endpoint.component.endpoint("disagg_stats")
            h["serveds"].append(
                await stats_ep.serve_endpoint(worker.stats_handler)
            )
            h["disagg"] = worker
            served_engine = worker
        if hasattr(engine, "inject_blocks"):  # native TPU engine
            # Live-migration surface: peers (and the planner's drain path)
            # move running sequences here preemption-free.  The instance
            # metadata advertises the capability so target discovery
            # (llm/migration/coordinator.py) finds this worker.
            from .llm.migration import (
                MIGRATE_IN_ENDPOINT,
                MIGRATE_OUT_ENDPOINT,
                MigratableWorker,
            )

            mig = MigratableWorker(engine, serve=served_engine)
            mig_in = endpoint.component.endpoint(MIGRATE_IN_ENDPOINT)
            mig_out = endpoint.component.endpoint(MIGRATE_OUT_ENDPOINT)
            h["serveds"].append(
                await mig_in.serve_endpoint(mig.migrate_in_handler)
            )
            h["serveds"].append(
                await mig_out.serve_endpoint(mig.migrate_out_handler)
            )
            metadata["migrate"] = {
                "import_path": mig_in.path,
                "out_path": mig_out.path,
                "generate_path": endpoint.path,
            }
            served_engine = mig
            h["mig"] = mig
            self.migratable = mig
        h["serveds"].append(
            await endpoint.serve_endpoint(
                served_engine, metadata=metadata or None
            )
        )
        h["metadata"] = metadata
        kv_block_size = 16
        if hasattr(engine, "set_event_callback"):  # native TPU engine
            from .llm.kv_router.publisher import (
                KvEventPublisher,
                KvMetricsPublisher,
            )

            kv_block_size = engine.cfg.block_size
            engine.set_event_callback(
                KvEventPublisher(endpoint.component, runtime.worker_id)
            )
            h["metrics_pub"] = await KvMetricsPublisher(
                endpoint.component, runtime.worker_id, engine.metrics
            ).start()
            # Fleet-wide prefix reuse (docs/kv_tiering.md): serve this
            # worker's sealed blocks to peers at kv_export, pull a deeper
            # peer prefix at admission (router-stamped kv_pull hints), and
            # — when the disk tier is on — consume the router's
            # kv_prefetch plane to warm predicted prefixes disk→host.
            from .llm.kv_router.pull import (
                KV_EXPORT_ENDPOINT,
                KvPrefetchConsumer,
                PrefixPuller,
                make_client_exporter,
                make_kv_export_handler,
            )

            export_ep = endpoint.component.endpoint(KV_EXPORT_ENDPOINT)
            h["serveds"].append(
                await export_ep.serve_endpoint(make_kv_export_handler(engine))
            )
            pull_client = await export_ep.client()
            h["pull_client"] = pull_client
            engine.set_prefix_puller(
                PrefixPuller(engine, make_client_exporter(pull_client))
            )
            # KV integrity self-reporting (docs/kv_tiering.md §integrity):
            # this worker's OWN disk/host corruption detections feed the
            # watchdog's ledger under its worker id — a sick local medium
            # earns the same quarantine path as a donor shipping poison.
            from .runtime.health import kv_corruption

            wid = runtime.worker_id
            engine.set_integrity_reporter(
                lambda plane, _wid=wid: kv_corruption.record(_wid)
            )
            if getattr(engine, "disk_kv", None) is not None:
                h["prefetch"] = await KvPrefetchConsumer(
                    endpoint.component, engine
                ).start()
            from .llm.metrics import kv_tier_metrics

            kv_tier_metrics.set_source(engine.kv_tier_summary)
        from .runtime.transports.bulk import bulk_enabled

        if bulk_enabled() and hasattr(engine, "inject_blocks"):
            # Bulk data plane (docs/bulk_plane.md, DYN_BULK_PLANE): run this
            # worker's peer-to-peer stream server, register its address for
            # hub rendezvous, and repoint the bulk producers (prefix pull
            # exporter, migration copy stream) at it.  Every producer keeps
            # its hub-path transport wired underneath as the fallback rung,
            # so a dead bulk peer costs a fallback tick, never a stream.
            from .llm.kv_router.pull import (
                KV_EXPORT_ENDPOINT,
                PrefixPuller,
                make_bulk_export_source,
                make_bulk_exporter,
                make_client_exporter,
            )
            from .runtime.transports.bulk import (
                BulkRendezvous,
                BulkServer,
                bulk_addr_key,
            )

            bulk_srv = BulkServer(
                getattr(runtime, "_host", "127.0.0.1"),
                worker_id=runtime.worker_id,
                hub=runtime.hub,
            )
            bulk_srv.register_source(
                KV_EXPORT_ENDPOINT, make_bulk_export_source(engine)
            )
            if h.get("mig") is not None:
                from .llm.migration import MIGRATE_IN_ENDPOINT
                from .llm.migration.worker import make_migrate_in_sink

                bulk_srv.register_sink(
                    MIGRATE_IN_ENDPOINT, make_migrate_in_sink(h["mig"])
                )
            await bulk_srv.start()
            await runtime.register_key(
                bulk_addr_key(runtime.worker_id),
                {
                    "address": bulk_srv.address,
                    "worker_id": str(runtime.worker_id),
                },
            )
            rendezvous = BulkRendezvous(
                runtime.hub, lease=runtime.primary_lease
            )
            if h.get("mig") is not None:
                h["mig"].bulk = rendezvous
            if h.get("pull_client") is not None and hasattr(
                engine, "set_prefix_puller"
            ):
                engine.set_prefix_puller(
                    PrefixPuller(
                        engine,
                        make_bulk_exporter(
                            rendezvous,
                            make_client_exporter(h["pull_client"]),
                            max_bytes=engine.cfg.kv_pull_max_bytes,
                        ),
                    )
                )
            h["bulk_srv"] = bulk_srv
        await register_model(
            runtime,
            args.model,
            endpoint.path,
            tokenizer=self.tokenizer_spec,
            kv_block_size=kv_block_size,
        )
        # LoRA adapters (llm/tenancy) register as additional model names on
        # the SAME endpoint: the frontend's watcher builds adapter-stamping
        # pipelines for them, tenant KV salting keeps router overlap exact,
        # and the engine's served-model allowlist 404s anything else.
        for adapter in (
            engine.adapter_names() if hasattr(engine, "adapter_names") else []
        ):
            await register_model(
                runtime,
                adapter,
                endpoint.path,
                tokenizer=self.tokenizer_spec,
                kv_block_size=kv_block_size,
                lora={"adapter": adapter, "base": args.model},
            )
        self._handles["decode"] = h

    async def stop_decode(self) -> None:
        h = self._handles.pop("decode", None)
        if h is None:
            return
        if h.get("mig") is not None:
            # Close the drain race at BOTH ends.  (1) Accept-time gate:
            # refuse migrate-in from here on — even a peer holding a stale
            # hub snapshot that still advertises us gets refused when its
            # push arrives, so mutual drains are impossible regardless of
            # metadata propagation timing.  (2) De-advertise the migrate
            # capability so fresh target discovery stops picking us.
            from .llm.migration import drain_via_migration

            h["mig"].stop_accepting()
            try:
                md = {
                    k: v
                    for k, v in (h.get("metadata") or {}).items()
                    if k != "migrate"
                }
                await self.endpoint.update_metadata(md)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — best effort; drain anyway
                logger.warning("could not de-advertise migrate capability",
                               exc_info=True)
            # Drain via migration: live sequences hand off to a peer in
            # O(transfer); anything that could not move (no peer, rollback)
            # simply keeps decoding here until it finishes.
            await drain_via_migration(
                h["mig"],
                self.runtime.hub,
                self.endpoint.instance_prefix,
                self.runtime.worker_id,
            )
        if h.get("disagg") is not None:
            await h["disagg"].drain(timeout=10.0)
        for served in reversed(h["serveds"]):
            await served.stop()
        if h.get("bulk_srv") is not None:
            # De-advertise BEFORE closing so a rendezvous racing the close
            # resolves to nothing (a caller falls back) instead of dialing
            # a dead address until its resume budget runs out.
            from .runtime.transports.bulk import bulk_addr_key

            await self.runtime.unregister_key(
                bulk_addr_key(self.runtime.worker_id)
            )
            await h["bulk_srv"].close()
        if h.get("prefetch") is not None:
            await h["prefetch"].stop()
        if hasattr(self.engine, "set_prefix_puller"):
            self.engine.set_prefix_puller(None)
        if hasattr(self.engine, "set_integrity_reporter"):
            self.engine.set_integrity_reporter(None)
        if h.get("pull_client") is not None:
            await h["pull_client"].close()
        if h.get("metrics_pub") is not None:
            await h["metrics_pub"].stop()
        if h.get("router") is not None:
            await h["router"].stop()
        await self.runtime.unregister_key(
            f"models/{self.args.model}/{self.runtime.worker_id}"
        )
        for adapter in (
            self.engine.adapter_names()
            if hasattr(self.engine, "adapter_names")
            else []
        ):
            await self.runtime.unregister_key(
                f"models/{adapter}/{self.runtime.worker_id}"
            )
        self.migratable = None

    # -- prefill role -------------------------------------------------------

    async def start_prefill(self) -> None:
        # Dedicated prefill worker: drains the queue; serves no endpoint.
        # It still registers a lease-bound heartbeat under its endpoint
        # path (metadata role=prefill) so the planner's SignalCollector
        # sees prefill-pool membership and its death is observable —
        # nothing routes to this path.
        from .llm.disagg import PrefillQueue, PrefillWorkerLoop

        ploop = await PrefillWorkerLoop(
            self.engine, PrefillQueue(self.runtime.hub, self.args.model)
        ).start()
        await self.runtime.register_key(
            self.endpoint.instance_key(self.runtime.worker_id),
            {
                "address": "",
                "path": self.endpoint.path,
                "worker_id": self.runtime.worker_id,
                "metadata": {"role": "prefill"},
            },
        )
        self._handles["prefill"] = {"ploop": ploop}

    async def stop_prefill(self) -> None:
        h = self._handles.pop("prefill", None)
        if h is None:
            return
        # Finish the in-flight queue item (bounded), then stop pulling;
        # a cancel that lands mid-dequeue requeues at-least-once.
        await h["ploop"].drain(timeout=10.0)
        await self.runtime.unregister_key(
            self.endpoint.instance_key(self.runtime.worker_id)
        )

    async def shutdown(self) -> None:
        await self.stop_decode()
        await self.stop_prefill()


async def _run_model_cmd(args) -> None:
    """llmctl equivalent (reference: launch/llmctl/src/main.rs:26-124)."""
    from .llm.discovery import MODEL_PREFIX, model_prefix

    runtime = await DistributedRuntime.connect(args.hub)
    try:
        if args.verb == "add":
            key = await register_model(
                runtime,
                args.name,
                args.endpoint,
                model_type=args.type,
                tokenizer={"kind": "hf", "file": args.tokenizer}
                if args.tokenizer
                else {"kind": "byte"},
                kv_block_size=args.block_size,
                static=True,
            )
            print(f"registered {args.name} -> {args.endpoint} ({key})")
        elif args.verb == "list":
            kvs = await runtime.hub.kv_get_prefix(MODEL_PREFIX)
            for key, entry in sorted(kvs.items()):
                print(f"{entry['name']}\t{entry['model_type']}\t{entry['endpoint']}")
            if not kvs:
                print("(no models registered)")
        elif args.verb == "remove":
            kvs = await runtime.hub.kv_get_prefix(model_prefix(args.name))
            for key in kvs:
                await runtime.hub.kv_delete(key)
            print(f"removed {len(kvs)} registration(s) for {args.name}")
    finally:
        await runtime.close()


async def _run_metrics(args) -> None:
    """Namespace metrics aggregator (reference: components/metrics)."""
    from .llm.metrics_service import MetricsAggregatorService

    runtime = await DistributedRuntime.connect(args.hub)
    component = runtime.namespace(args.namespace).component(args.component)
    service = await MetricsAggregatorService(
        component, host=args.host, port=args.port
    ).start()
    print(f"metrics aggregator on http://{args.host}:{args.port}/metrics", flush=True)
    try:
        await _wait_forever()
    finally:
        await service.stop()
        await runtime.close()


async def _run_mock_worker(args) -> None:
    """Synthetic metrics/KV-event publisher (reference: mock_worker.rs)."""
    from .llm.metrics_service import MockWorker

    runtime = await DistributedRuntime.connect(args.hub)
    component = runtime.namespace(args.namespace).component(args.component)
    worker = await MockWorker(
        component, runtime.worker_id, interval=args.interval
    ).start()
    print(f"mock worker {runtime.worker_id} publishing", flush=True)
    try:
        await _wait_forever()
    finally:
        await worker.stop()
        await runtime.close()


async def _run_operator(args) -> None:
    """In-cluster reconcile loop (reference: the Go operator binary) —
    drives BOTH CRDs: deployments and model caches (the reference's
    dynamonimdeployment + dynamonimrequest controller pair)."""
    from .deploy.controller import KubeApi, Reconciler
    from .deploy.model_cache import ModelCacheReconciler

    kube = KubeApi(namespace=args.namespace, base=args.api_server)
    print(
        f"operator reconciling {args.namespace}/dynamotpudeployments "
        f"+ dynamotpumodelcaches (watch-triggered, {args.poll_interval}s "
        f"resync)",
        flush=True,
    )
    try:
        # Both controllers run watch-triggered with periodic resync; a
        # failing watch degrades each to pure polling independently.
        await asyncio.gather(
            Reconciler(kube).run(poll_interval=args.poll_interval),
            ModelCacheReconciler(kube).run(poll_interval=args.poll_interval),
        )
    finally:
        await kube.close()


def _run_prepare(args) -> None:
    """Pre-stage a checkpoint into the model cache (the model-cache Job's
    entrypoint; also useful interactively for offline deployments)."""
    import shutil

    if args.cache:
        os.environ["DYN_MODEL_CACHE"] = args.cache
    from .models.hub import ALIASES, cache_dir, resolve_model

    path = resolve_model(args.model, revision=args.revision)
    # A remote spec resolves into huggingface_hub's OWN cache (ephemeral in
    # a fetch pod) — copy the serving artifacts into DYN_MODEL_CACHE so the
    # PVC actually holds them (the entire point of the fetch Job).
    spec_local = os.path.isdir(args.model) or args.model.endswith(".gguf")
    cd = os.path.abspath(cache_dir())
    if not spec_local and not os.path.abspath(path).startswith(cd + os.sep):
        repo = ALIASES.get(args.model.lower(), args.model)
        staged = os.path.join(cd, repo.replace("/", "--"))
        os.makedirs(staged, exist_ok=True)
        for f in sorted(os.listdir(path)):
            src = os.path.join(path, f)  # may symlink into the blob store
            dst = os.path.join(staged, f)
            if os.path.isfile(src) and not os.path.exists(dst):
                shutil.copyfile(src, dst)  # copyfile resolves symlinks
        path = staged
    print(path, flush=True)


async def _run_api_store(args) -> None:
    """Deployment-management REST API (reference: api-store FastAPI app)."""
    from .deploy.api_store import ApiStore
    from .runtime.transports.hub import HubClient

    hub = await HubClient(args.hub).connect()
    reconciler = None
    if args.kube:
        from .deploy.controller import KubeApi, Reconciler

        # Distinct manager identity: the operator's orphan sweep must never
        # treat api-store children as its own (and vice versa).
        reconciler = Reconciler(
            KubeApi(namespace=args.namespace), manager="api-store"
        )
    token = args.token or os.environ.get("DYN_API_TOKEN") or None
    if token is None and args.host not in ("127.0.0.1", "localhost", "::1"):
        print(
            "api-store WARNING: binding a non-loopback address with no "
            "--token/DYN_API_TOKEN — any network peer can create/delete "
            "deployments",
            flush=True,
        )
    store = await ApiStore(
        hub, reconciler, host=args.host, port=args.port, token=token
    ).start()
    print(f"api-store on http://{args.host}:{store.port}", flush=True)
    try:
        await _wait_forever()
    finally:
        await store.close()
        if reconciler is not None:
            await reconciler.kube.close()
        await hub.close()


async def _wait_forever() -> None:
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            pass
    await stop.wait()


def main(argv: Optional[list] = None) -> None:
    # DYN_LOG / DYN_LOG_FORMAT / DYN_LOG_FILE (reference logging.rs)
    from .runtime.logging_config import setup_logging

    setup_logging()
    parser = argparse.ArgumentParser(prog="dynamo-tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_hub = sub.add_parser("hub", help="run the control-plane hub")
    p_hub.add_argument("--host", default="0.0.0.0")
    p_hub.add_argument("--port", type=int, default=6650)
    p_hub.add_argument(
        "--persist", default=None,
        help="snapshot file: durable KV + queues survive hub restart",
    )

    p_http = sub.add_parser("http", help="standalone OpenAI frontend w/ discovery")
    p_http.add_argument("--hub", required=True)
    p_http.add_argument("--host", default="0.0.0.0")
    p_http.add_argument("--port", type=int, default=8000)
    p_http.add_argument(
        "--router",
        default="round_robin",
        choices=["random", "round_robin", "kv"],
        help="worker selection policy (kv = cache-aware)",
    )
    # Admission control / deadlines (runtime/resilience.py); defaults keep
    # both disabled, matching the previous behaviour.
    p_http.add_argument(
        "--max-inflight", type=int, default=None, dest="max_inflight",
        help="in-flight request cap (unset = unlimited)",
    )
    p_http.add_argument(
        "--admission-queue", type=int, default=0, dest="admission_queue",
        help="bounded wait queue beyond the cap; overflow sheds 429",
    )
    p_http.add_argument(
        "--admission-timeout-s", type=float, default=1.0,
        dest="admission_timeout_s",
        help="max queue wait before shedding 503",
    )
    p_http.add_argument(
        "--deadline-s", type=float, default=None, dest="deadline_s",
        help="default per-request deadline (504 on exhaustion)",
    )
    # QoS / overload control (llm/qos.py); defaults keep both disabled.
    p_http.add_argument(
        "--qos-rate", type=float, default=None, dest="qos_rate",
        help="per-tenant sustained requests/s (token bucket; unset = off)",
    )
    p_http.add_argument(
        "--qos-burst", type=float, default=None, dest="qos_burst",
        help="per-tenant burst allowance (default 2x rate)",
    )
    p_http.add_argument(
        "--brownout", action="store_true",
        help="enable the brownout degradation ladder (docs/qos.md)",
    )

    p_run = sub.add_parser("run", help="in=… out=… launcher")
    p_run.add_argument("inout", nargs=2, metavar="in=/out=")
    p_run.add_argument("--hub", default=None)
    p_run.add_argument("--host", default="0.0.0.0")
    p_run.add_argument("--port", type=int, default=8000)
    p_run.add_argument("--model", default="echo")
    # Console input modes (in=text/stdin/batch:FILE) sampling defaults.
    p_run.add_argument("--max-tokens", type=int, default=None, dest="max_tokens")
    p_run.add_argument("--temperature", type=float, default=None)
    p_run.add_argument("--tokenizer", default=None, help="path to tokenizer.json")
    p_run.add_argument("--model-config", default=None, help="model config json (out=tpu)")
    # out=tpu engine knobs (reference: launch/dynamo-run/src/flags.rs)
    p_run.add_argument("--arch", default=None, help="model architecture name or HF dir (out=tpu)")
    p_run.add_argument("--checkpoint", default=None, help="safetensors dir (out=tpu)")
    p_run.add_argument("--tp", type=int, default=1, help="tensor parallel size")
    p_run.add_argument("--dp", type=int, default=1, help="data parallel size")
    p_run.add_argument("--ep", type=int, default=1, help="expert parallel size")
    p_run.add_argument(
        "--sp", type=int, default=1,
        help="sequence parallel size (ring-attention long-prompt prefill)",
    )
    p_run.add_argument(
        "--sp-prefill-min", type=int, default=1024, dest="sp_prefill_min",
        help="prompts at least this long use the sp whole-prompt prefill",
    )
    p_run.add_argument("--block-size", type=int, default=16, dest="block_size")
    p_run.add_argument("--num-blocks", type=int, default=256, dest="num_blocks")
    p_run.add_argument("--max-batch", type=int, default=8, dest="max_batch")
    p_run.add_argument("--max-model-len", type=int, default=1024, dest="max_model_len")
    p_run.add_argument("--prefill-chunk", type=int, default=512, dest="prefill_chunk")
    p_run.add_argument(
        "--dtype", default="bfloat16",
        help="weight/activation dtype (bfloat16 on TPU; float32 for CPU runs)",
    )
    p_run.add_argument(
        "--decode-steps", type=int, default=4, dest="decode_steps",
        help="decode iterations fused into one device dispatch",
    )
    p_run.add_argument(
        "--pipeline-depth", type=int, default=2, dest="pipeline_depth",
        help="fused decode dispatches kept in flight",
    )
    p_run.add_argument(
        "--kv-cache-dtype", default=None, dest="cache_dtype",
        help="KV page dtype (e.g. float8_e4m3fn halves KV memory)",
    )
    p_run.add_argument(
        "--host-cache-mb", type=int, default=0, dest="host_cache_mb",
        help="host (CPU RAM) KV tier budget in MiB: sealed blocks survive "
        "HBM eviction and restore as prefix hits (0 = off)",
    )
    p_run.add_argument(
        "--disk-cache-mb", type=int, default=0, dest="disk_cache_mb",
        help="disk KV tier budget in MiB: host-tier eviction demotes "
        "blocks to hash-named files instead of dropping them "
        "(requires --host-cache-mb; docs/kv_tiering.md)",
    )
    p_run.add_argument(
        "--disk-cache-dir", default=None, dest="disk_cache_dir",
        help="directory for the disk KV tier's block files "
        "(default: a per-process dir under the system temp root)",
    )
    p_run.add_argument(
        "--object-store-mb", type=int, default=0, dest="object_store_mb",
        help="durable object-store KV tier budget in MiB: disk-tier "
        "eviction and explicit persists land in a fleet-shared object "
        "layout that outlives the worker, so a scale-from-zero replica "
        "boots warm (requires --disk-cache-mb and --object-store-dir; "
        "docs/kv_tiering.md)",
    )
    p_run.add_argument(
        "--object-store-dir", default=None, dest="object_store_dir",
        help="object layout root for the durable KV tier (required with "
        "--object-store-mb: the store outlives the process, so there is "
        "no per-process default)",
    )
    p_run.add_argument(
        "--kv-pull-mb", type=int, default=None, dest="kv_pull_mb",
        help="cross-worker prefix pull byte budget in MiB (the router "
        "hints a peer holding a deeper prefix; the engine pulls the "
        "delta over the KV transfer plane instead of recomputing)",
    )
    p_run.add_argument(
        "--kv-scale",
        type=lambda s: s if s == "auto" else float(s),
        default=1.0,
        dest="kv_scale",
        help="quantized KV pages: a static scale, or 'auto' to calibrate "
        "per-layer scales from a probe forward at startup",
    )
    p_run.add_argument(
        "--attn-impl",
        default="auto",
        choices=["auto", "xla", "pallas", "jax"],
        dest="attn_impl",
        help="decode attention backend",
    )
    from .engine.config import DECODE_KERNELS

    p_run.add_argument(
        "--decode-kernel",
        default="auto",
        choices=["auto", *DECODE_KERNELS],
        dest="decode_kernel",
        help="decode-path attention kernel (ops/decode_attention.py): "
        "pallas_fused = our fused-dequant split-KV kernel, stock = the "
        "jax pallas ragged kernel with tuned hints, xla = the "
        "bit-exactness oracle.  auto resolves DYN_DECODE_KERNEL, then "
        "pallas_fused on TPU / stock elsewhere",
    )
    p_run.add_argument(
        "--spec-decode",
        action="store_true",
        default=None,
        dest="spec_decode",
        help="enable draft-free speculative decoding (n-gram prompt "
        "lookup, verified in-step; engine/spec.py — token streams are "
        "identical to non-speculative decoding)",
    )
    p_run.add_argument(
        "--spec-k", type=int, default=None, dest="spec_k",
        help="max draft tokens per sequence per dispatch",
    )
    p_run.add_argument(
        "--spec-ngram-min", type=int, default=None, dest="spec_ngram_min",
        help="shortest suffix n-gram tried by the proposer",
    )
    p_run.add_argument(
        "--spec-ngram-max", type=int, default=None, dest="spec_ngram_max",
        help="longest suffix n-gram tried by the proposer",
    )
    p_run.add_argument(
        "--lora",
        action="append",
        default=None,
        metavar="NAME=SPEC",
        help="serve a LoRA adapter under model name NAME (repeatable; "
        "llm/tenancy).  SPEC is a local PEFT directory, a HF repo id, or "
        "'random[:seed]' for a synthetic adapter.  Requests select the "
        "adapter via the OpenAI 'model' field; unknown names 404.",
    )
    p_run.add_argument(
        "--lora-max-adapters", type=int, default=None,
        dest="lora_max_adapters",
        help="resident device adapter slots (distinct adapters per batch)",
    )
    p_run.add_argument(
        "--lora-rank", type=int, default=None, dest="lora_rank",
        help="per-slot rank ceiling (smaller-rank adapters zero-pad up)",
    )
    p_run.add_argument(
        "--record", default=None,
        help="capture every request/response stream to this JSONL file "
        "(replayable — runtime/recorder.py)",
    )
    p_run.add_argument(
        "--disagg",
        default=None,
        choices=["decode", "prefill"],
        help="disaggregated role for this worker (requires --hub)",
    )
    p_run.add_argument(
        "--max-local-prefill",
        type=int,
        default=512,
        dest="max_local_prefill",
        help="prefills longer than this (minus prefix hit) go remote",
    )
    # multi-host scale-out (reference: MultiNodeConfig, engines.rs:40-105)
    p_run.add_argument(
        "--nnodes", type=int, default=1, help="total hosts in this engine"
    )
    p_run.add_argument(
        "--node-rank", type=int, default=0, dest="node_rank",
        help="this host's rank (0 = leader)",
    )
    p_run.add_argument(
        "--coordinator", default="",
        help="host:port of rank 0's jax.distributed coordinator",
    )
    p_run.add_argument(
        "--step-port", type=int, default=6651, dest="step_port",
        help="leader port for the follower dispatch stream",
    )
    p_run.add_argument(
        "--cpu-devices", type=int, default=None, dest="cpu_devices",
        help="TEST ONLY: use N virtual CPU devices per process",
    )
    # QoS / overload control for in=http (llm/qos.py; defaults disabled).
    p_run.add_argument(
        "--qos-rate", type=float, default=None, dest="qos_rate",
        help="per-tenant sustained requests/s (token bucket; unset = off)",
    )
    p_run.add_argument(
        "--qos-burst", type=float, default=None, dest="qos_burst",
        help="per-tenant burst allowance (default 2x rate)",
    )
    p_run.add_argument(
        "--brownout", action="store_true",
        help="enable the brownout degradation ladder (docs/qos.md)",
    )

    p_model = sub.add_parser("model", help="model registry (llmctl equivalent)")
    p_model.add_argument("verb", choices=["add", "list", "remove"])
    p_model.add_argument("name", nargs="?", default=None)
    p_model.add_argument("endpoint", nargs="?", default=None, help="dyn://ns.comp.ep")
    p_model.add_argument("--hub", required=True)
    p_model.add_argument("--type", default="both", choices=["chat", "completion", "both"])
    p_model.add_argument("--tokenizer", default=None)
    p_model.add_argument("--block-size", type=int, default=16, dest="block_size")

    p_metrics = sub.add_parser("metrics", help="namespace metrics aggregator")
    p_metrics.add_argument("--hub", required=True)
    p_metrics.add_argument("--namespace", default="dynamo")
    p_metrics.add_argument("--component", default="TpuWorker")
    p_metrics.add_argument("--host", default="0.0.0.0")
    p_metrics.add_argument("--port", type=int, default=9091)

    p_deploy = sub.add_parser(
        "deploy", help="render k8s manifests from a DynamoTpuDeployment CR"
    )
    p_deploy.add_argument("verb", choices=["render", "preview"])
    p_deploy.add_argument("-f", "--file", required=True, dest="cr_file")

    p_mock = sub.add_parser("mock-worker", help="synthetic metrics/KV events")
    p_mock.add_argument("--hub", required=True)
    p_mock.add_argument("--namespace", default="dynamo")
    p_mock.add_argument("--component", default="TpuWorker")
    p_mock.add_argument("--interval", type=float, default=0.5)

    p_prep = sub.add_parser(
        "prepare",
        help="pre-stage a model checkpoint into the cache "
             "(model-cache Job entrypoint)",
    )
    p_prep.add_argument("model")
    p_prep.add_argument("--cache", default=None,
                        help="destination dir (overrides DYN_MODEL_CACHE)")
    p_prep.add_argument("--revision", default=None)

    p_op = sub.add_parser(
        "operator",
        help="k8s controller: reconcile DynamoTpuDeployment + "
             "DynamoTpuModelCache CRs in-cluster",
    )
    p_op.add_argument("--namespace", default="default")
    p_op.add_argument("--poll-interval", type=float, default=10.0,
                      dest="poll_interval")
    p_op.add_argument("--api-server", default=None, dest="api_server",
                      help="override the in-cluster API server URL")

    p_store = sub.add_parser(
        "api-store",
        help="deployment-management REST API over the hub store",
    )
    p_store.add_argument("--hub", required=True)
    # Loopback by default: the store can create/delete k8s objects (with
    # --kube), so exposure beyond localhost is opt-in and should come with
    # --token (r4 advisory).
    p_store.add_argument("--host", default="127.0.0.1")
    p_store.add_argument("--port", type=int, default=7070)
    p_store.add_argument(
        "--kube", action="store_true",
        help="also reconcile created deployments against the k8s API",
    )
    p_store.add_argument("--namespace", default="default")
    p_store.add_argument(
        "--token", default=None,
        help="bearer token required on every request (default: "
        "DYN_API_TOKEN env; unset = unauthenticated)",
    )

    args = parser.parse_args(argv)
    if args.cmd == "model" and args.verb in ("add", "remove") and not args.name:
        parser.error(f"model {args.verb} requires a model name")
    if args.cmd == "model" and args.verb == "add" and not args.endpoint:
        parser.error("model add requires an endpoint path")
    if args.cmd == "run":
        kv = dict(part.split("=", 1) for part in args.inout)
        if "in" not in kv or "out" not in kv:
            raise SystemExit("run requires in=… out=…")
        args.inp, args.out = kv["in"], kv["out"]
        if args.nnodes > 1 or args.cpu_devices:
            # Must run before anything initializes a jax backend.
            from .parallel.distributed import MultiHostConfig, init_multihost

            init_multihost(
                MultiHostConfig(
                    coordinator=args.coordinator,
                    nnodes=args.nnodes,
                    node_rank=args.node_rank,
                    cpu_devices=args.cpu_devices,
                )
            )

    if args.cmd == "deploy":
        import yaml

        from .deploy import render_to_yaml, shell_preview

        with open(args.cr_file) as f:
            cr = yaml.safe_load(f)
        print(
            render_to_yaml(cr) if args.verb == "render" else shell_preview(cr)
        )
        return

    try:
        if args.cmd == "hub":
            asyncio.run(_run_hub(args))
        elif args.cmd == "http":
            asyncio.run(_run_http_frontend(args))
        elif args.cmd == "model":
            asyncio.run(_run_model_cmd(args))
        elif args.cmd == "prepare":
            _run_prepare(args)
        elif args.cmd == "metrics":
            asyncio.run(_run_metrics(args))
        elif args.cmd == "mock-worker":
            asyncio.run(_run_mock_worker(args))
        elif args.cmd == "operator":
            asyncio.run(_run_operator(args))
        elif args.cmd == "api-store":
            asyncio.run(_run_api_store(args))
        else:
            asyncio.run(_run(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
