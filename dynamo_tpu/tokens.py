"""Token block sequences with chained hashing — the KV-reuse identity scheme.

Reference semantics (not code): lib/tokens/src/lib.rs:44-369 and
lib/llm/src/tokens.rs:30-173 — prompts are split into fixed-size blocks; each
block has a *local* hash (hash of its token ids alone) and a *sequence* hash
chained from the parent block's sequence hash, so a sequence hash uniquely
identifies "these tokens after that exact prefix".  The router's radix index,
the engine's prefix-reuse pool, and KV events all speak these hashes, which is
what lets the KV-aware router mirror engine cache state exactly.

An optional ``salt`` mixes tenant/LoRA identity into the root so equal token
streams from different tenants never share cache entries.

Hashing is pure host-side bookkeeping (never traced by JAX).  The algorithm
is XXH64 seed 1337 — chosen because the native C++ runtime components
(native/dyn_tokens.cc) implement the identical function, so hashes computed
in either language agree across one deployment.  blake2b-64 is the fallback
only when the xxhash module is missing (dev env) — mixing fallback and
native hashing in one fleet would break routing.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Iterable, List, Optional, Sequence, Tuple

HASH_SEED = 1337

try:
    import xxhash

    USING_XXHASH = True

    def _hash_bytes(data: bytes) -> int:
        return xxhash.xxh64_intdigest(data, seed=HASH_SEED)

except ImportError:  # pragma: no cover - image always has xxhash
    import hashlib

    USING_XXHASH = False

    def _hash_bytes(data: bytes) -> int:
        h = hashlib.blake2b(data, digest_size=8, salt=b"dyn1337\x00")
        return int.from_bytes(h.digest(), "little")


def _pack_tokens(tokens: Sequence[int]) -> bytes:
    return struct.pack(f"<{len(tokens)}I", *tokens)


def compute_block_hash(tokens: Sequence[int]) -> int:
    """Local hash of one block's token ids (order-sensitive, prefix-free)."""
    return _hash_bytes(_pack_tokens(tokens))


def chain_hash(parent: Optional[int], local_hash: int) -> int:
    """Sequence hash = H(parent_seq_hash || local_hash); root chains from salt."""
    parent_bytes = struct.pack("<Q", parent if parent is not None else 0)
    return _hash_bytes(parent_bytes + struct.pack("<Q", local_hash))


def salt_hash(salt: Optional[str]) -> Optional[int]:
    if not salt:
        return None
    return _hash_bytes(salt.encode("utf-8"))


@dataclass(frozen=True)
class TokenBlock:
    """One full block of tokens with its local + chained sequence hash."""

    tokens: Tuple[int, ...]
    block_hash: int  # local: hash of this block's tokens only
    sequence_hash: int  # chained: identifies tokens *and* their prefix
    parent_hash: Optional[int]  # previous block's sequence hash (None = root)


class TokenBlockSequence:
    """Splits a growing token stream into hashed fixed-size blocks.

    Only *complete* blocks are hashed/published; the partial tail is kept as
    plain tokens.  ``extend`` is incremental so the engine can hash during
    decode without rehashing the prompt each step.
    """

    def __init__(
        self,
        tokens: Iterable[int] = (),
        block_size: int = 16,
        salt: Optional[str] = None,
    ):
        if block_size <= 0:
            raise ValueError("block_size must be positive")
        self.block_size = block_size
        self._salt_hash = salt_hash(salt)
        self._blocks: List[TokenBlock] = []
        self._tail: List[int] = []
        self.extend(tokens)

    @property
    def blocks(self) -> List[TokenBlock]:
        return self._blocks

    @property
    def tail_tokens(self) -> List[int]:
        return list(self._tail)

    @property
    def total_tokens(self) -> int:
        return len(self._blocks) * self.block_size + len(self._tail)

    @property
    def last_sequence_hash(self) -> Optional[int]:
        if not self._blocks:
            return self._salt_hash
        return self._blocks[-1].sequence_hash

    def append(self, token: int) -> Optional[TokenBlock]:
        """Add one token; returns the newly completed block, if any."""
        self._tail.append(token)
        if len(self._tail) == self.block_size:
            return self._seal_tail()
        return None

    def extend(self, tokens: Iterable[int]) -> List[TokenBlock]:
        """Add many tokens; returns all blocks completed by this call."""
        new_blocks: List[TokenBlock] = []
        for tok in tokens:
            blk = self.append(tok)
            if blk is not None:
                new_blocks.append(blk)
        return new_blocks

    def _seal_tail(self) -> TokenBlock:
        parent = self.last_sequence_hash
        local = compute_block_hash(self._tail)
        block = TokenBlock(
            tokens=tuple(self._tail),
            block_hash=local,
            sequence_hash=chain_hash(parent, local),
            parent_hash=parent,
        )
        self._blocks.append(block)
        self._tail = []
        return block

    def sequence_hashes(self) -> List[int]:
        return [b.sequence_hash for b in self._blocks]

    def block_hashes(self) -> List[int]:
        return [b.block_hash for b in self._blocks]


def hash_token_blocks(
    tokens: Sequence[int], block_size: int, salt: Optional[str] = None
) -> List[TokenBlock]:
    """One-shot helper: hash all complete blocks of ``tokens``."""
    return TokenBlockSequence(tokens, block_size, salt).blocks


def fast_sequence_hashes(
    tokens: Sequence[int], block_size: int, salt: Optional[str] = None
) -> List[int]:
    """Chained sequence hashes of all complete blocks — the router's hot path
    (one call per routed request over the full prompt).  Uses the native C++
    library (native/dyn_tokens.cc, bit-identical XXH64 chain) when available,
    pure Python otherwise."""
    # The native library is XXH64; if this process hashes with the blake2b
    # fallback, native hashes would not match engine-sealed blocks — skip it.
    if USING_XXHASH:
        try:
            from . import native
        except ImportError:  # pragma: no cover
            native = None
        if native is not None:
            root = salt_hash(salt) or 0
            pairs = native.hash_blocks(list(tokens), block_size, root)
            if pairs is not None:
                return [seq for _local, seq in pairs]
    return [b.sequence_hash for b in hash_token_blocks(tokens, block_size, salt)]
