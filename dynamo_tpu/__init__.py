"""dynamo_tpu — a TPU-native distributed LLM inference serving framework.

A from-scratch re-design of the capabilities of NVIDIA Dynamo (reference:
basetenlabs/dynamo @ 2025-05-23) for TPU hardware:

- ``runtime``  — distributed runtime: AsyncEngine/Context, pipeline graph,
  discovery (lease-based KV with prefix watches), request plane, TCP response
  streaming, event plane.  (reference: lib/runtime/)
- ``llm``      — serving library: OpenAI protocols, preprocessor, backend
  (detokenize/stop), KV-aware router, model deployment cards.
  (reference: lib/llm/)
- ``engine``   — the TPU-native JAX engine: continuous batching with paged KV
  cache in HBM, jitted prefill/decode, sampling.  (replaces the reference's
  vLLM/sglang engine adapters with a native engine.)
- ``models``   — JAX model implementations (llama family, MoE).
- ``ops``      — Pallas/XLA kernels (paged attention, block copy).
- ``parallel`` — mesh construction, shardings, collectives-based parallelism.
- ``sdk``      — service-graph SDK (@service/@endpoint/depends) + supervisor.
"""

__version__ = "0.1.0"
