"""Multi-host scale-out: jax.distributed bootstrap + global-array helpers.

Reference counterpart: ``MultiNodeConfig {num_nodes, node_rank,
leader_addr}`` (/root/reference/lib/llm/src/engines.rs:40-105) and the vLLM
Ray leader/follower bootstrap (/root/reference/lib/engines/vllm0_7/src/
ray.rs).  The TPU-native translation is jax multi-controller SPMD: one
process per host, every process runs the same program over one global
``Mesh``; XLA collectives ride ICI within a slice and DCN across slices.
Nothing like NCCL bootstrap exists to port — the coordinator handshake and
device exchange are jax.distributed's job.

``init_multihost`` must run before anything initializes a jax backend.
For CI (no multi-host TPU hardware) the same code path runs as N processes
x M virtual CPU devices with gloo collectives — tests/test_multihost.py.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np


@dataclass
class MultiHostConfig:
    """--nnodes/--node-rank/--coordinator (reference: MultiNodeConfig)."""

    coordinator: str = ""  # host:port of the rank-0 process
    nnodes: int = 1
    node_rank: int = 0
    # Test/CI only: force this many virtual CPU devices per process (with
    # gloo cross-process collectives) instead of local TPU chips.
    cpu_devices: Optional[int] = None

    @property
    def is_multihost(self) -> bool:
        return self.nnodes > 1

    @property
    def is_leader(self) -> bool:
        return self.node_rank == 0


def init_multihost(cfg: MultiHostConfig) -> None:
    """Bring this process into the global jax runtime.  Call exactly once,
    before any jax backend initialization."""
    import jax

    if cfg.cpu_devices:
        jax.config.update("jax_num_cpu_devices", int(cfg.cpu_devices))
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    if cfg.nnodes > 1:
        if not cfg.coordinator:
            raise ValueError("multi-host run needs --coordinator host:port")
        jax.distributed.initialize(
            coordinator_address=cfg.coordinator,
            num_processes=cfg.nnodes,
            process_id=cfg.node_rank,
        )


def is_multiprocess() -> bool:
    import jax

    return jax.process_count() > 1


def global_array(x, sharding):
    """Assemble a global jax.Array from a full per-host copy of ``x``.

    Every process calls this with identical host data (the SPMD contract for
    replicated inputs and same-seed params); the callback hands each local
    device its slice.  Works for any PartitionSpec, single- or multi-host.
    """
    import jax

    x = np.asarray(x)
    return jax.make_array_from_callback(x.shape, sharding, lambda idx: x[idx])
