"""Device-mesh parallelism: TP/DP/EP shardings for the native engine.

The reference delegates intra-model parallelism to its engines (NCCL inside
vLLM — SURVEY.md §2.7); here it is first-class: a `jax.sharding.Mesh` with
named axes, PartitionSpec trees per params structure, and XLA-generated ICI
collectives.
"""

from .distributed import (  # noqa: F401
    MultiHostConfig,
    global_array,
    init_multihost,
)
from .mesh import (  # noqa: F401
    MeshConfig,
    make_mesh,
    pages_pspec,
    param_pspecs,
    shard_tree,
    sharding_tree,
)
