"""Mesh + sharding rules (the scaling-book recipe: pick a mesh, annotate
shardings, let XLA insert collectives).

Axes:
- "dp"  — data parallel: distinct batch rows (request-level; the serving tier
          usually does DP via multiple engine replicas instead, matching the
          reference's replica model, but in-engine dp is supported).
- "tp"  — tensor parallel: attention heads / FFN hidden / vocab. Collectives
          (all-reduce after wo/w_down, all-gather for logits) ride ICI.
- "ep"  — expert parallel for MoE: experts dimension. Folded onto "tp" when
          not given its own axis.

KV cache shards over "tp" on the kv_heads axis, so paged attention is fully
local per chip (each chip owns its heads' cache); block tables/ids are
replicated host metadata.

Reference counterpart: `--tensor-parallel-size` and friends
(launch/dynamo-run/src/flags.rs:63; SURVEY.md §2.7) — there they configure an
external engine; here they parameterise the mesh.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.config import ModelConfig


@dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    tp: int = 1
    ep: int = 1  # expert parallel; 1 = fold experts onto tp
    sp: int = 1  # sequence parallel (ring attention, long-context prefill)

    @property
    def num_devices(self) -> int:
        return self.dp * self.tp * self.ep * self.sp


def make_mesh(
    cfg: MeshConfig, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    n = cfg.num_devices
    if devices is None:
        devices = jax.devices()
        if len(devices) < n:
            # Virtual CPU mesh fallback (tests / dry-runs use
            # --xla_force_host_platform_device_count; SURVEY.md §4).
            try:
                cpus = jax.devices("cpu")
            except RuntimeError:
                cpus = []
            if len(cpus) >= n:
                devices = cpus
    if len(devices) < n:
        raise ValueError(f"need {n} devices for {cfg}, have {len(devices)}")
    # sp adjacent to tp: K/V ring hops between sp neighbors stay one ICI
    # hop for standard torus topologies.
    grid = np.array(devices[:n]).reshape(cfg.dp, cfg.ep, cfg.sp, cfg.tp)
    return Mesh(grid, ("dp", "ep", "sp", "tp"))


def param_pspecs(config: ModelConfig) -> Any:
    """PartitionSpec tree matching models.llama.init_params structure.

    Column-parallel (wq/wk/wv/w_gate/w_up): shard output features on tp.
    Row-parallel (wo/w_down): shard input features on tp → XLA all-reduces
    the partial sums.  Vocab shards on tp for embed and lm_head.  MoE experts
    shard on ep (plus tp on the expert FFN hidden dim).
    """
    layers = {
        "attn_norm": P(),
        "wq": P(None, None, "tp"),
        "wk": P(None, None, "tp"),
        "wv": P(None, None, "tp"),
        "wo": P(None, "tp", None),
        # Qwen2 attention biases: shard with their projections' outputs.
        "bq": P(None, "tp"),
        "bk": P(None, "tp"),
        "bv": P(None, "tp"),
        "mlp_norm": P(),
        # dense FFN
        "w_gate": P(None, None, "tp"),
        "w_up": P(None, None, "tp"),
        "w_down": P(None, "tp", None),
        # MoE
        "router": P(),
        "moe_gate": P(None, "ep", None, "tp"),
        "moe_up": P(None, "ep", None, "tp"),
        "moe_down": P(None, "ep", "tp", None),
        # int8 weight-quant scales (models/quant.py): a scale lives on its
        # weight's OUTPUT-channel axis and shards with it; row-parallel
        # weights (wo/w_down/moe_down) have replicated outputs.
        "wq_scale": P(None, "tp"),
        "wk_scale": P(None, "tp"),
        "wv_scale": P(None, "tp"),
        "wo_scale": P(),
        "w_gate_scale": P(None, "tp"),
        "w_up_scale": P(None, "tp"),
        "w_down_scale": P(),
        "moe_gate_scale": P(None, "ep", "tp"),
        "moe_up_scale": P(None, "ep", "tp"),
        "moe_down_scale": P(None, "ep", None),
    }
    specs = {
        "embed": P("tp", None),
        "embed_scale": P("tp"),  # per-vocab-row, shards with embed
        "layers": layers,
        "final_norm": P(),
        "lm_head": P(None, "tp"),
        "lm_head_scale": P("tp"),
    }
    return specs


def pages_pspec() -> P:
    """PagedKVCache slabs [L, pages, page_size, 2*kv_heads, head_dim]: the
    combined K/V head axis shards on tp (tp | kv_heads keeps each K/V pair
    on one shard)."""
    return P(None, None, None, "tp", None)


def _trim(spec: P, ndim: int) -> P:
    parts = list(spec) + [None] * ndim
    return P(*parts[:ndim])


def _spec_for_path(specs: Any, path: Sequence[Any]) -> P:
    """Walk a spec tree along a tree_map_with_path key path; P() if absent."""
    spec = specs
    for key in path:
        # DictKey.key / SequenceKey.idx / GetAttrKey.name (namedtuples)
        k = getattr(key, "key", None)
        if k is None:
            k = getattr(key, "idx", None)
        if k is None:
            k = getattr(key, "name", None)
        if isinstance(spec, dict):
            spec = spec.get(k, P())
        elif isinstance(spec, tuple) and not isinstance(spec, P):
            spec = getattr(spec, k) if isinstance(k, str) else spec[k]
    return spec if isinstance(spec, P) else P()


def sharding_tree(tree: Any, specs: Any, mesh: Mesh) -> Any:
    """NamedSharding pytree matching ``tree``'s structure (for use as jit
    in_shardings/out_shardings), pruning spec entries the tree lacks (e.g.
    MoE specs on a dense model, lm_head on tied embeddings)."""

    def to_sharding(path, leaf):
        spec = _spec_for_path(specs, path)
        return NamedSharding(mesh, _trim(spec, getattr(leaf, "ndim", 0)))

    return jax.tree_util.tree_map_with_path(to_sharding, tree)


def shard_tree(tree: Any, specs: Any, mesh: Mesh) -> Any:
    """Place a pytree's arrays onto the mesh per the spec tree.

    Multi-process: the mesh spans devices this process cannot address, so
    each leaf is assembled from the full per-host copy via
    ``jax.make_array_from_callback`` (every process holds identical host
    values — same init seed / same checkpoint)."""
    shardings = sharding_tree(tree, specs, mesh)
    if jax.process_count() > 1:
        from .distributed import global_array

        return jax.tree_util.tree_map(global_array, tree, shardings)
    return jax.tree_util.tree_map(jax.device_put, tree, shardings)
