"""Model discovery: hub-watched ModelEntry registry → live HTTP models.

Reference semantics: lib/llm/src/http/service/discovery.rs:36-166 — the HTTP
frontend watches ``models/`` registrations; a Put builds a typed remote
pipeline and adds it to the ModelManager, a Delete (lease expiry = worker
death) removes it.  Workers register a ``ModelEntry`` naming the token-level
endpoint they serve plus enough tokenizer info for the frontend to run the
preprocessor locally (the reference ships this in the ModelDeploymentCard).

Entry key: ``models/{model_name}/{worker_id}`` so multiple workers can back
one model; the engine is added on the first entry, removed with the last.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any, Dict, Optional

from ..runtime.client import RouterMode
from ..runtime.component import DistributedRuntime, parse_endpoint_path
from ..runtime.pipeline import build_pipeline
from ..runtime.transports.shard import hub_key, hub_prefix
from .backend import Backend
from .http_service import ModelManager
from .preprocessor import OpenAIPreprocessor
from .tokenizer import BaseTokenizer, ByteTokenizer, HFTokenizer

logger = logging.getLogger(__name__)

MODEL_PREFIX = "models/"


def model_key(name: str, worker_id: int) -> str:
    """Per-worker model registration key (shard-map routed: DYN401)."""
    return hub_key("models", name, worker_id)


def model_prefix(name: str) -> str:
    """Query prefix for one model's registrations across workers."""
    return hub_prefix("models", name)


def make_tokenizer(spec: Dict[str, Any]) -> BaseTokenizer:
    kind = (spec or {}).get("kind", "byte")
    if kind == "byte":
        return ByteTokenizer()
    if kind == "hf":
        if "file" in spec:
            return HFTokenizer(spec["file"])
        import os

        d = spec["dir"]
        if not os.path.exists(os.path.join(d, "tokenizer.json")) and spec.get(
            "source"
        ):
            # Registered dirs are paths on the REGISTERING worker's
            # filesystem; a frontend on another host re-resolves the
            # original model spec (HF snapshot / pre-staged cache) instead
            # of silently failing the model registration.
            from ..models.hub import resolve_model

            logger.info(
                "tokenizer dir %s not on this host; resolving %r locally",
                d, spec["source"],
            )
            d = resolve_model(spec["source"])
        return HFTokenizer.from_pretrained_dir(d)
    if kind == "gguf":
        import os

        f = spec["file"]
        if not os.path.exists(f) and spec.get("source"):
            from ..models.hub import resolve_model

            f = resolve_model(spec["source"])
        from ..models.gguf import GGUFFile

        return GGUFFile(f).to_tokenizer()
    if kind == "sp":
        import os

        from .tokenizer import SentencePieceTokenizer

        f = spec["file"]
        if not os.path.exists(f) and spec.get("source"):
            from ..models.hub import resolve_model

            f = os.path.join(resolve_model(spec["source"]), "tokenizer.model")
        return SentencePieceTokenizer(f)
    raise ValueError(f"unknown tokenizer kind {kind!r}")


async def register_model(
    runtime: DistributedRuntime,
    name: str,
    endpoint_path: str,
    model_type: str = "both",  # chat | completion | both
    tokenizer: Optional[Dict[str, Any]] = None,
    lease: Optional[int] = None,
    kv_block_size: int = 16,
    static: bool = False,  # no lease: survives the registrar (llmctl mode)
    lora: Optional[Dict[str, Any]] = None,  # adapter entry: {"adapter", "base"}
) -> str:
    """Worker-side model registration (reference: llmctl + ModelEntry).

    ``lora`` marks the entry as a LoRA adapter alias (llm/tenancy): the
    frontend's ModelWatcher builds its pipeline with an adapter-stamping
    preprocessor, so requests naming this model route to the base engine
    with tenant identity (adapter id + KV salt) attached."""
    key = model_key(name, runtime.worker_id)
    entry = {
        "name": name,
        "endpoint": endpoint_path,
        "model_type": model_type,
        "tokenizer": tokenizer or {"kind": "byte"},
        # Routers must hash with the engine's block size or overlap is zero.
        "kv_block_size": kv_block_size,
    }
    if lora:
        entry["lora"] = dict(lora)
    if static:
        await runtime.hub.kv_put(key, entry)  # persistent, no liveness tie
        return key
    if lease is None:
        await runtime.register_key(key, entry)  # self-healing registration
        return key
    await runtime.hub.kv_put(key, entry, lease if lease is not None else runtime.primary_lease)
    return key


class ModelWatcher:
    """Watches model registrations and maintains a ModelManager."""

    def __init__(
        self,
        runtime: DistributedRuntime,
        manager: ModelManager,
        router_mode: RouterMode = RouterMode.ROUND_ROBIN,
    ):
        self.runtime = runtime
        self.manager = manager
        self.router_mode = router_mode
        self._refcount: Dict[str, int] = {}
        self._clients: Dict[str, Any] = {}
        # One grammar compile cache per tokenizer spec (llm/tenancy):
        # constraint→automaton indexing costs seconds on big vocabularies,
        # and the base model plus its adapter aliases share a tokenizer —
        # per-pipeline caches would recompile the same schema per served
        # name and lose the warm cache on every watch rebuild.
        self._grammar_compilers: Dict[str, Any] = {}
        self._router_cores: Dict[str, Any] = {}
        self._task: Optional[asyncio.Task] = None
        self._watcher = None

    async def start(self) -> "ModelWatcher":
        self._watcher = await self.runtime.hub.watch_prefix(MODEL_PREFIX)
        self._task = asyncio.create_task(self._run())
        await self._watcher.synced.wait()
        return self

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        if self._watcher is not None:
            await self._watcher.aclose()
        for core in self._router_cores.values():
            await core.stop()
        self._router_cores.clear()
        for client in self._clients.values():
            await client.close()
        self._clients.clear()

    async def _run(self) -> None:
        """Model watch with hub-restart recovery: on watcher death (e.g.
        ``HubSessionLost``) the watch is re-armed and the served-model set
        resynced — models deregistered during the outage tear down, new
        ones build, surviving ones keep their warm pipelines/caches."""
        backoff = 0.1
        while True:
            try:
                async for event in self._watcher:
                    backoff = 0.1
                    name = event.key[len(MODEL_PREFIX) :].rsplit("/", 1)[0]
                    try:
                        if event.type == "put":
                            await self._handle_put(name, event.value)
                        else:
                            await self._handle_delete(name)
                    except asyncio.CancelledError:
                        raise
                    except Exception:  # noqa: BLE001 — keep watching
                        logger.exception(
                            "model watcher failed handling %s", event.key
                        )
                return  # closed cleanly (stop())
            except asyncio.CancelledError:
                return
            except Exception:  # noqa: BLE001 — re-arm below
                logger.exception("model watch died; re-arming + resync")
            while True:
                try:
                    await asyncio.sleep(backoff)
                    backoff = min(backoff * 2, 5.0)
                    old, self._watcher = self._watcher, None
                    if old is not None:
                        try:
                            await old.aclose()
                        except asyncio.CancelledError:
                            raise
                        except Exception:  # noqa: BLE001 — dead watcher
                            pass
                    self._watcher = await self.runtime.hub.watch_prefix(
                        MODEL_PREFIX
                    )
                    await self._resync()
                    break
                except asyncio.CancelledError:
                    return
                except Exception:  # noqa: BLE001 — hub still down
                    logger.warning("model watch re-arm failed; retrying")

    async def _resync(self) -> None:
        """Reconcile against the hub's current model registrations after a
        watch gap.  Names gone from the hub tear down now; refcounts reset
        to zero because the re-armed watch replays the current keys as its
        snapshot (each put re-counts one registration) — ``_handle_put``
        reuses live pipelines, so surviving models keep warm state."""
        snapshot = await self.runtime.hub.kv_get_prefix(MODEL_PREFIX)
        live = {
            key[len(MODEL_PREFIX):].rsplit("/", 1)[0]
            for key in snapshot
        }
        for name in [n for n in list(self._clients) if n not in live]:
            self._refcount[name] = 1  # force the teardown path
            await self._handle_delete(name)
        self._refcount = {}

    async def _handle_put(self, name: str, entry: Dict[str, Any]) -> None:
        self._refcount[name] = self._refcount.get(name, 0) + 1
        if name in self._clients:
            # Already built (refcount > 1, or a post-resync snapshot replay
            # re-counting a surviving model): keep the warm pipeline.
            return
        ns, comp, ep = parse_endpoint_path(entry["endpoint"])
        endpoint = self.runtime.namespace(ns).component(comp).endpoint(ep)
        client = await endpoint.client(router_mode=self.router_mode)
        self._clients[name] = client
        sink: Any = client
        if self.router_mode == RouterMode.KV:
            from .kv_router.router import KvPushRouter, KvRouterCore

            core = await KvRouterCore(
                endpoint.component,
                client,
                block_size=int(entry.get("kv_block_size", 16)),
            ).start()
            self._router_cores[name] = core
            sink = KvPushRouter(core)
        tokenizer = make_tokenizer(entry.get("tokenizer"))
        # Adapter-alias entries (llm/tenancy): the preprocessor stamps the
        # adapter id + KV salt so the engine (and the KV router above, when
        # router_mode == KV) resolves tenant identity per request.
        adapter = (entry.get("lora") or {}).get("adapter")
        tok_key = json.dumps(entry.get("tokenizer"), sort_keys=True)
        compiler = self._grammar_compilers.get(tok_key)
        if compiler is None:
            from .tenancy.grammar import GrammarCompiler

            compiler = self._grammar_compilers[tok_key] = GrammarCompiler(
                tokenizer
            )
        pipeline = build_pipeline(
            [
                OpenAIPreprocessor(
                    tokenizer, name, adapter=adapter,
                    grammar_compiler=compiler,
                ),
                Backend(tokenizer),
            ],
            sink,
        )
        model_type = entry.get("model_type", "both")
        if model_type in ("chat", "both"):
            self.manager.add_chat_model(name, pipeline)
        if model_type in ("completion", "both"):
            self.manager.add_completion_model(name, pipeline)
        logger.info("model added: %s → %s", name, entry["endpoint"])

    async def _handle_delete(self, name: str) -> None:
        if name not in self._refcount:
            return
        self._refcount[name] -= 1
        if self._refcount[name] > 0:
            return
        del self._refcount[name]
        self.manager.remove_model(name)
        core = self._router_cores.pop(name, None)
        if core is not None:
            await core.stop()
        client = self._clients.pop(name, None)
        if client is not None:
            await client.close()
        logger.info("model removed: %s", name)
