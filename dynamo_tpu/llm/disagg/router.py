"""Local-vs-remote prefill decision with live config.

Reference semantics: lib/llm/src/disagg_router.rs:24-41,142-253 — prefill
goes remote iff

    prefill_tokens − prefix_hit_tokens > max_local_prefill_length
    AND queue_size < max_prefill_queue_size

and the thresholds live-update from a config key watched in the control
plane (etcd key ``public/components/disagg_router/models/chat/{model}``
there; hub key ``disagg_router/{model}`` here).
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass
from typing import Optional

logger = logging.getLogger(__name__)

from ...runtime.transports.shard import hub_key

CONFIG_PREFIX = "disagg_router/"


def disagg_config_key(model: str) -> str:
    """Live-threshold config key for one model (shard-map routed: DYN401)."""
    return hub_key("disagg_router", model)


@dataclass
class DisaggConfig:
    max_local_prefill_length: int = 512
    max_prefill_queue_size: int = 64

    def to_dict(self) -> dict:
        return {
            "max_local_prefill_length": self.max_local_prefill_length,
            "max_prefill_queue_size": self.max_prefill_queue_size,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "DisaggConfig":
        return cls(
            max_local_prefill_length=int(
                d.get("max_local_prefill_length", cls.max_local_prefill_length)
            ),
            max_prefill_queue_size=int(
                d.get("max_prefill_queue_size", cls.max_prefill_queue_size)
            ),
        )


class DisaggregatedRouter:
    def __init__(self, model: str, config: Optional[DisaggConfig] = None):
        self.model = model
        self.config = config or DisaggConfig()
        self._watch_task: Optional[asyncio.Task] = None
        self._watcher = None
        self._hub = None

    def prefill_remote(
        self, prefill_length: int, prefix_hit_length: int, queue_size: int
    ) -> bool:
        return (
            prefill_length - prefix_hit_length > self.config.max_local_prefill_length
            and queue_size < self.config.max_prefill_queue_size
        )

    # ---------------------------------------------------------- live config
    @property
    def config_key(self) -> str:
        return disagg_config_key(self.model)

    async def watch_config(self, hub) -> "DisaggregatedRouter":
        """Start live-updating thresholds from the hub KV."""
        self._hub = hub
        current = await hub.kv_get(self.config_key)
        if current:
            self.config = DisaggConfig.from_dict(current)
        self._watcher = await hub.watch_prefix(self.config_key)
        self._watch_task = asyncio.get_running_loop().create_task(self._watch())
        return self

    async def _watch(self) -> None:
        """Apply config deltas; a crashed watch re-establishes with backoff
        (same shape as runtime/client.py — a raised watcher must not freeze
        the thresholds stale forever).  The router keeps serving its current
        config throughout; only liveness of UPDATES degrades."""
        backoff = 0.1
        while True:
            try:
                async for event in self._watcher:
                    backoff = 0.1
                    if event.type == "put" and event.value:
                        self.config = DisaggConfig.from_dict(event.value)
                        logger.info(
                            "disagg config updated for %s: %s", self.model, self.config
                        )
                return  # closed cleanly (stop())
            except asyncio.CancelledError:
                return
            except Exception:  # noqa: BLE001 — hub hiccup
                logger.exception("disagg config watch died; re-establishing")
            try:
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 5.0)
                old, self._watcher = self._watcher, None
                if old is not None:
                    try:
                        await old.aclose()  # free the hub-side registration
                    except asyncio.CancelledError:
                        raise
                    except Exception:  # noqa: BLE001 — dead watcher
                        pass
                self._watcher = await self._hub.watch_prefix(self.config_key)
                current = await self._hub.kv_get(self.config_key)
                if current:
                    self.config = DisaggConfig.from_dict(current)
            except asyncio.CancelledError:
                return
            except Exception:  # noqa: BLE001 — still down; retry
                pass

    async def stop(self) -> None:
        if self._watch_task is not None:
            self._watch_task.cancel()
            try:
                await self._watch_task
            except asyncio.CancelledError:
                pass
            self._watch_task = None
        if self._watcher is not None:
            await self._watcher.aclose()


async def publish_config(hub, model: str, config: DisaggConfig) -> None:
    """Operator-side: push new thresholds (hot-reloads every watcher)."""
    await hub.kv_put(disagg_config_key(model), config.to_dict())
