"""Disaggregated prefill/decode serving (SURVEY §3.4, §7 stage 6).

Reference shape: long prefills are offloaded from decode workers to
dedicated prefill workers through a work queue; the prefill worker computes
prompt KV and pushes the blocks directly into the decode worker's cache
(reference: NIXL GPUDirect-RDMA inside the vLLM patch).  TPU-native
equivalent: the KV blocks travel host-staged over the service plane
(msgpack binary frames; ICI-direct device-to-device transfer applies when
prefill and decode share a pod slice), and land in the decode engine's
paged cache as *sealed, hash-addressed blocks* — so the decode pass sees
them as a prefix-cache hit and the scheduler needs no special remote mode.
"""

from .prefill_queue import PrefillQueue  # noqa: F401
from .router import DisaggConfig, DisaggregatedRouter  # noqa: F401
from .worker import DisaggDecodeWorker, PrefillWorkerLoop, KV_IMPORT_ENDPOINT  # noqa: F401
