"""Prefill work queue over the hub's ack/nack queue plane.

Reference semantics: examples/llm/utils/{nats_queue,prefill_queue}.py — a
JetStream work queue named per model; decode workers enqueue
RemotePrefillRequests, prefill workers pull with at-least-once handoff
(un-acked items requeue on failure, so a dying prefill worker never loses a
request).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ...runtime.transports.shard import hub_key


def prefill_queue_name(model: str) -> str:
    """Per-model prefill queue name (shard-map routed: DYN401)."""
    return hub_key("prefill", model)


class PrefillQueue:
    def __init__(self, hub, model: str):
        self.hub = hub
        self.queue_name = prefill_queue_name(model)

    async def enqueue(self, request: Dict[str, Any]) -> None:
        await self.hub.q_push(self.queue_name, request)

    async def dequeue(self):
        """Returns ``(request, ack_token)``; blocks until an item arrives."""
        return await self.hub.q_pop(self.queue_name)

    async def ack(self, token: str) -> bool:
        return await self.hub.q_ack(token)

    async def nack(self, token: str) -> bool:
        return await self.hub.q_nack(token)

    async def size(self) -> int:
        return await self.hub.q_len(self.queue_name)
