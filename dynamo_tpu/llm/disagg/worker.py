"""Disaggregated decode + prefill worker orchestration.

Flow (reference: SURVEY §3.4; examples/llm/components/{worker,
prefill_worker}.py semantics, re-designed around hash-addressed KV blocks):

decode side (``DisaggDecodeWorker`` wraps the decode TpuEngine):
1. request arrives; ask the engine how much prefix is already local;
2. DisaggregatedRouter decides local vs remote using (prefill_len −
   prefix_hit, queue depth);
3. remote: enqueue {token_ids, reply address} on the PrefillQueue and wait;
4. the prefill worker computes the prompt KV on its own engine, then calls
   this worker's ``kv_import`` endpoint with the block payload;
5. ``inject_blocks`` seals the blocks into the decode cache → the normal
   ``engine.generate`` admission sees a (near-)full prefix hit and decode
   proceeds — no special remote state inside the scheduler;
6. timeout or transfer failure falls back to local prefill (the request is
   never lost — at-least-once queue semantics cover prefill-worker death).

prefill side (``PrefillWorkerLoop``): pull → generate(max_tokens=1, KV
retained via prefix cache) → export blocks → push to the decode worker's
import endpoint → ack.
"""

from __future__ import annotations

import asyncio
import logging
import time
import uuid
from typing import Any, AsyncIterator, Dict, Optional

from ...runtime.client import Client
from ...runtime.engine import AsyncEngine, Context, ResponseStream
from ...runtime.tracing import parse_trace, span as trace_span
from ..protocols import PreprocessedRequest
from .prefill_queue import PrefillQueue
from .router import DisaggregatedRouter

logger = logging.getLogger(__name__)

KV_IMPORT_ENDPOINT = "kv_import"


class DisaggDecodeWorker(AsyncEngine):
    def __init__(
        self,
        engine,
        queue: PrefillQueue,
        router: DisaggregatedRouter,
        import_address: str,
        import_path: str,
        transfer_timeout: float = 30.0,
    ):
        self.engine = engine
        self.queue = queue
        self.router = router
        self.import_address = import_address
        self.import_path = import_path
        self.transfer_timeout = transfer_timeout
        self._pending: Dict[str, asyncio.Future] = {}
        self._covered: Dict[str, int] = {}  # per-transfer chunk accumulation
        # Planner drain/role-flip support: while draining, no NEW remote
        # prefills are enqueued (everything serves locally) so the pending
        # set can only shrink.
        self.draining = False
        self.remote_prefills = 0
        self.local_prefills = 0
        # Degraded-mode fallbacks: remote prefill abandoned (timeout, queue
        # unreachable, deadline pressure) and served by local prefill instead.
        self.degraded_fallbacks = 0
        from collections import deque as _deque

        # rolling remote-prefill wait wall (TTFT input), bounded
        self.transfer_ms = _deque(maxlen=1024)

    def stats(self) -> Dict[str, Any]:
        """Disaggregation counters (served at the worker's disagg_stats
        endpoint).  remote_prefills counts transfers that LANDED; a
        timeout-fallback increments local_prefills instead — so an e2e can
        assert the remote path actually ran (VERDICT r3 weak #5)."""
        ms = list(self.transfer_ms)
        return {
            "remote_prefills": self.remote_prefills,
            "local_prefills": self.local_prefills,
            "degraded_fallbacks": self.degraded_fallbacks,
            "pending_transfers": len(self._pending),
            "transfer_ms_p50": (
                sorted(ms)[len(ms) // 2] if ms else None
            ),
            "transfer_ms_last": ms[-1] if ms else None,
        }

    async def stats_handler(self, request: Context) -> AsyncIterator[Dict]:
        yield self.stats()

    # The engine handler served at the decode worker's kv_import endpoint.
    async def kv_import_handler(self, request: Context) -> AsyncIterator[Dict]:
        data = request.data
        tokens = data["token_ids"]
        # Tenant transfers (llm/tenancy) seal under the tenant's salted hash
        # chain — same identity the prefill engine sealed them under.
        # ``data["trace"]`` (omit-when-absent) joins the import to the
        # request's trace — the decode-side half of the transfer.
        with trace_span(
            parse_trace(data.get("trace")), "disagg.kv_import", "disagg"
        ) as ispan:
            covered = await self.engine.inject_blocks(
                tokens, data["payload"], data.get("salt")
            )
            ispan.set(tokens_covered=covered)
        self._covered[data["transfer_id"]] = (
            self._covered.get(data["transfer_id"], 0) + covered
        )
        # Chunked transfer: the future resolves on the LAST chunk; earlier
        # chunks are already sealed, so decode admission can begin while the
        # tail is still in flight.
        if data.get("last", True):
            total = self._covered.pop(data["transfer_id"], covered)
            fut = self._pending.pop(data["transfer_id"], None)
            if fut is not None and not fut.done():
                fut.set_result(total)
        yield {"ok": True, "tokens_covered": covered}

    async def transfer_direct(
        self, transfer_id: str, tokens, src_engine, salt=None
    ) -> int:
        """Same-process fast path: device→device block copy, no host staging
        (engine.transfer_blocks_device).  A zero-block transfer leaves the
        future pending — the sender retries and the decode side's timeout
        fallback covers permanent failure."""
        from ...engine.engine import transfer_blocks_device

        covered = await transfer_blocks_device(
            src_engine, self.engine, tokens, salt=salt
        )
        if covered > 0:
            fut = self._pending.pop(transfer_id, None)
            if fut is not None and not fut.done():
                fut.set_result(covered)
        return covered

    async def generate(self, request: Context) -> ResponseStream:
        pre = PreprocessedRequest.from_dict(request.data)
        tokens = pre.token_ids
        # Tenant requests (llm/tenancy) seal KV under a salted hash chain:
        # estimate with the same salt or the local-hit count is fiction.
        prefix_hit = self.engine.estimate_prefix_hit(
            tokens, (pre.annotations or {}).get("kv_salt")
        )
        # Cheap local length test first; the queue-depth RPC to the hub only
        # runs for prompts that are candidates for remote prefill.
        remote = (
            not self.draining
            and len(tokens) - prefix_hit > self.router.config.max_local_prefill_length
        )
        if remote:
            try:
                qsize = await self.queue.size()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — hub/queue unreachable
                # Degraded mode: can't even ask the queue — serve locally
                # rather than failing the request.
                logger.warning("prefill queue unreachable; degrading to local")
                self._degrade()
                remote = False
            else:
                remote = self.router.prefill_remote(len(tokens), prefix_hit, qsize)
        if remote:
            await self._remote_prefill(
                tokens,
                deadline=getattr(request.ctx, "deadline", None),
                annotations=pre.annotations,
            )
        else:
            self.local_prefills += 1
        return await self.engine.generate(request)

    async def drain(self, timeout: float = 30.0) -> None:
        """Quiesce remote-prefill activity (planner role flip): stop
        enqueueing new remote prefills, give in-flight transfers
        ``timeout`` to land, then resolve leftovers with 0 covered tokens
        — their requests fall back to local prefill, nothing is lost."""
        self.draining = True
        deadline = time.perf_counter() + timeout
        while self._pending and time.perf_counter() < deadline:
            await asyncio.sleep(0.02)
        for fut in list(self._pending.values()):
            if not fut.done():
                fut.set_result(0)
        self._pending.clear()
        self._covered.clear()

    def _degrade(self) -> None:
        self.local_prefills += 1
        self.degraded_fallbacks += 1
        from ...runtime.resilience import metrics as _metrics

        _metrics.degraded_prefills_total += 1

    async def _remote_prefill(self, tokens, deadline=None, annotations=None) -> None:
        # Tracing (runtime/tracing.py): the queue item's annotations carry
        # the trace, so the prefill worker's engine spans — and its
        # transfer span — join the request's trace; this side records the
        # decode worker's WAIT (the remote-prefill share of TTFT).
        wspan = trace_span(
            parse_trace((annotations or {}).get("trace")),
            "disagg.remote_prefill_wait", "disagg",
        )
        transfer_id = uuid.uuid4().hex
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[transfer_id] = fut
        item = {
            "transfer_id": transfer_id,
            "token_ids": list(tokens),
            "reply": {"address": self.import_address, "path": self.import_path},
        }
        if annotations:
            # Tenant identity (llm/tenancy): the prefill worker must run the
            # prompt under the same adapter + KV salt or the transferred
            # blocks would be wrong (adapter) or unaddressable (salt).
            # Omitted when empty so pre-tenancy queue consumers see the old
            # item shape.
            item["annotations"] = dict(annotations)
        try:
            await self.queue.enqueue(item)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — hub/queue unreachable
            self._pending.pop(transfer_id, None)
            logger.warning("prefill enqueue failed; degrading to local prefill")
            self._degrade()
            wspan.set(degraded="enqueue_failed").finish()
            return
        # The transfer wait never outlives the request's deadline: leave a
        # margin so local prefill still has budget to run after fallback.
        timeout = self.transfer_timeout
        if deadline is not None:
            timeout = min(timeout, max(deadline.remaining() * 0.5, 0.05))
        t0 = time.perf_counter()
        try:
            covered = await asyncio.wait_for(fut, timeout)
            self.remote_prefills += 1
            self.transfer_ms.append((time.perf_counter() - t0) * 1e3)
            wspan.set(tokens_covered=covered)
            logger.info("remote prefill covered %d tokens", covered)
        except asyncio.TimeoutError:
            # Fall back to local prefill; a late transfer still lands as a
            # harmless prefix-cache fill.
            self._pending.pop(transfer_id, None)
            self._covered.pop(transfer_id, None)  # orphaned chunk counts
            logger.warning("remote prefill timed out; prefilling locally")
            self._degrade()
            wspan.set(degraded="timeout")
        except BaseException as e:
            # Cancellation / future failed with an unexpected error: record
            # the wait span rather than leaking it unrecorded.
            wspan.set(error=type(e).__name__)
            raise
        finally:
            wspan.finish()


class PrefillWorkerLoop:
    """Dedicated prefill worker: drain the queue, compute KV, push blocks.

    Transfers stream in ``chunk_blocks``-block chunks (ordered per
    connection), so the decode side seals and can use early blocks while
    later ones are still in flight.  ``direct`` maps reply addresses of
    CO-LOCATED decode workers (same process / shared slice) to their
    DisaggDecodeWorker: those transfers take the device→device path and
    never stage in host RAM."""

    MAX_ATTEMPTS = 3
    # Adaptive chunk sizing targets this per-chunk transfer latency: large
    # enough to amortize framing, small enough that the decode side keeps
    # sealing (and decoding against) early blocks while the tail is in
    # flight.  On a fast intra-pod link the chunk grows toward max; over a
    # slow DCN hop it shrinks so pipelining stays fine-grained.
    TARGET_CHUNK_S = 0.05
    MIN_CHUNK_BLOCKS = 4
    MAX_CHUNK_BLOCKS = 256

    def __init__(
        self,
        engine,
        queue: PrefillQueue,
        chunk_blocks: int = 32,
        direct: Optional[Dict[str, "DisaggDecodeWorker"]] = None,
        adaptive_chunks: bool = True,
    ):
        self.engine = engine
        self.queue = queue
        self.chunk_blocks = max(1, chunk_blocks)  # default for new links
        # Adaptive size is PER DESTINATION: a co-pod link converges large
        # while a cross-region DCN link converges small — one shared value
        # would thrash between them.
        self._chunk_by_dest: Dict[str, int] = {}
        self.adaptive_chunks = adaptive_chunks
        self.direct = direct or {}
        self._task: Optional[asyncio.Task] = None
        self._clients: Dict[str, Client] = {}
        self._attempts: Dict[str, int] = {}
        self._busy = False  # an item is between dequeue and ack/nack
        self.handled = 0
        self.dropped = 0
        self.direct_transfers = 0

    def chunk_for(self, dest: str) -> int:
        return self._chunk_by_dest.get(dest, self.chunk_blocks)

    def _adapt_chunk(self, dest: str, blocks_sent: int, elapsed_s: float) -> None:
        """Move ``dest``'s chunk size toward TARGET_CHUNK_S of measured link
        time (half-step toward the bandwidth-implied size — smooths jitter)."""
        if not self.adaptive_chunks or blocks_sent <= 0 or elapsed_s <= 0:
            return
        ideal = blocks_sent * self.TARGET_CHUNK_S / elapsed_s
        stepped = (self.chunk_for(dest) + ideal) / 2
        self._chunk_by_dest[dest] = int(
            min(self.MAX_CHUNK_BLOCKS, max(self.MIN_CHUNK_BLOCKS, stepped))
        )

    async def start(self) -> "PrefillWorkerLoop":
        self._task = asyncio.get_running_loop().create_task(self._run())
        return self

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def drain(self, timeout: float = 30.0) -> None:
        """Graceful stop (planner role flip): let the in-flight item
        finish (bounded by ``timeout``), then stop pulling.  A cancel
        that does land mid-dequeue requeues via the hub's at-least-once
        pop path, so no request is ever lost."""
        deadline = time.perf_counter() + timeout
        while self._busy and time.perf_counter() < deadline:
            await asyncio.sleep(0.02)
        await self.stop()

    async def _run(self) -> None:
        try:
            while True:
                item, token = await self.queue.dequeue()
                self._busy = True
                tid = item.get("transfer_id", "?")
                try:
                    await self._handle(item)
                    await self.queue.ack(token)
                    self._attempts.pop(tid, None)
                    self.handled += 1
                    logger.info(
                        "prefill %s done (%d tokens)", tid, len(item["token_ids"])
                    )
                except asyncio.CancelledError:
                    await self.queue.nack(token)
                    raise
                except Exception:
                    # Bounded retry with backoff: the decode side falls back
                    # to local prefill on timeout anyway, so a poisoned item
                    # (dead reply target, evicted blocks) is dropped rather
                    # than spun on forever.
                    attempts = self._attempts.get(tid, 0) + 1
                    self._attempts[tid] = attempts
                    if attempts >= self.MAX_ATTEMPTS:
                        logger.exception(
                            "prefill %s failed %d times; dropping", tid, attempts
                        )
                        await self.queue.ack(token)
                        self._attempts.pop(tid, None)
                        self.dropped += 1
                    else:
                        logger.warning("prefill %s failed; requeueing", tid)
                        await self.queue.nack(token)
                        await asyncio.sleep(0.2 * attempts)
                finally:
                    self._busy = False
        except asyncio.CancelledError:
            pass

    async def _handle(self, item: Dict[str, Any]) -> None:
        tokens = item["token_ids"]
        # Tenant items (llm/tenancy) carry the request annotations: the
        # prefill runs under the same adapter (correct KV contents) and
        # seals under the same salted hash chain (addressable transfer).
        annotations = dict(item.get("annotations") or {})
        salt = annotations.get("kv_salt")
        # Tracing: annotations.trace rides into the engine request below
        # (its prefill spans join the originating request's trace); this
        # side additionally records the block transfer back to the decode
        # worker.
        tc = parse_trace(annotations.get("trace"))
        pre = PreprocessedRequest(token_ids=list(tokens), annotations=annotations)
        pre.stop_conditions.max_tokens = 1
        pre.stop_conditions.ignore_eos = True
        # Run the prompt through the engine: prefix caching retains the KV
        # blocks (sealed, hash-addressed) after the request completes.
        stream = await self.engine.generate(Context(pre.to_dict()))
        async for _ in stream:
            pass
        reply = item["reply"]

        worker = self.direct.get(reply["address"])
        if worker is not None:
            with trace_span(
                tc, "disagg.prefill_transfer", "disagg-prefill",
                attrs={"direct": True},
            ):
                covered = await worker.transfer_direct(
                    item["transfer_id"], tokens, self.engine, salt=salt
                )
            if covered == 0:
                raise RuntimeError("direct transfer moved no blocks")
            self.direct_transfers += 1
            return

        client = self._client_for(reply["address"], reply["path"])
        dest = reply["address"]
        total_blocks = len(tokens) // self.engine.cfg.block_size
        start = 0
        tspan = trace_span(
            tc, "disagg.prefill_transfer", "disagg-prefill",
            attrs={"dest": dest},
        )
        while True:
            chunk = self.chunk_for(dest)
            payload = await self.engine.export_prompt_blocks(
                tokens, start_block=start, max_blocks=chunk, salt=salt
            )
            if payload is None:
                if start == 0:
                    raise RuntimeError(
                        "prompt blocks missing after prefill (evicted?)"
                    )
                # Partial run (tail evicted mid-transfer): finalize with an
                # empty chunk so the decode side resolves with what landed
                # and prefills the remainder locally.
                resp = await client.generate(
                    Context(
                        {
                            "transfer_id": item["transfer_id"],
                            "token_ids": list(tokens),
                            "payload": {"n_blocks": 0},
                            "last": True,
                            **({"salt": salt} if salt else {}),
                            **(
                                {"trace": tc.to_dict()}
                                if tc is not None
                                else {}
                            ),
                        }
                    )
                )
                async for _ack in resp:
                    pass
                break
            start += payload["n_blocks"]
            last = start >= total_blocks or payload["n_blocks"] < chunk
            t0 = time.perf_counter()
            resp = await client.generate(
                Context(
                    {
                        "transfer_id": item["transfer_id"],
                        "token_ids": list(tokens),
                        "payload": payload,
                        "last": last,
                        **({"salt": salt} if salt else {}),
                        **({"trace": tc.to_dict()} if tc is not None else {}),
                    }
                )
            )
            async for _ack in resp:
                pass
            self._adapt_chunk(
                dest, payload["n_blocks"], time.perf_counter() - t0
            )
            if last:
                break
        tspan.set(blocks=start).finish()

    def _client_for(self, address: str, path: str) -> Client:
        key = f"{address}/{path}"
        if key not in self._clients:
            self._clients[key] = Client.static(address, path)
        return self._clients[key]
