"""HTTP-edge Prometheus metrics.

Reference semantics: lib/llm/src/http/service/metrics.rs:57-128,319 —
``{prefix}_http_service_{requests_total, inflight_requests,
request_duration_seconds, time_to_first_token_seconds,
inter_token_latency_seconds}`` with status labels
``success | client_drop | rejected | error``, and a RAII ``InflightGuard``
that records duration + status when dropped.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

from ..labels import escape_label

REQUEST_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
TOKEN_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5)


class Status:
    SUCCESS = "success"
    CLIENT_DROP = "client_drop"
    REJECTED = "rejected"
    ERROR = "error"


class RollingWindow:
    """Bounded rolling sample window with percentile queries.

    Histograms answer "distribution since process start"; the planner's
    SLO loop needs "distribution right now" — a window of the most recent
    observations, cheap to query at scrape/publish time."""

    def __init__(self, maxlen: int = 2048):
        self._xs: Deque[float] = deque(maxlen=maxlen)

    def observe(self, x: float) -> None:
        self._xs.append(x)

    def percentile(self, p: float) -> float:
        if not self._xs:
            return 0.0
        xs = sorted(self._xs)
        return xs[min(len(xs) - 1, int(len(xs) * p))]

    def __len__(self) -> int:
        return len(self._xs)


class TimedWindow:
    """Sample window bounded by AGE, not count — the brownout ladder's
    latency input (llm/qos.py).  A count-bounded window (RollingWindow)
    holds a spike's samples until enough NEW traffic pushes them out: at
    zero traffic it never drains, so pressure reads high forever and the
    ladder can never recover.  Here samples expire after ``max_age_s``
    regardless of traffic, so "the spike ended" is observable."""

    def __init__(self, max_age_s: float = 10.0, maxlen: int = 4096,
                 clock=time.monotonic):
        self.max_age_s = max_age_s
        self._clock = clock
        self._xs: Deque[Tuple[float, float]] = deque(maxlen=maxlen)

    def observe(self, x: float) -> None:
        self._xs.append((self._clock(), x))

    def _prune(self) -> None:
        horizon = self._clock() - self.max_age_s
        while self._xs and self._xs[0][0] < horizon:
            self._xs.popleft()

    def percentile(self, p: float) -> Optional[float]:
        """p-quantile of the live samples, or None when the window is
        empty (signal absent — distinct from 'fast')."""
        self._prune()
        if not self._xs:
            return None
        xs = sorted(x for _, x in self._xs)
        return xs[min(len(xs) - 1, int(len(xs) * p))]

    def __len__(self) -> int:
        self._prune()
        return len(self._xs)


class Metrics:
    def __init__(self, prefix: str = "dynamo_tpu"):
        self.registry = CollectorRegistry()
        ns = f"{prefix}_http_service"
        self.requests_total = Counter(
            f"{ns}_requests_total",
            "Total requests by model/endpoint/status",
            ["model", "endpoint", "request_type", "status"],
            registry=self.registry,
        )
        self.inflight = Gauge(
            f"{ns}_inflight_requests",
            "Currently in-flight requests",
            ["model", "endpoint"],
            registry=self.registry,
        )
        self.request_duration = Histogram(
            f"{ns}_request_duration_seconds",
            "End-to-end request duration",
            ["model", "endpoint"],
            buckets=REQUEST_BUCKETS,
            registry=self.registry,
        )
        self.ttft = Histogram(
            f"{ns}_time_to_first_token_seconds",
            "Time to first token (streaming)",
            ["model", "endpoint"],
            buckets=REQUEST_BUCKETS,
            registry=self.registry,
        )
        self.itl = Histogram(
            f"{ns}_inter_token_latency_seconds",
            "Inter-token latency (streaming)",
            ["model", "endpoint"],
            buckets=TOKEN_BUCKETS,
            registry=self.registry,
        )
        self.output_tokens = Counter(
            f"{ns}_output_tokens_total",
            "Total output tokens produced",
            ["model", "endpoint"],
            registry=self.registry,
        )
        # Rolling-window percentile gauges (the planner's SLO input): the
        # histograms above accumulate since start; these answer "now".
        self.ttft_p50_gauge = Gauge(
            f"{ns}_ttft_p50_seconds",
            "Rolling-window TTFT p50",
            ["model", "endpoint"],
            registry=self.registry,
        )
        self.ttft_p95_gauge = Gauge(
            f"{ns}_ttft_p95_seconds",
            "Rolling-window TTFT p95",
            ["model", "endpoint"],
            registry=self.registry,
        )
        self.itl_p50_gauge = Gauge(
            f"{ns}_itl_p50_seconds",
            "Rolling-window inter-token-latency p50",
            ["model", "endpoint"],
            registry=self.registry,
        )
        self.itl_p95_gauge = Gauge(
            f"{ns}_itl_p95_seconds",
            "Rolling-window inter-token-latency p95",
            ["model", "endpoint"],
            registry=self.registry,
        )
        # (model, endpoint) → (ttft window, itl window)
        self._windows: Dict[Tuple[str, str], Tuple[RollingWindow, RollingWindow]] = {}
        # Age-bounded TTFT window across all models: the brownout ladder's
        # latency signal (llm/qos.py) — must DRAIN when the spike ends,
        # which the count-bounded windows above deliberately do not.
        self.ttft_recent = TimedWindow(max_age_s=10.0)

    def recent_ttft_p95_ms(self) -> Optional[float]:
        """p95 TTFT over the last ``ttft_recent.max_age_s`` seconds, or
        None when no request produced a first token in that span."""
        p = self.ttft_recent.percentile(0.95)
        return None if p is None else p * 1e3

    def window(self, model: str, endpoint: str) -> Tuple[RollingWindow, RollingWindow]:
        key = (model, endpoint)
        if key not in self._windows:
            self._windows[key] = (RollingWindow(), RollingWindow())
        return self._windows[key]

    def guard(self, model: str, endpoint: str, request_type: str) -> "InflightGuard":
        return InflightGuard(self, model, endpoint, request_type)

    def _update_quantile_gauges(self) -> None:
        for (model, endpoint), (ttft_w, itl_w) in self._windows.items():
            self.ttft_p50_gauge.labels(model, endpoint).set(ttft_w.percentile(0.5))
            self.ttft_p95_gauge.labels(model, endpoint).set(ttft_w.percentile(0.95))
            self.itl_p50_gauge.labels(model, endpoint).set(itl_w.percentile(0.5))
            self.itl_p95_gauge.labels(model, endpoint).set(itl_w.percentile(0.95))

    def edge_slo_snapshot(self) -> Dict[str, float]:
        """Merged-over-models rolling percentiles in ms (what the edge
        publishes to the planner on the ``slo_metrics`` subject)."""
        ttft_all = RollingWindow(maxlen=4096)
        itl_all = RollingWindow(maxlen=4096)
        for ttft_w, itl_w in self._windows.values():
            for x in ttft_w._xs:
                ttft_all.observe(x)
            for x in itl_w._xs:
                itl_all.observe(x)
        return {
            "ttft_p50_ms": ttft_all.percentile(0.5) * 1e3,
            "ttft_p95_ms": ttft_all.percentile(0.95) * 1e3,
            "itl_p50_ms": itl_all.percentile(0.5) * 1e3,
            "itl_p95_ms": itl_all.percentile(0.95) * 1e3,
            "ttft_samples": float(len(ttft_all)),
            "itl_samples": float(len(itl_all)),
        }

    def render(self) -> bytes:
        self._update_quantile_gauges()
        return generate_latest(self.registry)


class SpecDecodeMetrics:
    """Speculative-decoding counters + derived gauges (engine/spec.py).

    Module-level singleton rendered as Prometheus text and appended to the
    ``/metrics`` exposition (same pattern as runtime.resilience.metrics /
    planner.pmetrics) — dependency-free so the engine layer can update it
    without touching the prometheus_client registry."""

    def __init__(self):
        self.drafted_total = 0  # draft tokens submitted for verification
        self.accepted_total = 0  # draft tokens accepted
        self.emitted_total = 0  # tokens committed by spec dispatches (incl. bonus)
        self.dispatches_total = 0  # unified verification dispatches
        self.fallback_total = 0  # plans where spec stood down for the fused pipeline

    @property
    def acceptance_rate(self) -> float:
        return (
            self.accepted_total / self.drafted_total
            if self.drafted_total
            else 0.0
        )

    @property
    def tokens_per_dispatch(self) -> float:
        return (
            self.emitted_total / self.dispatches_total
            if self.dispatches_total
            else 0.0
        )

    def reset(self) -> None:
        self.__init__()

    def snapshot(self) -> Dict[str, float]:
        return {
            "drafted_total": float(self.drafted_total),
            "accepted_total": float(self.accepted_total),
            "emitted_total": float(self.emitted_total),
            "dispatches_total": float(self.dispatches_total),
            "fallback_total": float(self.fallback_total),
            "acceptance_rate": self.acceptance_rate,
            "tokens_per_dispatch": self.tokens_per_dispatch,
        }

    def render(self, prefix: str = "dynamo_tpu") -> str:
        ns = f"{prefix}_spec_decode"
        lines = []

        def emit(name: str, kind: str, help_: str, value) -> None:
            lines.append(f"# HELP {ns}_{name} {help_}")
            lines.append(f"# TYPE {ns}_{name} {kind}")
            lines.append(f"{ns}_{name} {value}")

        emit("drafted_tokens_total", "counter",
             "Draft tokens submitted for in-step verification",
             self.drafted_total)
        emit("accepted_tokens_total", "counter",
             "Draft tokens accepted (sampled-stream match)",
             self.accepted_total)
        emit("emitted_tokens_total", "counter",
             "Tokens committed by speculative dispatches (incl. the bonus "
             "sample)", self.emitted_total)
        emit("dispatches_total", "counter",
             "Unified verification dispatches", self.dispatches_total)
        emit("fallback_total", "counter",
             "Plans where speculation stood down for the fused pipeline",
             self.fallback_total)
        emit("acceptance_rate", "gauge",
             "accepted/drafted since start", round(self.acceptance_rate, 6))
        emit("tokens_per_dispatch", "gauge",
             "Committed tokens per verification dispatch",
             round(self.tokens_per_dispatch, 6))
        return "\n".join(lines) + "\n"


spec_metrics = SpecDecodeMetrics()


class MigrationMetrics:
    """Live-sequence-migration counters (llm/migration).

    Module-level singleton rendered as Prometheus text and appended to the
    ``/metrics`` exposition (same pattern as ``spec_metrics``): the worker
    process updates plain attributes; no registry dependency."""

    def __init__(self):
        self.started_total = 0       # migrate_out attempts begun
        self.completed_total = 0     # cutovers that landed
        self.rolled_back_total = 0   # phase-2 failures (source kept authority)
        self.aborted_total = 0       # phase-1 aborts (seq finished / target cold)
        self.migrated_in_total = 0   # commits accepted on the target side
        self.blocks_total = 0        # KV blocks pushed (phase 1 + final delta)
        self.bytes_total = 0         # payload bytes pushed
        self.cutover_pause_ms = RollingWindow(maxlen=512)  # freeze→cutover wall

    def reset(self) -> None:
        self.__init__()

    def snapshot(self) -> Dict[str, float]:
        return {
            "started_total": float(self.started_total),
            "completed_total": float(self.completed_total),
            "rolled_back_total": float(self.rolled_back_total),
            "aborted_total": float(self.aborted_total),
            "migrated_in_total": float(self.migrated_in_total),
            "blocks_total": float(self.blocks_total),
            "bytes_total": float(self.bytes_total),
            "cutover_pause_ms_p50": self.cutover_pause_ms.percentile(0.5),
            "cutover_pause_ms_p95": self.cutover_pause_ms.percentile(0.95),
        }

    def render(self, prefix: str = "dynamo_tpu") -> str:
        ns = f"{prefix}_migration"
        lines = []

        def emit(name: str, kind: str, help_: str, value) -> None:
            lines.append(f"# HELP {ns}_{name} {help_}")
            lines.append(f"# TYPE {ns}_{name} {kind}")
            lines.append(f"{ns}_{name} {value}")

        emit("started_total", "counter",
             "Live migrations begun (source side)", self.started_total)
        emit("completed_total", "counter",
             "Live migrations cut over successfully", self.completed_total)
        emit("rolled_back_total", "counter",
             "Migrations rolled back in the final-delta phase "
             "(source stayed authoritative)", self.rolled_back_total)
        emit("aborted_total", "counter",
             "Migrations abandoned in the copy phase", self.aborted_total)
        emit("migrated_in_total", "counter",
             "Migration commits accepted (target side)",
             self.migrated_in_total)
        emit("kv_blocks_total", "counter",
             "KV blocks pushed by migrations", self.blocks_total)
        emit("kv_bytes_total", "counter",
             "KV payload bytes pushed by migrations", self.bytes_total)
        emit("cutover_pause_ms_p50", "gauge",
             "Rolling p50 of the freeze-to-cutover pause",
             round(self.cutover_pause_ms.percentile(0.5), 3))
        emit("cutover_pause_ms_p95", "gauge",
             "Rolling p95 of the freeze-to-cutover pause",
             round(self.cutover_pause_ms.percentile(0.95), 3))
        return "\n".join(lines) + "\n"


migration_metrics = MigrationMetrics()


class TenancyMetrics:
    """Multi-tenancy counters (llm/tenancy): grammar-constrained decoding +
    batched multi-LoRA.  Module-level singleton rendered as Prometheus text
    and appended to ``/metrics`` (same pattern as ``spec_metrics``)."""

    def __init__(self):
        # structured output
        self.grammar_requests_total = 0   # requests carrying a constraint
        self.grammar_compiles_total = 0   # automaton compiles (cache misses)
        self.grammar_cache_hits_total = 0
        self.grammar_masked_rows_total = 0  # device rows sampled under a mask
        self.grammar_violations_total = 0   # defensive: inadmissible accepts
        # hash-first wire protocol (engine content-hash LRU)
        self.grammar_hash_hits_total = 0    # stubs resolved with zero bytes
        self.grammar_hash_misses_total = 0  # stubs that forced a full resend
        self.grammar_full_resends_total = 0  # preprocessor-side fallbacks
        self.grammar_stub_dispatches_total = 0  # stubs accepted first try
        # multi-LoRA
        self.adapters_registered = 0      # gauge: host-pool size
        self.adapter_promotions = 0       # host→device slot writes
        self.adapter_evictions = 0        # resident slots reclaimed
        self.adapter_requests_total = 0   # requests routed to an adapter
        self.adapter_not_found_total = 0  # unknown-model rejections

    def reset(self) -> None:
        self.__init__()

    def snapshot(self) -> Dict[str, float]:
        return {k: float(v) for k, v in vars(self).items()}

    def render(self, prefix: str = "dynamo_tpu") -> str:
        ns = f"{prefix}_tenancy"
        lines = []

        def emit(name: str, kind: str, help_: str, value) -> None:
            lines.append(f"# HELP {ns}_{name} {help_}")
            lines.append(f"# TYPE {ns}_{name} {kind}")
            lines.append(f"{ns}_{name} {value}")

        emit("grammar_requests_total", "counter",
             "Requests with a structured-output constraint",
             self.grammar_requests_total)
        emit("grammar_compiles_total", "counter",
             "Token-mask automaton compiles (cache misses)",
             self.grammar_compiles_total)
        emit("grammar_cache_hits_total", "counter",
             "Constraint compile-cache hits", self.grammar_cache_hits_total)
        emit("grammar_masked_rows_total", "counter",
             "Device rows sampled under a grammar mask",
             self.grammar_masked_rows_total)
        emit("grammar_violations_total", "counter",
             "Accepted tokens the mask should have forbidden (defensive; "
             "always 0)", self.grammar_violations_total)
        emit("grammar_hash_hits_total", "counter",
             "Hash-only grammar stubs resolved from the engine LRU",
             self.grammar_hash_hits_total)
        emit("grammar_hash_misses_total", "counter",
             "Hash-only grammar stubs that forced a full-table resend",
             self.grammar_hash_misses_total)
        emit("grammar_full_resends_total", "counter",
             "Constrained dispatches that fell back to the full edge table",
             self.grammar_full_resends_total)
        emit("grammar_stub_dispatches_total", "counter",
             "Constrained dispatches served hash-only end to end",
             self.grammar_stub_dispatches_total)
        emit("lora_adapters_registered", "gauge",
             "Adapters in the host pool", self.adapters_registered)
        emit("lora_promotions_total", "counter",
             "Adapter host-to-device slot promotions", self.adapter_promotions)
        emit("lora_evictions_total", "counter",
             "Resident adapter slots reclaimed", self.adapter_evictions)
        emit("lora_requests_total", "counter",
             "Requests served through a LoRA adapter",
             self.adapter_requests_total)
        emit("lora_model_not_found_total", "counter",
             "Requests naming an unregistered model/adapter",
             self.adapter_not_found_total)
        return "\n".join(lines) + "\n"


tenancy_metrics = TenancyMetrics()


class QosMetrics:
    """QoS/overload-control counters (llm/qos.py): per-tenant quota sheds,
    brownout rung + transitions, priority sheds.  Module-level singleton
    rendered as Prometheus text and appended to ``/metrics`` (same pattern
    as ``spec_metrics``)."""

    def __init__(self):
        self.brownout_rung = 0  # gauge: current ladder rung
        self.brownout_transitions_total = 0
        self.quota_shed_total = 0       # 429s from tenant token buckets
        self.batch_shed_total = 0       # rung-3 batch-class sheds
        self.interactive_shed_total = 0  # rung-4 interactive overflow 503s
        self.capped_requests_total = 0  # rung-1 max_tokens caps applied
        self.spec_standdowns_total = 0  # rung-2 spec-decode opt-outs applied
        # tenant → sheds (bounded: the render sorts and truncates)
        self.shed_by_tenant: Dict[str, int] = {}

    def shed_tenant(self, tenant: str) -> None:
        if len(self.shed_by_tenant) < 256 or tenant in self.shed_by_tenant:
            self.shed_by_tenant[tenant] = self.shed_by_tenant.get(tenant, 0) + 1

    def reset(self) -> None:
        self.__init__()

    def snapshot(self) -> Dict[str, float]:
        return {
            k: float(v) for k, v in vars(self).items() if isinstance(v, (int, float))
        }

    def render(self, prefix: str = "dynamo_tpu") -> str:
        ns = f"{prefix}_qos"
        lines = []

        def emit(name: str, kind: str, help_: str, value) -> None:
            lines.append(f"# HELP {ns}_{name} {help_}")
            lines.append(f"# TYPE {ns}_{name} {kind}")
            lines.append(f"{ns}_{name} {value}")

        emit("brownout_rung", "gauge",
             "Current brownout ladder rung (0=normal .. 4=shed-interactive)",
             self.brownout_rung)
        emit("brownout_transitions_total", "counter",
             "Brownout rung transitions", self.brownout_transitions_total)
        emit("quota_shed_total", "counter",
             "Requests shed by tenant token buckets (429)",
             self.quota_shed_total)
        emit("batch_shed_total", "counter",
             "Batch-class requests shed by brownout rung >= 3",
             self.batch_shed_total)
        emit("interactive_shed_total", "counter",
             "Interactive requests shed at rung 4 (admission saturated)",
             self.interactive_shed_total)
        emit("capped_requests_total", "counter",
             "Requests with max_tokens capped by brownout rung >= 1",
             self.capped_requests_total)
        emit("spec_standdowns_total", "counter",
             "Requests with spec-decode stood down by brownout rung >= 2",
             self.spec_standdowns_total)
        lines.append(f"# HELP {ns}_shed_by_tenant_total Sheds per tenant")
        lines.append(f"# TYPE {ns}_shed_by_tenant_total counter")
        for tenant, n in sorted(self.shed_by_tenant.items()):
            # Tenant ids come off the wire (x-tenant header): escape the
            # Prometheus label syntax so a crafted id cannot inject rows
            # into the exposition.  (Credential-sourced ids are already
            # hashed at resolution — llm/qos.py resolve_tenant.)
            safe = escape_label(tenant)
            lines.append(f'{ns}_shed_by_tenant_total{{tenant="{safe}"}} {n}')
        return "\n".join(lines) + "\n"


qos_metrics = QosMetrics()


class EngineDispatchMetrics:
    """Decode-pipeline dispatch health (engine/pipeline.py): per-kind
    dispatch counts/wall/percentiles from the engine's step_trace, plus the
    continuous-batching session gauges (sessions, rebuilds, in-loop
    admissions/retirements, fused-loop host-gap fraction).

    The engine owns the trace, so this singleton holds a SOURCE callable
    (``engine.dispatch_summary``) wired by whoever colocates an engine with
    the HTTP edge (cli ``run in=http out=tpu`` — same pattern as the
    brownout ladder's ``kv_usage_fn``); rendered as Prometheus text and
    appended to ``/metrics`` like the other module singletons.  Without a
    source it renders nothing, so remote-engine edges are unaffected."""

    def __init__(self):
        self._source = None

    def set_source(self, source) -> None:
        """``source() -> engine.dispatch_summary()`` dict, or None to
        detach."""
        self._source = source

    def reset(self) -> None:
        self.__init__()

    def host_gap_frac(self) -> Optional[float]:
        """The colocated engine's fused-decode host-gap fraction, or None
        without a wired source (remote-engine edge) — the planner-side
        drift signal (EdgeSloPublisher ``host_gap``)."""
        if self._source is None:
            return None
        try:
            s = self._source()
        except Exception:  # noqa: BLE001 — engine mid-teardown
            return None
        gap = (s.get("pipeline") or {}).get("host_gap_frac")
        return float(gap) if isinstance(gap, (int, float)) else None

    def render(self, prefix: str = "dynamo_tpu") -> str:
        if self._source is None:
            return ""
        try:
            s = self._source()
        except Exception:  # engine mid-teardown: drop this scrape's section
            return ""
        ns = f"{prefix}_engine_dispatch"
        # Per-kind stats come from the engine's BOUNDED step_trace window
        # (deque maxlen) — they can shrink as old entries evict, so they
        # are gauges, never counters (a decreasing counter breaks rate()).
        lines = [
            f"# HELP {ns}_window_dispatches Device dispatches per step "
            "kind over the bounded trace window",
            f"# TYPE {ns}_window_dispatches gauge",
        ]
        kinds = sorted(s.get("kinds", {}).items())
        for kind, v in kinds:
            lines.append(
                f'{ns}_window_dispatches{{kind="{escape_label(kind)}"}} '
                f'{v["dispatches"]}'
            )
        lines.append(f"# HELP {ns}_window_wall_seconds Wall per step kind "
                     "over the bounded trace window")
        lines.append(f"# TYPE {ns}_window_wall_seconds gauge")
        for kind, v in kinds:
            lines.append(f'{ns}_window_wall_seconds{{kind="'
                         f'{escape_label(kind)}"}} {v["wall_s"]}')
        for q in ("p50", "p99"):
            lines.append(f"# HELP {ns}_{q}_ms {q} dispatch latency per "
                         "step kind (over the bounded trace window)")
            lines.append(f"# TYPE {ns}_{q}_ms gauge")
            for kind, v in kinds:
                lines.append(f'{ns}_{q}_ms{{kind="{escape_label(kind)}"}} '
                             f'{v[f"{q}_ms"]}')
        pipe = s.get("pipeline", {})

        def emit(name: str, kind: str, help_: str, value) -> None:
            lines.append(f"# HELP {ns}_{name} {help_}")
            lines.append(f"# TYPE {ns}_{name} {kind}")
            lines.append(f"{ns}_{name} {value}")

        emit("pipeline_sessions_total", "counter",
             "Fused decode pipeline sessions begun",
             pipe.get("sessions", 0))
        emit("pipeline_rebuilds_total", "counter",
             "Sessions drained by a rebuild event (incompatible change)",
             pipe.get("rebuilds", 0))
        emit("continuous_admissions_total", "counter",
             "Sequences admitted into a live fused session (no drain)",
             pipe.get("continuous_admissions", 0))
        emit("continuous_retired_total", "counter",
             "Rows retired from a live fused session (no drain)",
             pipe.get("continuous_retired", 0))
        emit("pipeline_wall_seconds_total", "counter",
             "Cumulative fused-session wall time",
             pipe.get("wall_s", 0.0))
        emit("host_gap_frac", "gauge",
             "Fraction of fused-session wall not covered by decode "
             "dispatch/wait device work", pipe.get("host_gap_frac", 0.0))
        # Decode-stall watchdog (decode_stall_s / DYN_DECODE_STALL_S;
        # engine/pipeline.py _await_device).  OUTSIDE the _dispatch ns —
        # the alert rule keys on this exact name.
        lines.append(f"# HELP {prefix}_engine_stall_total Token fetches "
                     "that exceeded the decode-stall threshold")
        lines.append(f"# TYPE {prefix}_engine_stall_total counter")
        lines.append(f"{prefix}_engine_stall_total {pipe.get('stalls', 0)}")
        # Which decode kernel serves this engine (info-style gauge).
        kern = s.get("decode_kernel", "")
        if kern:
            lines.append(f"# HELP {ns}_decode_kernel_info Active decode "
                         "attention kernel (DYN_DECODE_KERNEL)")
            lines.append(f"# TYPE {ns}_decode_kernel_info gauge")
            lines.append(
                f'{ns}_decode_kernel_info{{kernel="{escape_label(kern)}"}} 1'
            )
        pkern = s.get("prefill_kernel", "")
        if pkern:
            lines.append(f"# HELP {ns}_prefill_kernel_info Active prefill "
                         "attention kernel (DYN_PREFILL_KERNEL)")
            lines.append(f"# TYPE {ns}_prefill_kernel_info gauge")
            lines.append(
                f'{ns}_prefill_kernel_info{{kernel="{escape_label(pkern)}"}} 1'
            )
        # Prefill-chunk latency summary (engine.prefill_summary): cumulative
        # _sum/_count are true counters; the quantiles come from the
        # bounded per-chunk trace window (gauges in counter clothing, same
        # caveat as the per-kind stats above).  OUTSIDE the _dispatch ns —
        # the CI gate and loadgen scrape key on this exact name.
        pf = s.get("prefill", {})
        if pf:
            pn = f"{prefix}_prefill_chunk_seconds"
            lines.append(f"# HELP {pn} Prefill chunk dispatch wall time")
            lines.append(f"# TYPE {pn} summary")
            for q, key in (("0.5", "p50_ms"), ("0.99", "p99_ms")):
                lines.append(
                    f'{pn}{{quantile="{escape_label(q)}"}} '
                    f"{pf.get(key, 0.0) / 1e3}"
                )
            lines.append(f"{pn}_sum {pf.get('wall_s', 0.0)}")
            lines.append(f"{pn}_count {pf.get('chunks', 0)}")
            lines.append(
                f"# HELP {prefix}_prefill_tokens_total Prompt tokens "
                "computed by prefill chunks")
            lines.append(f"# TYPE {prefix}_prefill_tokens_total counter")
            lines.append(
                f"{prefix}_prefill_tokens_total {pf.get('prompt_tokens', 0)}"
            )
        return "\n".join(lines) + "\n"


engine_dispatch_metrics = EngineDispatchMetrics()


class KvTierMetrics:
    """Tiered-KV-cache counters + gauges (docs/kv_tiering.md): per-tier
    bytes/blocks, restore/demote/promote/pull activity, restore + pull
    latency percentiles.  Module-level singleton rendered as Prometheus
    text and appended to ``/metrics`` (same pattern as ``spec_metrics``).

    Counters are updated inline by the engine/puller; the per-tier
    bytes/blocks GAUGES come from a source callable
    (``engine.kv_tier_summary`` — wired like EngineDispatchMetrics by
    whoever colocates an engine with the HTTP edge), so remote-engine
    edges render counters only."""

    def __init__(self):
        self._source = None
        # restore path (host/disk → HBM ahead of admission)
        self.restore_hits_total = 0      # requests that restored ≥1 block
        self.restore_misses_total = 0    # tiered restore attempts, 0 blocks
        self.restored_blocks_total = 0   # host→HBM scatters
        self.promoted_blocks_total = 0   # disk→host promotions
        self.prefetched_blocks_total = 0  # promotions driven by kv_prefetch
        # cross-worker pull (llm/kv_router/pull.py)
        self.pulls_started_total = 0
        self.pulls_completed_total = 0
        self.pulls_failed_total = 0      # any degraded-to-local outcome
        self.pulled_blocks_total = 0
        self.pulled_bytes_total = 0
        self.restore_latency_ms = RollingWindow(maxlen=1024)
        self.pull_latency_ms = RollingWindow(maxlen=512)

    def set_source(self, source) -> None:
        """``source() -> engine.kv_tier_summary()`` dict, or None."""
        self._source = source

    def reset(self) -> None:
        self.__init__()

    def tier_summary(self) -> Dict[str, object]:
        """The engine's per-tier gauges ({} without a wired source) —
        shared by render() and the edge SLO publication."""
        if self._source is None:
            return {}
        try:
            return self._source() or {}
        except Exception:  # noqa: BLE001 — engine mid-teardown
            return {}

    def snapshot(self) -> Dict[str, float]:
        out = {
            k: float(v) for k, v in vars(self).items() if isinstance(v, (int, float))
        }
        out["restore_latency_ms_p50"] = self.restore_latency_ms.percentile(0.5)
        out["restore_latency_ms_p99"] = self.restore_latency_ms.percentile(0.99)
        out["pull_latency_ms_p50"] = self.pull_latency_ms.percentile(0.5)
        out["pull_latency_ms_p99"] = self.pull_latency_ms.percentile(0.99)
        return out

    def render(self, prefix: str = "dynamo_tpu") -> str:
        ns = f"{prefix}_kv_tier"
        lines = []

        def emit(name: str, kind: str, help_: str, value) -> None:
            lines.append(f"# HELP {ns}_{name} {help_}")
            lines.append(f"# TYPE {ns}_{name} {kind}")
            lines.append(f"{ns}_{name} {value}")

        summary = self.tier_summary()
        tiers = [t for t in ("hbm", "host", "disk", "objstore") if t in summary]
        if tiers:
            lines.append(f"# HELP {ns}_blocks Sealed KV blocks per tier")
            lines.append(f"# TYPE {ns}_blocks gauge")
            for t in tiers:  # bounded constant label set
                lines.append(
                    f'{ns}_blocks{{tier="{escape_label(t)}"}} '
                    f'{summary[t]["blocks"]}'
                )
            lines.append(f"# HELP {ns}_bytes KV bytes per tier")
            lines.append(f"# TYPE {ns}_bytes gauge")
            for t in tiers:
                lines.append(
                    f'{ns}_bytes{{tier="{escape_label(t)}"}} '
                    f'{summary[t]["bytes"]}'
                )
            emit("prefix_hit_rate", "gauge",
                 "Engine prefix-cache hit rate (matched/looked-up blocks)",
                 round(float(summary.get("prefix_hit_rate", 0.0)), 6))
        emit("restore_hits_total", "counter",
             "Requests that restored >=1 prefix block from a lower tier",
             self.restore_hits_total)
        emit("restore_misses_total", "counter",
             "Tiered restore attempts that found nothing restorable",
             self.restore_misses_total)
        emit("restored_blocks_total", "counter",
             "Blocks scattered host->HBM ahead of admission",
             self.restored_blocks_total)
        emit("promoted_blocks_total", "counter",
             "Blocks promoted disk->host", self.promoted_blocks_total)
        emit("prefetched_blocks_total", "counter",
             "disk->host promotions driven by the kv_prefetch plane",
             self.prefetched_blocks_total)
        emit("pulls_started_total", "counter",
             "Cross-worker prefix pulls attempted", self.pulls_started_total)
        emit("pulls_completed_total", "counter",
             "Cross-worker prefix pulls that landed blocks",
             self.pulls_completed_total)
        emit("pulls_failed_total", "counter",
             "Pulls degraded to local prefill (timeout/refusal/error)",
             self.pulls_failed_total)
        emit("pulled_blocks_total", "counter",
             "Blocks imported by cross-worker pulls", self.pulled_blocks_total)
        emit("pulled_bytes_total", "counter",
             "Bytes imported by cross-worker pulls", self.pulled_bytes_total)
        emit("restore_latency_ms_p50", "gauge",
             "Rolling p50 of tier-restore latency",
             round(self.restore_latency_ms.percentile(0.5), 3))
        emit("restore_latency_ms_p99", "gauge",
             "Rolling p99 of tier-restore latency",
             round(self.restore_latency_ms.percentile(0.99), 3))
        emit("pull_latency_ms_p50", "gauge",
             "Rolling p50 of cross-worker pull latency",
             round(self.pull_latency_ms.percentile(0.5), 3))
        emit("pull_latency_ms_p99", "gauge",
             "Rolling p99 of cross-worker pull latency",
             round(self.pull_latency_ms.percentile(0.99), 3))
        return "\n".join(lines) + "\n"


kv_tier_metrics = KvTierMetrics()

# The integrity plane's verification boundaries (engine/integrity.py):
# ``disk`` = .kvblk envelope reads, ``host`` = host-tier entries verified
# before the HBM scatter (plus demotion-time re-verification), ``wire`` =
# transfer-plane payloads (cross-worker pull, migration push, disagg
# import) verified before sealing, ``objstore`` = durable-object envelope
# reads (engine/object_store.py).
INTEGRITY_PLANES = ("disk", "host", "wire", "objstore")


class KvIntegrityMetrics:
    """KV integrity-plane counters (docs/kv_tiering.md §integrity):
    per-plane verified/corrupt, plus the quarantine machinery's activity
    — negative-cache hits, chained-descendant drops, recompute fallbacks,
    and corruption-attributed worker quarantines.  Module-level singleton
    rendered as Prometheus text and appended to ``/metrics``."""

    def __init__(self):
        self.verified_total: Dict[str, int] = {p: 0 for p in INTEGRITY_PLANES}
        self.corrupt_total: Dict[str, int] = {p: 0 for p in INTEGRITY_PLANES}
        # blocks dropped from the tiers because their chain passes through
        # a corrupt block (the corrupt block itself is not counted here)
        self.descendants_dropped_total = 0
        # restore/promotion/pull attempts skipped on a negative-cached hash
        self.negative_cache_hits_total = 0
        # corruption events that degraded a live request to recompute
        # (the disagg degraded-mode shape — never a drop, never a wrong token)
        self.recomputed_total = 0
        # watchdog quarantines attributed to repeated KV corruption
        self.quarantined_total = 0

    def reset(self) -> None:
        self.__init__()

    def corrupt_sum(self) -> int:
        return sum(self.corrupt_total.values())

    def verified_sum(self) -> int:
        return sum(self.verified_total.values())

    def snapshot(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for p in INTEGRITY_PLANES:
            out[f"verified_{p}_total"] = float(self.verified_total[p])
            out[f"corrupt_{p}_total"] = float(self.corrupt_total[p])
        out["descendants_dropped_total"] = float(self.descendants_dropped_total)
        out["negative_cache_hits_total"] = float(self.negative_cache_hits_total)
        out["recomputed_total"] = float(self.recomputed_total)
        out["quarantined_total"] = float(self.quarantined_total)
        return out

    def render(self, prefix: str = "dynamo_tpu") -> str:
        ns = f"{prefix}_kv_integrity"
        lines = []

        def per_plane(name: str, help_: str, values: Dict[str, int]) -> None:
            lines.append(f"# HELP {ns}_{name} {help_}")
            lines.append(f"# TYPE {ns}_{name} counter")
            for p in INTEGRITY_PLANES:  # bounded constant label set
                lines.append(
                    f'{ns}_{name}{{plane="{escape_label(p)}"}} {values[p]}'
                )

        def emit(name: str, help_: str, value: int) -> None:
            lines.append(f"# HELP {ns}_{name} {help_}")
            lines.append(f"# TYPE {ns}_{name} counter")
            lines.append(f"{ns}_{name} {value}")

        per_plane("verified_total",
                  "KV blocks whose checksum verified at this plane's boundary",
                  self.verified_total)
        per_plane("corrupt_total",
                  "KV blocks that FAILED checksum verification at this plane",
                  self.corrupt_total)
        emit("descendants_dropped_total",
             "Tier blocks dropped because their chain passes through a "
             "corrupt block", self.descendants_dropped_total)
        emit("negative_cache_hits_total",
             "Restore/promotion/pull attempts skipped on a negative-cached "
             "(recently corrupt) hash", self.negative_cache_hits_total)
        emit("recomputed_total",
             "Corruption events degraded to local recompute (streams stay "
             "byte-identical)", self.recomputed_total)
        emit("quarantined_total",
             "Worker quarantines attributed to repeated KV corruption",
             self.quarantined_total)
        return "\n".join(lines) + "\n"


kv_integrity_metrics = KvIntegrityMetrics()


class BulkMetrics:
    """Bulk data-plane counters (docs/bulk_plane.md): bytes and transfers
    moved peer-to-peer (off the hub control plane), resumes after peer
    connection drops, and fallbacks onto the hub path.  Module-level
    singleton rendered as Prometheus text and appended to ``/metrics``;
    ``loadgen.py`` folds ``snapshot()`` into its run summary."""

    def __init__(self):
        self.bytes_total = 0
        self.transfers_total = 0
        # bulk attempts that fell back to the hub path (dead peer, expired
        # ticket, rendezvous outage) — the stream survives either way
        self.fallbacks_total = 0
        # reconnects that continued from the last verified chunk
        self.resumes_total = 0

    def reset(self) -> None:
        self.__init__()

    def snapshot(self) -> Dict[str, float]:
        return {
            "bytes_total": float(self.bytes_total),
            "transfers_total": float(self.transfers_total),
            "fallbacks_total": float(self.fallbacks_total),
            "resumes_total": float(self.resumes_total),
        }

    def render(self, prefix: str = "dynamo_tpu") -> str:
        ns = f"{prefix}_bulk"
        lines = []

        def emit(name: str, help_: str, value: int) -> None:
            lines.append(f"# HELP {ns}_{name} {help_}")
            lines.append(f"# TYPE {ns}_{name} counter")
            lines.append(f"{ns}_{name} {value}")

        emit("bytes_total",
             "Payload bytes moved over the peer-to-peer bulk plane "
             "(KV pulls, migration copies, span batches)", self.bytes_total)
        emit("transfers_total",
             "Completed bulk transfers (fetch + push)", self.transfers_total)
        emit("fallbacks_total",
             "Bulk attempts that fell back to the hub path (stream "
             "survives; bytes ride the control plane)", self.fallbacks_total)
        emit("resumes_total",
             "Transfers resumed from the last verified chunk after a peer "
             "connection drop", self.resumes_total)
        return "\n".join(lines) + "\n"


bulk_metrics = BulkMetrics()


class ObjstoreMetrics:
    """Durable object-store tier counters (engine/object_store.py): put/get
    traffic in blocks and bytes plus byte-budgeted GC evictions.  Module-level
    singleton rendered as Prometheus text and appended to ``/metrics``."""

    def __init__(self):
        self.puts_total = 0
        self.put_bytes_total = 0
        self.gets_total = 0
        self.get_bytes_total = 0
        # objects evicted by the byte-budgeted GC (coldest-first); corrupt
        # drops are counted on the integrity plane, not here
        self.gc_evictions_total = 0

    def reset(self) -> None:
        self.__init__()

    def snapshot(self) -> Dict[str, float]:
        return {
            "puts_total": float(self.puts_total),
            "put_bytes_total": float(self.put_bytes_total),
            "gets_total": float(self.gets_total),
            "get_bytes_total": float(self.get_bytes_total),
            "gc_evictions_total": float(self.gc_evictions_total),
        }

    def render(self, prefix: str = "dynamo_tpu") -> str:
        ns = f"{prefix}_objstore"
        lines = []

        def emit(name: str, help_: str, value: int) -> None:
            lines.append(f"# HELP {ns}_{name} {help_}")
            lines.append(f"# TYPE {ns}_{name} counter")
            lines.append(f"{ns}_{name} {value}")

        emit("puts_total",
             "Objects published to the durable store (demotions + explicit "
             "persists)", self.puts_total)
        emit("put_bytes_total",
             "Envelope bytes published to the durable store",
             self.put_bytes_total)
        emit("gets_total",
             "Objects read back from the durable store (restores + "
             "promotions)", self.gets_total)
        emit("get_bytes_total",
             "Envelope bytes read back from the durable store",
             self.get_bytes_total)
        emit("gc_evictions_total",
             "Objects evicted by the byte-budgeted GC (coldest-first)",
             self.gc_evictions_total)
        return "\n".join(lines) + "\n"


objstore_metrics = ObjstoreMetrics()


class InflightGuard:
    """Tracks one request: inflight gauge, duration, TTFT, ITL, final status.

    Must be closed with ``finish(status)``; a guard dropped without an explicit
    status records ``error`` (the reference's RAII Drop behaviour).
    """

    def __init__(self, metrics: Metrics, model: str, endpoint: str, request_type: str):
        self._m = metrics
        self.model = model
        self.endpoint = endpoint
        self.request_type = request_type
        self._start = time.monotonic()
        self._last_token_t: Optional[float] = None
        self._finished = False
        metrics.inflight.labels(model, endpoint).inc()

    def on_token(self, n_tokens: int = 1) -> None:
        now = time.monotonic()
        ttft_w, itl_w = self._m.window(self.model, self.endpoint)
        if self._last_token_t is None:
            self._m.ttft.labels(self.model, self.endpoint).observe(now - self._start)
            ttft_w.observe(now - self._start)
            self._m.ttft_recent.observe(now - self._start)
        else:
            self._m.itl.labels(self.model, self.endpoint).observe(now - self._last_token_t)
            itl_w.observe(now - self._last_token_t)
        self._last_token_t = now
        self._m.output_tokens.labels(self.model, self.endpoint).inc(n_tokens)

    def finish(self, status: str) -> None:
        if self._finished:
            return
        self._finished = True
        self._m.inflight.labels(self.model, self.endpoint).dec()
        self._m.request_duration.labels(self.model, self.endpoint).observe(
            time.monotonic() - self._start
        )
        self._m.requests_total.labels(
            self.model, self.endpoint, self.request_type, status
        ).inc()

    def __del__(self):
        if not self._finished:
            try:
                self.finish(Status.ERROR)
            except Exception:  # noqa: BLE001 — interpreter teardown
                pass
