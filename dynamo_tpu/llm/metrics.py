"""HTTP-edge Prometheus metrics.

Reference semantics: lib/llm/src/http/service/metrics.rs:57-128,319 —
``{prefix}_http_service_{requests_total, inflight_requests,
request_duration_seconds, time_to_first_token_seconds,
inter_token_latency_seconds}`` with status labels
``success | client_drop | rejected | error``, and a RAII ``InflightGuard``
that records duration + status when dropped.
"""

from __future__ import annotations

import time
from typing import Optional

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

REQUEST_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
TOKEN_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5)


class Status:
    SUCCESS = "success"
    CLIENT_DROP = "client_drop"
    REJECTED = "rejected"
    ERROR = "error"


class Metrics:
    def __init__(self, prefix: str = "dynamo_tpu"):
        self.registry = CollectorRegistry()
        ns = f"{prefix}_http_service"
        self.requests_total = Counter(
            f"{ns}_requests_total",
            "Total requests by model/endpoint/status",
            ["model", "endpoint", "request_type", "status"],
            registry=self.registry,
        )
        self.inflight = Gauge(
            f"{ns}_inflight_requests",
            "Currently in-flight requests",
            ["model", "endpoint"],
            registry=self.registry,
        )
        self.request_duration = Histogram(
            f"{ns}_request_duration_seconds",
            "End-to-end request duration",
            ["model", "endpoint"],
            buckets=REQUEST_BUCKETS,
            registry=self.registry,
        )
        self.ttft = Histogram(
            f"{ns}_time_to_first_token_seconds",
            "Time to first token (streaming)",
            ["model", "endpoint"],
            buckets=REQUEST_BUCKETS,
            registry=self.registry,
        )
        self.itl = Histogram(
            f"{ns}_inter_token_latency_seconds",
            "Inter-token latency (streaming)",
            ["model", "endpoint"],
            buckets=TOKEN_BUCKETS,
            registry=self.registry,
        )
        self.output_tokens = Counter(
            f"{ns}_output_tokens_total",
            "Total output tokens produced",
            ["model", "endpoint"],
            registry=self.registry,
        )

    def guard(self, model: str, endpoint: str, request_type: str) -> "InflightGuard":
        return InflightGuard(self, model, endpoint, request_type)

    def render(self) -> bytes:
        return generate_latest(self.registry)


class InflightGuard:
    """Tracks one request: inflight gauge, duration, TTFT, ITL, final status.

    Must be closed with ``finish(status)``; a guard dropped without an explicit
    status records ``error`` (the reference's RAII Drop behaviour).
    """

    def __init__(self, metrics: Metrics, model: str, endpoint: str, request_type: str):
        self._m = metrics
        self.model = model
        self.endpoint = endpoint
        self.request_type = request_type
        self._start = time.monotonic()
        self._last_token_t: Optional[float] = None
        self._finished = False
        metrics.inflight.labels(model, endpoint).inc()

    def on_token(self, n_tokens: int = 1) -> None:
        now = time.monotonic()
        if self._last_token_t is None:
            self._m.ttft.labels(self.model, self.endpoint).observe(now - self._start)
        else:
            self._m.itl.labels(self.model, self.endpoint).observe(now - self._last_token_t)
        self._last_token_t = now
        self._m.output_tokens.labels(self.model, self.endpoint).inc(n_tokens)

    def finish(self, status: str) -> None:
        if self._finished:
            return
        self._finished = True
        self._m.inflight.labels(self.model, self.endpoint).dec()
        self._m.request_duration.labels(self.model, self.endpoint).observe(
            time.monotonic() - self._start
        )
        self._m.requests_total.labels(
            self.model, self.endpoint, self.request_type, status
        ).inc()

    def __del__(self):
        if not self._finished:
            try:
                self.finish(Status.ERROR)
            except Exception:  # noqa: BLE001 — interpreter teardown
                pass
