"""QoS under overload: per-tenant quotas, priority classes, brownout ladder.

The resilience stack survives *crashes* (runtime/resilience.py, PR 7 hub
failover); this module makes the fleet survive *sustained overload* without
failing indiscriminately:

- ``TenantQuotas``   — token-bucket rate limits keyed on tenant identity
  (API key header / OpenAI ``model`` field / adapter), enforced at the HTTP
  edge before a request costs any engine work.  One flooding tenant burns
  its own bucket, not the fleet.
- priority classes  — ``interactive`` (default) vs ``batch``, carried as
  ``x-priority`` header or ``nvext.priority`` and threaded through
  ``PreprocessedRequest.priority`` down to the scheduler, where batch rows
  are the first preemption victims and interactive admission is protected
  (engine/scheduler.py WfqQueue).
- ``BrownoutLadder`` — a deterministic, hysteresis-gated degradation state
  machine (same confirm-streak/cooldown idiom as the planner
  ``DecisionEngine``) driven by the edge's queue-depth / TTFT / KV-pressure
  signals.  Instead of today's cliff (healthy → 429/503 for everyone) the
  edge degrades in defined rungs and recovers monotonically:

  ====  =====================================================================
  rung  behaviour (each rung includes all lower rungs' measures)
  ====  =====================================================================
  0     normal service
  1     cap ``max_tokens`` at ``max_tokens_cap`` (bound per-request cost)
  2     stand down speculative-decode drafts (``nvext.spec_decode=false``
        on admitted requests — verify bursts stop competing for batch rows)
  3     shed the ``batch`` class with 429 + drain-rate ``Retry-After``
  4     503 *overflow* interactive requests (admission saturated → shed
        instead of queueing; never sheds below the in-flight cap)
  ====  =====================================================================

The ladder is PURE: ``tick(signals) -> rung`` with no clock and no I/O —
the same signal sequence always yields the same rung sequence (the
determinism gate in tests/test_qos.py).  The HTTP edge owns a small driver
task that samples signals on an interval and applies the current rung
(llm/http_service.py).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Tuple

INTERACTIVE = "interactive"
BATCH = "batch"
PRIORITIES = (INTERACTIVE, BATCH)


def normalize_priority(value: Any, default: str = INTERACTIVE) -> str:
    """Clamp any wire value to a known class (unknown → default, never an
    error: priority is a hint, not a schema)."""
    if isinstance(value, str) and value.lower() in PRIORITIES:
        return value.lower()
    return default


def resolve_priority(headers: Mapping[str, str], body: Mapping[str, Any]) -> str:
    """Request priority: ``x-priority`` header wins, else ``nvext.priority``,
    else interactive (protecting latency-sensitive traffic by default)."""
    raw = headers.get("x-priority")
    if raw is None and isinstance(body.get("nvext"), Mapping):
        raw = body["nvext"].get("priority")
    return normalize_priority(raw)


def _credential_tenant(secret: str) -> str:
    """Stable non-secret tenant id for a credential: the raw API key /
    bearer token must never become the tenant string — tenant ids reach
    logs, /metrics labels and scheduler annotations, none of which may
    carry a secret.  One shared derivation (dynamo_tpu.labels) so every
    layer agrees on the digest."""
    from ..labels import hash_credential

    return hash_credential(secret)


def resolve_tenant(headers: Mapping[str, str], body: Mapping[str, Any]) -> str:
    """Tenant identity for quota/fairness accounting, in resolution order:
    explicit ``x-tenant`` header, API key (``x-api-key`` / bearer token —
    HASHED, see ``_credential_tenant``), ``nvext.tenant``, then the OpenAI
    ``model`` field (adapters ARE model names under llm/tenancy, so
    per-adapter isolation falls out)."""
    raw = headers.get("x-tenant")
    if raw:
        return raw.strip()
    key = headers.get("x-api-key")
    if key:
        return _credential_tenant(key.strip())
    auth = headers.get("authorization", "")
    if auth.lower().startswith("bearer ") and auth[7:].strip():
        return _credential_tenant(auth[7:].strip())
    nvext = body.get("nvext")
    if isinstance(nvext, Mapping) and nvext.get("tenant"):
        return str(nvext["tenant"])
    model = body.get("model")
    return str(model) if model else "anonymous"


# --------------------------------------------------------------------------
# Per-tenant token buckets
# --------------------------------------------------------------------------


@dataclass
class _Bucket:
    rate: float  # tokens per second
    burst: float  # bucket capacity
    level: float  # current tokens
    t_last: float  # last refill timestamp


class TenantQuotas:
    """Token-bucket rate limiting keyed on tenant identity.

    ``rate`` is requests/second sustained, ``burst`` the instantaneous
    allowance.  ``rate=None`` disables quotas entirely (default: zero
    behaviour change for embedded/test services).  Per-tenant overrides
    (``tenants={"gold": {"rate": 50, "burst": 100}}``) let operators sell
    tiers.  The clock is injectable so tests replay deterministically.
    """

    def __init__(
        self,
        rate: Optional[float] = None,
        burst: Optional[float] = None,
        tenants: Optional[Mapping[str, Mapping[str, float]]] = None,
        clock=time.monotonic,
        max_tenants: int = 4096,
    ):
        self.rate = rate
        self.burst = burst if burst is not None else (rate or 0.0) * 2
        self.tenants = dict(tenants or {})
        self._clock = clock
        self._buckets: Dict[str, _Bucket] = {}
        # Bounded: tenant ids arrive from the wire (API keys churn), so the
        # bucket table must not grow without limit.  Eviction picks the
        # fullest bucket — the tenant least likely to notice a refill reset.
        self.max_tenants = max_tenants

    @property
    def enabled(self) -> bool:
        return self.rate is not None

    def _bucket(self, tenant: str) -> _Bucket:
        bucket = self._buckets.get(tenant)
        if bucket is None:
            over = self.tenants.get(tenant) or {}
            rate = float(over.get("rate", self.rate or 0.0))
            burst = float(over.get("burst", over.get("rate", self.burst)))
            bucket = _Bucket(
                rate=max(rate, 1e-9),
                burst=max(burst, 1.0),
                level=max(burst, 1.0),
                t_last=self._clock(),
            )
            if len(self._buckets) >= self.max_tenants:
                victim = max(self._buckets, key=lambda k: self._buckets[k].level)
                del self._buckets[victim]
            self._buckets[tenant] = bucket
        return bucket

    def try_acquire(self, tenant: str, cost: float = 1.0) -> Tuple[bool, float]:
        """Charge ``cost`` against the tenant's bucket.  Returns
        ``(admitted, retry_after_s)`` — retry_after is the refill time until
        the bucket holds ``cost`` again (0.0 when admitted)."""
        if not self.enabled:
            return True, 0.0
        bucket = self._bucket(tenant)
        now = self._clock()
        bucket.level = min(
            bucket.burst, bucket.level + (now - bucket.t_last) * bucket.rate
        )
        bucket.t_last = now
        if bucket.level >= cost:
            bucket.level -= cost
            return True, 0.0
        return False, (cost - bucket.level) / bucket.rate

    def refund(self, tenant: str, cost: float = 1.0) -> None:
        """Credit back a charge for a request that was shed downstream
        (admission queue full / rung-4 overflow) — shed work consumed no
        capacity and must not drain the tenant's budget."""
        if not self.enabled:
            return
        bucket = self._bucket(tenant)
        bucket.level = min(bucket.burst, bucket.level + cost)

    def level(self, tenant: str) -> float:
        return self._bucket(tenant).level if self.enabled else float("inf")


# --------------------------------------------------------------------------
# Brownout ladder
# --------------------------------------------------------------------------

RUNG_NORMAL = 0
RUNG_CAP_TOKENS = 1
RUNG_SPEC_STANDDOWN = 2
RUNG_SHED_BATCH = 3
RUNG_SHED_INTERACTIVE = 4

RUNG_NAMES = {
    RUNG_NORMAL: "normal",
    RUNG_CAP_TOKENS: "cap-max-tokens",
    RUNG_SPEC_STANDDOWN: "spec-standdown",
    RUNG_SHED_BATCH: "shed-batch",
    RUNG_SHED_INTERACTIVE: "shed-interactive-overflow",
}


@dataclass(frozen=True)
class BrownoutConfig:
    """Thresholds (pressure 1.0 = exactly at target) + hysteresis shape.

    ``band_down`` is deliberately wider than ``band_up`` and recovery takes
    more confirm ticks — stepping down too eagerly re-enters overload and
    flaps, the classic oscillation driver (Llumnix; planner/policy.py uses
    the same asymmetry)."""

    # Admission queue depth considered "at target" (pressure 1.0).
    queue_high: float = 16.0
    # KV usage fraction considered "at target" (signal optional).
    kv_high: float = 0.90
    # TTFT p95 SLO in ms (None = ignore the latency signal).
    ttft_p95_ms: Optional[float] = None
    band_up: float = 0.10
    band_down: float = 0.40
    confirm_up: int = 2
    confirm_down: int = 4
    cooldown: int = 3
    max_rung: int = RUNG_SHED_INTERACTIVE
    # Rung 1: admitted requests' max_tokens are capped here.
    max_tokens_cap: int = 256

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "BrownoutConfig":
        kw = {f: d[f] for f in cls.__dataclass_fields__ if f in d}
        return cls(**kw)


@dataclass(frozen=True)
class BrownoutSignals:
    """One tick's pressure inputs (all optional signals default benign)."""

    queue_depth: float = 0.0
    kv_usage: float = 0.0
    ttft_p95_ms: Optional[float] = None


class BrownoutLadder:
    """Deterministic hysteresis-gated rung selector.

    Escalation moves ONE rung per confirmed breach (``confirm_up``
    consecutive ticks above ``1 + band_up``); recovery moves ONE rung per
    confirmed calm (``confirm_down`` ticks below ``1 - band_down``); either
    move starts a ``cooldown`` during which the ladder holds its rung, and
    inside the band both streaks reset — a signal oscillating within the
    band produces zero transitions by construction.  Recovery is therefore
    monotonic: 4 → 3 → 2 → 1 → 0, one cooldown apart, with no flip-flop
    unless pressure genuinely re-breaches.
    """

    def __init__(self, config: Optional[BrownoutConfig] = None):
        self.config = config or BrownoutConfig()
        self.rung = RUNG_NORMAL
        self.tick_count = 0
        self._up_streak = 0
        self._down_streak = 0
        self._cooldown = 0
        # (tick, from_rung, to_rung, pressure) — the determinism gate's
        # comparison artifact, bounded like step_trace.
        self.transitions: List[Tuple[int, int, int, float]] = []

    # -- pressure ----------------------------------------------------------

    def pressure(self, sig: BrownoutSignals) -> float:
        cfg = self.config
        ratios = [0.0]
        if cfg.queue_high > 0:
            ratios.append(sig.queue_depth / cfg.queue_high)
        if cfg.kv_high > 0:
            ratios.append(sig.kv_usage / cfg.kv_high)
        if sig.ttft_p95_ms is not None and cfg.ttft_p95_ms:
            ratios.append(sig.ttft_p95_ms / cfg.ttft_p95_ms)
        return max(ratios)

    # -- tick --------------------------------------------------------------

    def tick(self, sig: BrownoutSignals) -> int:
        cfg = self.config
        self.tick_count += 1
        p = self.pressure(sig)
        if self._cooldown > 0:
            self._cooldown -= 1
        if p >= 1.0 + cfg.band_up:
            self._up_streak += 1
            self._down_streak = 0
        elif p <= 1.0 - cfg.band_down:
            self._down_streak += 1
            self._up_streak = 0
        else:  # inside the hysteresis band: full reset — oscillation absorbed
            self._up_streak = 0
            self._down_streak = 0
        if (
            self._up_streak >= cfg.confirm_up
            and self._cooldown == 0
            and self.rung < cfg.max_rung
        ):
            self._move(self.rung + 1, p)
        elif (
            self._down_streak >= cfg.confirm_down
            and self._cooldown == 0
            and self.rung > RUNG_NORMAL
        ):
            self._move(self.rung - 1, p)
        return self.rung

    def _move(self, to: int, pressure: float) -> None:
        self.transitions.append((self.tick_count, self.rung, to, pressure))
        if len(self.transitions) > 4096:
            del self.transitions[:2048]
        self.rung = to
        self._cooldown = self.config.cooldown
        self._up_streak = 0
        self._down_streak = 0

    # -- introspection -----------------------------------------------------

    def state(self) -> Dict[str, Any]:
        return {
            "rung": self.rung,
            "name": RUNG_NAMES.get(self.rung, str(self.rung)),
            "tick": self.tick_count,
            "up_streak": self._up_streak,
            "down_streak": self._down_streak,
            "cooldown": self._cooldown,
            "transitions": len(self.transitions),
        }


# --------------------------------------------------------------------------
# Edge controller (quota check + rung enforcement in one object)
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class QosConfig:
    """The edge's ``qos`` config section (runtime/config.py; CLI --qos-*).

    ``rate=None`` disables quotas; ``brownout=None`` disables the ladder —
    both default off so embedded/test services see zero behaviour change.
    """

    rate: Optional[float] = None
    burst: Optional[float] = None
    tenants: Dict[str, Dict[str, float]] = field(default_factory=dict)
    brownout: Optional[BrownoutConfig] = None
    tick_s: float = 0.5

    @classmethod
    def from_dict(cls, d: Optional[Mapping[str, Any]]) -> "QosConfig":
        d = d or {}
        brownout = d.get("brownout")
        if isinstance(brownout, Mapping):
            brownout = BrownoutConfig.from_dict(brownout)
        elif brownout:  # truthy scalar: enable with defaults
            brownout = BrownoutConfig()
        else:
            brownout = None
        rate = d.get("rate")
        return cls(
            rate=float(rate) if rate not in (None, "", 0) else None,
            burst=float(d["burst"]) if d.get("burst") else None,
            tenants=dict(d.get("tenants") or {}),
            brownout=brownout,
            tick_s=float(d.get("tick_s", 0.5)),
        )


class QosShed(Exception):
    """A QoS decision shed this request (maps to 429/503 at the edge)."""

    def __init__(
        self,
        status: int,
        message: str,
        retry_after_s: float,
        reason: str = "quota",
    ):
        super().__init__(message)
        self.status = status
        self.message = message
        self.retry_after_s = retry_after_s
        self.reason = reason  # "quota" | "batch_shed"


class QosController:
    """Bundles quotas + ladder for the HTTP edge.

    ``admit(tenant, priority)`` makes the cheap pre-admission decisions
    (quota charge, rung-3 batch shed); ``shape(body)`` applies the current
    rung's request rewrites (max_tokens cap, spec stand-down) to an
    admitted request.  Rung-4 interactive overflow is decided by the edge
    itself, which can see admission-controller saturation.
    """

    def __init__(self, config: Optional[QosConfig] = None, clock=time.monotonic):
        self.config = config or QosConfig()
        self.quotas = TenantQuotas(
            rate=self.config.rate,
            burst=self.config.burst,
            tenants=self.config.tenants,
            clock=clock,
        )
        self.ladder = (
            BrownoutLadder(self.config.brownout)
            if self.config.brownout is not None
            else None
        )

    @property
    def rung(self) -> int:
        return self.ladder.rung if self.ladder is not None else RUNG_NORMAL

    def admit(
        self,
        tenant: str,
        priority: str,
        drain_retry_after_s: Optional[float] = None,
    ) -> None:
        """Raise QosShed if quota or the brownout rung rejects the request.

        ``drain_retry_after_s`` is the edge's queue-drain estimate
        (AdmissionController.estimate_retry_after); shed responses back
        clients off proportionally to REAL pressure, scaled up with the
        rung (deeper brownout → longer back-off)."""
        # Rung check FIRST: a request the brownout sheds consumed no
        # capacity, so it must not drain the tenant's bucket — otherwise a
        # well-behaved batch tenant exits the brownout already quota-broke
        # for work that was never served.
        if self.rung >= RUNG_SHED_BATCH and priority == BATCH:
            base = drain_retry_after_s if drain_retry_after_s else 1.0
            raise QosShed(
                429,
                f"brownout rung {self.rung} "
                f"({RUNG_NAMES[self.rung]}): batch class shed",
                base * (1 + self.rung - RUNG_SHED_BATCH),
                reason="batch_shed",
            )
        ok, refill_s = self.quotas.try_acquire(tenant)
        if not ok:
            # Quota Retry-After is the tenant's own refill time — never the
            # fleet's drain rate; the tenant is the bottleneck, not us.
            raise QosShed(
                429,
                f"tenant {tenant!r} over its request quota",
                max(refill_s, 0.05),
                reason="quota",
            )

    def shape(self, body: Dict[str, Any]) -> Dict[str, Any]:
        """Apply the current rung's degradations to an ADMITTED request
        body (returns the same dict, mutated — the edge owns it by now)."""
        rung = self.rung
        if rung >= RUNG_CAP_TOKENS:
            cap = self.config.brownout.max_tokens_cap if self.config.brownout else 256
            for key in ("max_tokens", "max_completion_tokens"):
                req = body.get(key)
                if req is None and key == "max_tokens":
                    body[key] = cap
                elif isinstance(req, int) and req > cap:
                    body[key] = cap
        if rung >= RUNG_SPEC_STANDDOWN:
            # NOT setdefault: a client-sent ``"nvext": null`` would satisfy
            # setdefault and silently skip the stand-down.
            nvext = body.get("nvext")
            if not isinstance(nvext, dict):
                nvext = {}
                body["nvext"] = nvext
            nvext["spec_decode"] = False
        return body


__all__ = [
    "BATCH",
    "BrownoutConfig",
    "BrownoutLadder",
    "BrownoutSignals",
    "INTERACTIVE",
    "QosConfig",
    "QosController",
    "QosShed",
    "RUNG_NAMES",
    "TenantQuotas",
    "normalize_priority",
    "resolve_priority",
    "resolve_tenant",
]
