"""OpenAI preprocessor operator: template + tokenize → PreprocessedRequest.

Reference semantics: lib/llm/src/preprocessor.rs (OpenAIPreprocessor) — the
forward edge renders the chat template and tokenizes into ``BackendInput``;
the backward edge shapes backend text deltas into OpenAI chunks via
``DeltaGenerator``.  Annotation requests (nvext.annotations) can echo the
formatted prompt / token ids back to the caller as annotation events.
"""

from __future__ import annotations

import asyncio
import dataclasses
from typing import Any, AsyncIterator, Dict, Optional, Union

from ..runtime.engine import AsyncEngine, Context, ResponseStream
from ..runtime.pipeline import Operator
from .openai import ChatCompletionRequest, CompletionRequest, DeltaGenerator
from .protocols import PreprocessedRequest
from .tokenizer import BaseTokenizer

# Sentinel for preprocess(grammar=...): distinguishes "compile it for me"
# (the default, sync callers) from an explicitly precompiled value — which
# may legitimately be None (generate() compiles off-loop first).
_UNSET = object()


class OpenAIPreprocessor(Operator):
    """Chat/completions requests → token-level requests → OpenAI chunks.

    Multi-tenancy (llm/tenancy): this operator is the structured-output
    compile point — it is the only pipeline layer holding the tokenizer, so
    ``response_format`` / ``nvext.grammar`` constraints compile HERE into a
    serializable token-mask automaton that rides the PreprocessedRequest
    (engines just walk integers).  When the pipeline fronts a LoRA adapter
    (``adapter=`` — one pipeline per served name under discovery), every
    request is stamped with the adapter id and its KV-hash salt so the
    KV-aware router and the engine agree on tenant cache identity.
    """

    def __init__(
        self,
        tokenizer: BaseTokenizer,
        model_name: str = "",
        adapter: Optional[str] = None,
        grammar_compiler=None,
    ):
        self._tokenizer = tokenizer
        self.model_name = model_name
        self.adapter = adapter
        # Shared across the sibling per-adapter preprocessors of one
        # tokenizer when the caller passes it (cli http mode); lazily
        # created otherwise.  Compilation is the expensive step (vocab
        # indexing), so the cache matters for agent/tool-calling traffic.
        self._grammar_compiler = grammar_compiler
        # Hash-first dispatch state per automaton hash: {"misses", "full"}.
        # After a miss the next 2**misses dispatches ship the full table —
        # with round-robin routing a single miss→resend pair can seed the
        # SAME two workers forever (the stub always lands on the unseeded
        # one), so the full-table burst walks the rotation and seeds the
        # whole fleet before stubs are retried.
        self._grammar_wire: Dict[str, Dict[str, int]] = {}

    def _constraint_spec(self, oai) -> Optional[dict]:
        from .tenancy.grammar import constraint_spec

        return constraint_spec(
            getattr(oai, "response_format", None),
            oai.nvext.grammar if oai.nvext else None,
        )

    def _compile_grammar(self, oai) -> Optional[dict]:
        """Constraint spec → serialized automaton dict (None when the
        request is unconstrained).  GrammarError (bad schema/regex) is a
        ValueError: the HTTP edge maps it to 400."""
        from .metrics import tenancy_metrics
        from .tenancy.grammar import GrammarCompiler

        spec = self._constraint_spec(oai)
        if spec is None:
            return None
        if self._grammar_compiler is None:
            self._grammar_compiler = GrammarCompiler(self._tokenizer)
        before = self._grammar_compiler.compiles
        automaton = self._grammar_compiler.compile(spec)
        if self._grammar_compiler.compiles > before:
            tenancy_metrics.grammar_compiles_total += 1
        else:
            tenancy_metrics.grammar_cache_hits_total += 1
        return automaton.to_dict()

    async def _compile_grammar_async(self, oai) -> Optional[dict]:
        """Off-loop grammar compile: a cache miss indexes the whole
        vocabulary (seconds on large vocabs) and must not stall every
        concurrent stream on this process's event loop."""
        if self._constraint_spec(oai) is None:
            return None
        import asyncio

        return await asyncio.to_thread(self._compile_grammar, oai)

    # -- forward ------------------------------------------------------------

    @staticmethod
    def _parse(
        oai: Union[ChatCompletionRequest, CompletionRequest, Dict[str, Any]]
    ) -> Union[ChatCompletionRequest, CompletionRequest]:
        if isinstance(oai, dict):
            return (
                ChatCompletionRequest.model_validate(oai)
                if "messages" in oai
                else CompletionRequest.model_validate(oai)
            )
        return oai

    def preprocess(
        self,
        oai: Union[ChatCompletionRequest, CompletionRequest, Dict[str, Any]],
        grammar: Any = _UNSET,
    ) -> PreprocessedRequest:
        oai = self._parse(oai)
        if isinstance(oai, ChatCompletionRequest):
            if oai.nvext and oai.nvext.use_raw_prompt and len(oai.messages) == 1:
                prompt = oai.messages[0].text()
            else:
                prompt = self._tokenizer.apply_chat_template(
                    [
                        {"role": m.role, "content": m.text()}
                        for m in oai.messages
                    ],
                    add_generation_prompt=True,
                    tools=oai.tools,
                )
            token_ids = self._tokenizer.encode(prompt, add_special_tokens=False)
        else:
            prompt_field = oai.prompt
            if isinstance(prompt_field, list) and prompt_field and isinstance(prompt_field[0], int):
                prompt = None
                token_ids = list(prompt_field)
            else:
                prompt = prompt_field if isinstance(prompt_field, str) else str(prompt_field)
                token_ids = self._tokenizer.encode(prompt)
        annotations: Dict[str, Any] = {}
        if oai.nvext and oai.nvext.annotations:
            if "formatted_prompt" in oai.nvext.annotations and prompt is not None:
                annotations["formatted_prompt"] = prompt
            if "token_ids" in oai.nvext.annotations:
                annotations["token_ids"] = token_ids
        if self.adapter:
            from .tenancy.lora import kv_salt_for_adapter

            # Tenant identity rides the request: the engine resolves the
            # adapter to a device slot, and the KV router salts its overlap
            # hashing with the same value the engine seals blocks under —
            # set HERE so routing happens before any engine is chosen.
            annotations["adapter"] = self.adapter
            annotations["kv_salt"] = kv_salt_for_adapter(self.adapter)
        # QoS identity (llm/qos.py): an explicit nvext.tenant overrides the
        # scheduler's default fairness key (adapter → model name); priority
        # rides its own PreprocessedRequest field (the HTTP edge may have
        # already stamped it from the x-priority header).
        priority = None
        if oai.nvext:
            if oai.nvext.tenant:
                annotations["tenant"] = str(oai.nvext.tenant)
            if oai.nvext.priority is not None:
                from .qos import normalize_priority

                priority = normalize_priority(oai.nvext.priority)
        return PreprocessedRequest(
            token_ids=token_ids,
            stop_conditions=oai.stop_conditions(),
            sampling_options=oai.sampling_options(),
            model=oai.model,
            annotations=annotations,
            grammar=self._compile_grammar(oai) if grammar is _UNSET else grammar,
            priority=priority,
        )

    # -- dispatch -----------------------------------------------------------

    @staticmethod
    def _is_grammar_miss(exc: BaseException) -> bool:
        from ..runtime.transports.service import RemoteEngineError
        from .tenancy.grammar import GrammarCacheMissError

        if isinstance(exc, GrammarCacheMissError):
            return True  # in-process engine (cli run out=tpu)
        return (
            isinstance(exc, RemoteEngineError) and exc.kind == "grammar_miss"
        )

    async def _dispatch(self, next: AsyncEngine, ctx, pre) -> ResponseStream:
        """Hash-first constrained dispatch (ROADMAP tenancy carry-over):
        ship the automaton's content hash alone; only an engine whose LRU
        lacks it answers ``grammar_miss``, and exactly then the full edge
        table (KBs per request on a real vocabulary) goes over the wire.
        Repeated misses (cold fleet) switch to an exponentially growing
        full-table burst that seeds the routing rotation, then stubs are
        retried.  Unconstrained requests dispatch unchanged."""
        from .metrics import tenancy_metrics

        g = pre.grammar
        if not g or not g.get("hash") or "edges" not in g:
            return await next.generate(Context(pre.to_dict(), ctx))
        state = self._grammar_wire.setdefault(
            g["hash"], {"misses": 0, "full": 0}
        )
        if len(self._grammar_wire) > 256:  # bounded (hash churn)
            # (`next` names the downstream engine here — index, don't iter.)
            self._grammar_wire.pop(list(self._grammar_wire)[0])
        if state["full"] > 0:
            state["full"] -= 1
        else:
            stub = dataclasses.replace(
                pre, grammar={"hash": g["hash"], "stub": True}
            )
            try:
                stream = await next.generate(Context(stub.to_dict(), ctx))
                state["misses"] = 0
                tenancy_metrics.grammar_stub_dispatches_total += 1
                return stream
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — classified below
                if not self._is_grammar_miss(e):
                    raise
                state["misses"] += 1
                state["full"] = min(2 ** state["misses"], 16)
                tenancy_metrics.grammar_full_resends_total += 1
        return await next.generate(Context(pre.to_dict(), ctx))

    # -- the operator -------------------------------------------------------

    async def generate(self, request: Context, next: AsyncEngine) -> ResponseStream:
        raw = request.data
        chat = "messages" in raw if isinstance(raw, dict) else True
        oai = self._parse(raw)
        from .trace_service import preprocess_span

        with preprocess_span(request.ctx):
            pre = self.preprocess(
                oai, grammar=await self._compile_grammar_async(oai)
            )
        trace = getattr(request.ctx, "trace", None)
        if trace is not None and trace.sampled:
            # Wire propagation (runtime/tracing.py): the trace rides
            # ``annotations.trace`` on the PreprocessedRequest — the same
            # omit-when-absent idiom as adapter/kv_salt/tenant, so
            # pre-tracing consumers never see the key.
            pre.annotations["trace"] = trace.to_dict()
        model = pre.model or self.model_name
        n = int(raw.get("n") or 1) if isinstance(raw, dict) else 1
        # Only user-REQUESTED debug annotations (nvext.annotations) echo as
        # the SSE ``annotation`` event; internal routing identity
        # (llm/tenancy adapter/kv_salt, migration resume) stays off the
        # client wire.
        echo = {
            k: v
            for k, v in pre.annotations.items()
            if k in ("formatted_prompt", "token_ids")
        }
        if n <= 1:
            stream = await self._dispatch(next, request.ctx, pre)
            return ResponseStream(
                self._to_chunks(stream, model, chat, request.id, echo),
                request.ctx,
            )
        # n > 1: one engine request per choice — the prefix cache shares the
        # prompt KV across them; streams merge with per-choice indices.
        # Reference: protocols/openai (n) + multiple SSE choice indices.
        from ..runtime.engine import AsyncEngineContext

        streams = []
        for i in range(n):
            child = AsyncEngineContext(f"{request.id}-c{i}")
            request.ctx.link_child(child)
            pre_i = pre
            if pre.sampling_options.seed is not None:
                so = dataclasses.replace(
                    pre.sampling_options, seed=pre.sampling_options.seed + i
                )
                pre_i = dataclasses.replace(pre, sampling_options=so)
            streams.append(await self._dispatch(next, child, pre_i))
        return ResponseStream(
            self._merge_choices(streams, model, chat, request.id, echo),
            request.ctx,
        )

    async def _merge_choices(
        self,
        streams,
        model: str,
        chat: bool,
        request_id: str,
        annotations: Dict[str, Any],
    ) -> AsyncIterator[Dict[str, Any]]:
        """Interleave n sub-request streams into one chunk stream with
        per-choice indices; one summed usage chunk at the end."""
        import asyncio

        queue: "asyncio.Queue" = asyncio.Queue()

        async def pump(i: int, stream) -> None:
            gen = DeltaGenerator(model, chat=chat, request_id=request_id, index=i)
            try:
                async for item in stream:
                    reason = item.get("finish_reason")
                    if reason is not None:
                        await queue.put((gen.finish_chunk(reason), item.get("usage")))
                        return
                    if item.get("text") or item.get("logprobs"):
                        await queue.put(
                            (
                                gen.text_chunk(
                                    item.get("text") or "",
                                    logprobs=item.get("logprobs"),
                                ),
                                None,
                            )
                        )
            except asyncio.CancelledError:
                raise
            except Exception as e:  # surface, don't truncate silently
                await queue.put((e, None))
            finally:
                await stream.aclose()
                await queue.put((None, None))  # stream-done marker

        tasks = [asyncio.ensure_future(pump(i, s)) for i, s in enumerate(streams)]
        try:
            if annotations:
                yield {"__annotations__": annotations}
            done = 0
            usages = []
            while done < len(streams):
                chunk, usage = await queue.get()
                if usage:
                    usages.append(usage)
                if chunk is None:
                    done += 1
                    continue
                if isinstance(chunk, Exception):
                    # A failed choice fails the request, matching n=1.
                    raise chunk
                yield chunk
            if usages:
                merged = {
                    "prompt_tokens": usages[0].get("prompt_tokens", 0),
                    "completion_tokens": sum(
                        u.get("completion_tokens", 0) for u in usages
                    ),
                }
                merged["total_tokens"] = (
                    merged["prompt_tokens"] + merged["completion_tokens"]
                )
                gen = DeltaGenerator(model, chat=chat, request_id=request_id)
                yield gen.usage_chunk(merged)
        finally:
            for t in tasks:
                t.cancel()

    async def _to_chunks(
        self,
        stream: ResponseStream,
        model: str,
        chat: bool,
        request_id: str,
        annotations: Dict[str, Any],
    ) -> AsyncIterator[Dict[str, Any]]:
        gen = DeltaGenerator(model, chat=chat, request_id=request_id)
        try:
            if annotations:
                yield {"__annotations__": annotations}
            async for item in stream:
                reason = item.get("finish_reason")
                if reason is not None:
                    if item.get("usage"):
                        # merge usage into the finish chunk (OpenAI shape
                        # allows usage on the final chunk)
                        chunk = gen.finish_chunk(reason)
                        chunk["usage"] = item["usage"]
                        yield chunk
                    else:
                        yield gen.finish_chunk(reason)
                    return
                if item.get("text") or item.get("logprobs"):
                    yield gen.text_chunk(
                        item.get("text") or "", logprobs=item.get("logprobs")
                    )
        finally:
            await stream.aclose()
