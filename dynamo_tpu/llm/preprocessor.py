"""OpenAI preprocessor operator: template + tokenize → PreprocessedRequest.

Reference semantics: lib/llm/src/preprocessor.rs (OpenAIPreprocessor) — the
forward edge renders the chat template and tokenizes into ``BackendInput``;
the backward edge shapes backend text deltas into OpenAI chunks via
``DeltaGenerator``.  Annotation requests (nvext.annotations) can echo the
formatted prompt / token ids back to the caller as annotation events.
"""

from __future__ import annotations

import asyncio
from typing import Any, AsyncIterator, Dict, Optional, Union

from ..runtime.engine import AsyncEngine, Context, ResponseStream
from ..runtime.pipeline import Operator
from .openai import ChatCompletionRequest, CompletionRequest, DeltaGenerator
from .protocols import PreprocessedRequest
from .tokenizer import BaseTokenizer


class OpenAIPreprocessor(Operator):
    """Chat/completions requests → token-level requests → OpenAI chunks."""

    def __init__(self, tokenizer: BaseTokenizer, model_name: str = ""):
        self._tokenizer = tokenizer
        self.model_name = model_name

    # -- forward ------------------------------------------------------------

    def preprocess(
        self, oai: Union[ChatCompletionRequest, CompletionRequest, Dict[str, Any]]
    ) -> PreprocessedRequest:
        if isinstance(oai, dict):
            oai = (
                ChatCompletionRequest.model_validate(oai)
                if "messages" in oai
                else CompletionRequest.model_validate(oai)
            )
        if isinstance(oai, ChatCompletionRequest):
            if oai.nvext and oai.nvext.use_raw_prompt and len(oai.messages) == 1:
                prompt = oai.messages[0].text()
            else:
                prompt = self._tokenizer.apply_chat_template(
                    [
                        {"role": m.role, "content": m.text()}
                        for m in oai.messages
                    ],
                    add_generation_prompt=True,
                    tools=oai.tools,
                )
            token_ids = self._tokenizer.encode(prompt, add_special_tokens=False)
        else:
            prompt_field = oai.prompt
            if isinstance(prompt_field, list) and prompt_field and isinstance(prompt_field[0], int):
                prompt = None
                token_ids = list(prompt_field)
            else:
                prompt = prompt_field if isinstance(prompt_field, str) else str(prompt_field)
                token_ids = self._tokenizer.encode(prompt)
        annotations: Dict[str, Any] = {}
        if oai.nvext and oai.nvext.annotations:
            if "formatted_prompt" in oai.nvext.annotations and prompt is not None:
                annotations["formatted_prompt"] = prompt
            if "token_ids" in oai.nvext.annotations:
                annotations["token_ids"] = token_ids
        return PreprocessedRequest(
            token_ids=token_ids,
            stop_conditions=oai.stop_conditions(),
            sampling_options=oai.sampling_options(),
            model=oai.model,
            annotations=annotations,
        )

    # -- the operator -------------------------------------------------------

    async def generate(self, request: Context, next: AsyncEngine) -> ResponseStream:
        raw = request.data
        chat = "messages" in raw if isinstance(raw, dict) else True
        pre = self.preprocess(raw)
        model = pre.model or self.model_name
        n = int(raw.get("n") or 1) if isinstance(raw, dict) else 1
        if n <= 1:
            stream = await next.generate(request.transfer(pre.to_dict()))
            return ResponseStream(
                self._to_chunks(stream, model, chat, request.id, pre.annotations),
                request.ctx,
            )
        # n > 1: one engine request per choice — the prefix cache shares the
        # prompt KV across them; streams merge with per-choice indices.
        # Reference: protocols/openai (n) + multiple SSE choice indices.
        import dataclasses

        from ..runtime.engine import AsyncEngineContext

        streams = []
        for i in range(n):
            child = AsyncEngineContext(f"{request.id}-c{i}")
            request.ctx.link_child(child)
            pre_i = pre
            if pre.sampling_options.seed is not None:
                so = dataclasses.replace(
                    pre.sampling_options, seed=pre.sampling_options.seed + i
                )
                pre_i = dataclasses.replace(pre, sampling_options=so)
            streams.append(
                await next.generate(Context(pre_i.to_dict(), child))
            )
        return ResponseStream(
            self._merge_choices(
                streams, model, chat, request.id, pre.annotations
            ),
            request.ctx,
        )

    async def _merge_choices(
        self,
        streams,
        model: str,
        chat: bool,
        request_id: str,
        annotations: Dict[str, Any],
    ) -> AsyncIterator[Dict[str, Any]]:
        """Interleave n sub-request streams into one chunk stream with
        per-choice indices; one summed usage chunk at the end."""
        import asyncio

        queue: "asyncio.Queue" = asyncio.Queue()

        async def pump(i: int, stream) -> None:
            gen = DeltaGenerator(model, chat=chat, request_id=request_id, index=i)
            try:
                async for item in stream:
                    reason = item.get("finish_reason")
                    if reason is not None:
                        await queue.put((gen.finish_chunk(reason), item.get("usage")))
                        return
                    if item.get("text") or item.get("logprobs"):
                        await queue.put(
                            (
                                gen.text_chunk(
                                    item.get("text") or "",
                                    logprobs=item.get("logprobs"),
                                ),
                                None,
                            )
                        )
            except asyncio.CancelledError:
                raise
            except Exception as e:  # surface, don't truncate silently
                await queue.put((e, None))
            finally:
                await stream.aclose()
                await queue.put((None, None))  # stream-done marker

        tasks = [asyncio.ensure_future(pump(i, s)) for i, s in enumerate(streams)]
        try:
            if annotations:
                yield {"__annotations__": annotations}
            done = 0
            usages = []
            while done < len(streams):
                chunk, usage = await queue.get()
                if usage:
                    usages.append(usage)
                if chunk is None:
                    done += 1
                    continue
                if isinstance(chunk, Exception):
                    # A failed choice fails the request, matching n=1.
                    raise chunk
                yield chunk
            if usages:
                merged = {
                    "prompt_tokens": usages[0].get("prompt_tokens", 0),
                    "completion_tokens": sum(
                        u.get("completion_tokens", 0) for u in usages
                    ),
                }
                merged["total_tokens"] = (
                    merged["prompt_tokens"] + merged["completion_tokens"]
                )
                gen = DeltaGenerator(model, chat=chat, request_id=request_id)
                yield gen.usage_chunk(merged)
        finally:
            for t in tasks:
                t.cancel()

    async def _to_chunks(
        self,
        stream: ResponseStream,
        model: str,
        chat: bool,
        request_id: str,
        annotations: Dict[str, Any],
    ) -> AsyncIterator[Dict[str, Any]]:
        gen = DeltaGenerator(model, chat=chat, request_id=request_id)
        try:
            if annotations:
                yield {"__annotations__": annotations}
            async for item in stream:
                reason = item.get("finish_reason")
                if reason is not None:
                    if item.get("usage"):
                        # merge usage into the finish chunk (OpenAI shape
                        # allows usage on the final chunk)
                        chunk = gen.finish_chunk(reason)
                        chunk["usage"] = item["usage"]
                        yield chunk
                    else:
                        yield gen.finish_chunk(reason)
                    return
                if item.get("text") or item.get("logprobs"):
                    yield gen.text_chunk(
                        item.get("text") or "", logprobs=item.get("logprobs")
                    )
        finally:
            await stream.aclose()
