"""Engine-free test engines (echo) — reference lib/llm/src/engines.rs:40-105.

``EchoEngineCore`` speaks the token-level protocol (PreprocessedRequest in,
LLMEngineOutput dicts out) and echoes the prompt tokens back one at a time —
it lets the entire distributed serving graph (HTTP → preprocess → route →
backend) run and be load-tested with no model and no TPU, like the
reference's ``out=echocore``.  ``DYN_TOKEN_ECHO_DELAY_MS`` (env) or the
``delay_ms`` argument paces emission to simulate decode latency.

``EchoEngineFull`` echoes at the OpenAI level (``out=echofull``).
"""

from __future__ import annotations

import asyncio
import os
from typing import Any, AsyncIterator, Dict

from ..runtime.engine import AsyncEngine, Context, ResponseStream
from .openai import ChatCompletionRequest, CompletionRequest, DeltaGenerator
from .protocols import FinishReason, LLMEngineOutput, PreprocessedRequest


def _delay_s(delay_ms: float | None) -> float:
    if delay_ms is None:
        delay_ms = float(os.environ.get("DYN_TOKEN_ECHO_DELAY_MS", "0"))
    return delay_ms / 1000.0


class EchoEngineCore(AsyncEngine):
    """Token-in/token-out echo: yields the prompt tokens back."""

    def __init__(self, delay_ms: float | None = None):
        self._delay = _delay_s(delay_ms)

    async def generate(self, request: Context) -> ResponseStream:
        pre = PreprocessedRequest.from_dict(request.data)

        async def gen() -> AsyncIterator[Dict[str, Any]]:
            max_tokens = pre.stop_conditions.max_tokens
            emitted = 0
            for tok in pre.token_ids:
                if request.is_stopped:
                    break
                if max_tokens is not None and emitted >= max_tokens:
                    break
                if self._delay:
                    await asyncio.sleep(self._delay)
                yield LLMEngineOutput.token(tok)
                emitted += 1
            yield LLMEngineOutput.finished(
                FinishReason.LENGTH,
                usage={
                    "prompt_tokens": len(pre.token_ids),
                    "completion_tokens": emitted,
                    "total_tokens": len(pre.token_ids) + emitted,
                },
            )

        return ResponseStream(gen(), request.ctx)


class EchoEngineFull(AsyncEngine):
    """OpenAI-level echo: streams the prompt text back as chunks."""

    def __init__(self, delay_ms: float | None = None):
        self._delay = _delay_s(delay_ms)

    async def generate(self, request: Context) -> ResponseStream:
        raw = request.data
        chat = "messages" in raw
        if chat:
            oai = ChatCompletionRequest.model_validate(raw)
            text = oai.messages[-1].text() if oai.messages else ""
        else:
            oai = CompletionRequest.model_validate(raw)
            text = oai.prompt if isinstance(oai.prompt, str) else str(oai.prompt)

        async def gen() -> AsyncIterator[Dict[str, Any]]:
            gen_ = DeltaGenerator(oai.model, chat=chat, request_id=request.id)
            for word in text.split():
                if request.is_stopped:
                    break
                if self._delay:
                    await asyncio.sleep(self._delay)
                yield gen_.text_chunk(word + " ")
            yield gen_.finish_chunk("stop")

        return ResponseStream(gen(), request.ctx)
