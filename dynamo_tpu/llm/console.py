"""Console input modes for ``cli run``: interactive chat, single-prompt
stdin, and batch-file evaluation.

Reference parity: ``dynamo-run in=text|stdin|batch:FILE``
(/root/reference/launch/dynamo-run/src/opt.rs:23-38, input/text.rs,
input/batch.rs).  All three drive the SAME pipeline object the HTTP
frontend serves (preprocessor → backend → engine), so a prompt typed at
the REPL exercises chat templates, sampling, and streaming identically to
a /v1/chat/completions call.

Batch file format (reference input/batch.rs Entry): one JSON object per
line with ``{"text": ...}``; results are written next to the input as
``output.jsonl`` with response/tokens_in/tokens_out/elapsed_ms/
finish_reason added.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

from ..runtime.engine import Context


def _chat_request(model: str, messages: List[dict], args) -> Dict[str, Any]:
    req: Dict[str, Any] = {
        "model": model,
        "messages": messages,
        "stream": True,
    }
    if getattr(args, "max_tokens", None):
        req["max_tokens"] = args.max_tokens
    if getattr(args, "temperature", None) is not None:
        req["temperature"] = args.temperature
    return req


async def _stream_chat(pipeline, req, out) -> Dict[str, Any]:
    """Stream one chat request, echoing deltas to ``out``; returns
    {content, finish_reason, usage}."""
    parts: List[str] = []
    finish = None
    usage: Dict[str, Any] = {}
    stream = await pipeline.generate(Context(req))
    try:
        async for chunk in stream:
            if "__annotations__" in chunk:
                continue
            for ch in chunk.get("choices") or []:
                delta = (ch.get("delta") or {}).get("content") or ch.get("text")
                if delta:
                    parts.append(delta)
                    if out is not None:
                        out.write(delta)
                        out.flush()
                if ch.get("finish_reason"):
                    finish = ch["finish_reason"]
            if chunk.get("usage"):
                usage = chunk["usage"]
    finally:
        await stream.aclose()
    return {"content": "".join(parts), "finish_reason": finish, "usage": usage}


async def run_text_chat(pipeline, model: str, args, *, instream=None, out=None) -> None:
    """Interactive chat REPL with in-session message history (in=text).
    EOF (ctrl-D) or an empty line with ctrl-C exits."""
    instream = instream or sys.stdin
    out = out or sys.stdout
    loop = asyncio.get_running_loop()
    messages: List[dict] = []
    out.write(f"chat with {model!r} — ctrl-D to exit\n")
    while True:
        out.write("> ")
        out.flush()
        line = await loop.run_in_executor(None, instream.readline)
        if not line:  # EOF
            out.write("\n")
            return
        prompt = line.strip()
        if not prompt:
            continue
        messages.append({"role": "user", "content": prompt})
        try:
            result = await _stream_chat(
                pipeline, _chat_request(model, messages, args), out
            )
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — REPL stays alive
            out.write(f"error: {e}\n")
            messages.pop()
            continue
        out.write("\n")
        messages.append({"role": "assistant", "content": result["content"]})


async def run_stdin_prompt(pipeline, model: str, args, *, instream=None, out=None) -> None:
    """Read ONE prompt (whole stdin), stream the completion, exit (in=stdin)."""
    instream = instream or sys.stdin
    out = out or sys.stdout
    loop = asyncio.get_running_loop()
    prompt = (await loop.run_in_executor(None, instream.read)).strip()
    if not prompt:
        raise SystemExit("in=stdin: empty prompt on stdin")
    messages = [{"role": "user", "content": prompt}]
    await _stream_chat(pipeline, _chat_request(model, messages, args), out)
    out.write("\n")


async def run_batch(
    pipeline, model: str, path: str, args, *, concurrency: int = 8, out=None
) -> str:
    """Evaluate every ``{"text": ...}`` line of ``path``; write
    ``output.jsonl`` beside it (in=batch:FILE).  Returns the output path."""
    out = out or sys.stderr
    if not os.path.isfile(path):
        raise SystemExit(f"in=batch: no such file {path!r}")
    with open(path) as f:
        entries = []
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError as e:
                raise SystemExit(f"in=batch: {path}:{ln}: invalid JSON ({e})")
            if not isinstance(obj, dict) or not isinstance(obj.get("text"), str):
                raise SystemExit(f'in=batch: {path}:{ln}: need {{"text": ...}}')
            entries.append(obj)

    sem = asyncio.Semaphore(concurrency)
    results: List[Optional[dict]] = [None] * len(entries)
    t0 = time.perf_counter()

    async def one(i: int, entry: dict) -> None:
        async with sem:
            start = time.perf_counter()
            req = _chat_request(model, [{"role": "user", "content": entry["text"]}], args)
            try:
                r = await _stream_chat(pipeline, req, None)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — batch keeps going
                results[i] = dict(entry, response=None, error=str(e))
                return
            usage = r["usage"] or {}
            results[i] = dict(
                entry,
                response=r["content"],
                tokens_in=usage.get("prompt_tokens", 0),
                tokens_out=usage.get("completion_tokens", 0),
                elapsed_ms=int((time.perf_counter() - start) * 1e3),
                finish_reason=r["finish_reason"],
            )

    await asyncio.gather(*[one(i, e) for i, e in enumerate(entries)])
    elapsed = time.perf_counter() - t0

    out_path = os.path.join(os.path.dirname(os.path.abspath(path)), "output.jsonl")
    with open(out_path, "w") as f:
        for r in results:
            f.write(json.dumps(r) + "\n")
    tokens_out = sum((r or {}).get("tokens_out", 0) for r in results)
    out.write(
        f"batch: {len(entries)} prompts in {elapsed:.1f}s "
        f"({tokens_out} output tokens, {tokens_out / max(elapsed, 1e-9):.1f} tok/s) "
        f"-> {out_path}\n"
    )
    return out_path
