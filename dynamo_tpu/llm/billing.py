"""Billing events on the event plane (reference: lib/llm/src/billing.rs:35-67,
the baseten fork's addition): per-request token usage published to the
``token_events`` subject for a metering consumer."""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Any, Dict, Optional

TOKEN_EVENTS_SUBJECT = "token_events"


@dataclass(frozen=True)
class BillingEvent:
    input_tokens: int
    output_tokens: int
    model: str
    organization_id: Optional[str] = None
    request_id: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "input_tokens": self.input_tokens,
            "output_tokens": self.output_tokens,
            "model": self.model,
            "organization_id": self.organization_id,
            "request_id": self.request_id,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "BillingEvent":
        return cls(
            input_tokens=int(d.get("input_tokens", 0)),
            output_tokens=int(d.get("output_tokens", 0)),
            model=d.get("model", ""),
            organization_id=d.get("organization_id"),
            request_id=d.get("request_id"),
        )


class BillingPublisher:
    def __init__(self, namespace):
        self._namespace = namespace
        self._bg: set = set()

    async def publish(self, event: BillingEvent) -> None:
        await self._namespace.publish(TOKEN_EVENTS_SUBJECT, event.to_dict())

    def publish_nowait(self, event: BillingEvent) -> None:
        task = asyncio.get_event_loop().create_task(self.publish(event))
        self._bg.add(task)
        task.add_done_callback(self._bg.discard)
