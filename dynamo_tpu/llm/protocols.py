"""Internal LLM protocols: the token-level request/response types.

Reference semantics: lib/llm/src/protocols/common.rs — ``StopConditions``,
``SamplingOptions``, ``PreprocessedRequest`` (aka BackendInput),
``LLMEngineOutput``, ``FinishReason``.  These cross process boundaries, so the
canonical wire form is a plain dict (msgpack-friendly); the classes here are
thin construction/validation helpers with ``to_dict``/``from_dict``.

Per-token engine outputs stay plain dicts on the hot path (one per generated
token per request) — schema documented on ``LLMEngineOutput``.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


class ModelNotFoundError(LookupError):
    """Request named a model/adapter nobody serves.  Raised by engines
    (TpuEngine._resolve_adapter) and mapped to the OpenAI 404
    ``model_not_found`` error body at the HTTP edge — never silently
    falling through to the base model (llm/tenancy)."""

    # Wire tag: the service transport ships this in its error prologue so
    # remote callers (runtime/client.py RemoteEngineError.kind) can map the
    # failure back to a 404 without importing this module.
    error_kind = "model_not_found"

    def __init__(self, model: str):
        super().__init__(f"model {model!r} not found")
        self.model = model


class FinishReason(str, enum.Enum):
    STOP = "stop"  # hit eos or a stop sequence
    LENGTH = "length"  # hit max_tokens
    CANCELLED = "cancelled"  # request cancelled
    ERROR = "error"

    def __str__(self) -> str:  # serialize as bare string
        return self.value


@dataclass
class StopConditions:
    """When to stop generating (protocols/common.rs StopConditions)."""

    max_tokens: Optional[int] = None
    min_tokens: Optional[int] = None
    stop: List[str] = field(default_factory=list)  # stop strings (hidden)
    stop_token_ids: List[int] = field(default_factory=list)
    ignore_eos: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return {
            "max_tokens": self.max_tokens,
            "min_tokens": self.min_tokens,
            "stop": self.stop,
            "stop_token_ids": self.stop_token_ids,
            "ignore_eos": self.ignore_eos,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "StopConditions":
        return cls(
            max_tokens=d.get("max_tokens"),
            min_tokens=d.get("min_tokens"),
            stop=list(d.get("stop") or []),
            stop_token_ids=list(d.get("stop_token_ids") or []),
            ignore_eos=bool(d.get("ignore_eos", False)),
        )


@dataclass
class SamplingOptions:
    """How to sample (protocols/common.rs SamplingOptions)."""

    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    seed: Optional[int] = None
    # None = no logprobs; 0 = chosen-token only; N = chosen + top-N
    logprobs: Optional[int] = None
    # Speculative decoding opt-out (nvext.spec_decode): False disables the
    # engine's draft-free speculation for THIS request; None/True defer to
    # the engine's spec_decode config.  Output tokens are identical either
    # way (engine/spec.py exact-stream acceptance) — the knob exists for
    # latency-shape control and for A/B measurement.
    spec_decode: Optional[bool] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "temperature": self.temperature,
            "top_p": self.top_p,
            "top_k": self.top_k,
            "frequency_penalty": self.frequency_penalty,
            "presence_penalty": self.presence_penalty,
            "seed": self.seed,
            "logprobs": self.logprobs,
            "spec_decode": self.spec_decode,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SamplingOptions":
        return cls(
            temperature=d.get("temperature"),
            top_p=d.get("top_p"),
            top_k=d.get("top_k"),
            frequency_penalty=d.get("frequency_penalty"),
            presence_penalty=d.get("presence_penalty"),
            seed=d.get("seed"),
            logprobs=d.get("logprobs"),
            spec_decode=d.get("spec_decode"),
        )


@dataclass
class PreprocessedRequest:
    """Token-in request to an engine (protocols/common.rs PreprocessedRequest).

    ``token_ids`` is the full prompt after templating+tokenization.
    ``annotations`` carries pass-through flags (e.g. requesting the engine
    echo back ``token_ids``/``formatted_prompt``).
    """

    token_ids: List[int]
    stop_conditions: StopConditions = field(default_factory=StopConditions)
    sampling_options: SamplingOptions = field(default_factory=SamplingOptions)
    model: Optional[str] = None
    annotations: Dict[str, Any] = field(default_factory=dict)
    # Structured-output constraint (llm/tenancy/grammar.py): the serialized
    # TokenMaskAutomaton dict compiled by the PREPROCESSOR (the only layer
    # holding the tokenizer); engines deserialize by content hash and apply
    # it as a per-row logit mask.  None = unconstrained.
    grammar: Optional[Dict[str, Any]] = None
    # QoS priority class (llm/qos.py): "interactive" | "batch".  None =
    # unspecified (treated as interactive downstream); parsed at the edge
    # from the x-priority header / nvext.priority and consumed by the
    # scheduler (batch rows preempt first, shed first under brownout).
    priority: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        out = {
            "token_ids": self.token_ids,
            "stop_conditions": self.stop_conditions.to_dict(),
            "sampling_options": self.sampling_options.to_dict(),
            "model": self.model,
            "annotations": self.annotations,
        }
        if self.grammar is not None:
            # Omitted when absent: pre-tenancy consumers (recorded streams,
            # older workers) never see the key.
            out["grammar"] = self.grammar
        if self.priority is not None:
            # Same omitted-when-absent wire compat as grammar.
            out["priority"] = self.priority
        return out

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PreprocessedRequest":
        return cls(
            token_ids=list(d["token_ids"]),
            stop_conditions=StopConditions.from_dict(d.get("stop_conditions") or {}),
            sampling_options=SamplingOptions.from_dict(d.get("sampling_options") or {}),
            model=d.get("model"),
            annotations=dict(d.get("annotations") or {}),
            grammar=d.get("grammar"),
            priority=d.get("priority"),
        )


class LLMEngineOutput:
    """Schema of the per-step engine output dict (kept as a plain dict on the
    wire and in the hot loop; one per generated token):

    ``{"token_ids": [int, ...],        # newly generated token(s) this step
       "text": str | None,            # filled by the Backend detokenizer
       "finish_reason": str | None,   # FinishReason value when finished
       "cum_log_prob": float | None,
       "usage": {...} | None}``        # optional final usage stats
    """

    @staticmethod
    def token(token_id: int) -> Dict[str, Any]:
        return {"token_ids": [token_id], "text": None, "finish_reason": None}

    @staticmethod
    def tokens(token_ids: List[int]) -> Dict[str, Any]:
        """Multi-token step output (fused-chunk fast path; consumers
        iterate ``token_ids``, so granularity is an engine choice)."""
        return {"token_ids": list(token_ids), "text": None, "finish_reason": None}

    @staticmethod
    def finished(reason: FinishReason, usage: Optional[Dict[str, int]] = None) -> Dict[str, Any]:
        return {"token_ids": [], "text": None, "finish_reason": str(reason), "usage": usage}
