"""Namespace metrics aggregator service + mock worker.

Reference semantics: components/metrics (src/main.rs:16-200) — a standalone
service that aggregates every worker's ForwardPassMetrics and the router's
KV-hit-rate events for one namespace and exposes them as Prometheus text
(port 9091 there); plus a mock worker (src/bin/mock_worker.rs) that
publishes synthetic metrics/events so the whole observability path is
testable with no engine and no TPU (SURVEY §4 engine-free serving).
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import Dict, List, Optional

from aiohttp import web

from ..llm.kv_router.protocols import ForwardPassMetrics, KvCacheEvent, KvCacheStoredBlockData
from ..llm.kv_router.publisher import (
    KV_EVENTS_TOPIC,
    KV_METRICS_TOPIC,
    unpack_message,
)
from ..llm.kv_router.scheduler import KV_HIT_RATE_SUBJECT
from ..labels import escape_label
from ..planner.signals import StalenessTracker, classify_instance
from ..runtime.component import INSTANCE_PREFIX, instance_prefix

logger = logging.getLogger(__name__)


class MetricsAggregatorService:
    """Aggregates worker metrics + hit-rate events; serves /metrics.

    Rows are TTL-evicted (``StalenessTracker`` — shared with the
    planner's SignalCollector) and dropped immediately when the worker's
    discovery registration disappears, so ``/metrics`` never serves a
    dead worker's last snapshot forever."""

    def __init__(
        self,
        component,
        host: str = "0.0.0.0",
        port: int = 9091,
        stale_after_s: Optional[float] = 30.0,
    ):
        self.component = component
        self.host = host
        self.port = port
        self._metrics: StalenessTracker = StalenessTracker(ttl_s=stale_after_s)
        self._hit_isl_blocks = 0
        self._hit_overlap_blocks = 0
        self._tasks: List[asyncio.Task] = []
        self._subs: List = []
        self._watcher = None
        self._runner: Optional[web.AppRunner] = None

    async def start(self) -> "MetricsAggregatorService":
        loop = asyncio.get_running_loop()
        m_sub = await self.component.subscribe(KV_METRICS_TOPIC)
        h_sub = await self.component.subscribe(KV_HIT_RATE_SUBJECT)
        self._subs = [m_sub, h_sub]
        self._tasks = [
            loop.create_task(self._consume_metrics(m_sub)),
            loop.create_task(self._consume_hit_rate(h_sub)),
        ]
        # Instance-gone eviction: watch the namespace's discovery prefix;
        # a delete (lease expiry / deregistration) drops the row at once —
        # the TTL only covers workers that die without ever registering.
        ns = self.component.namespace.name
        self._watcher = await self.component.runtime.hub.watch_prefix(
            instance_prefix(ns)
        )
        self._tasks.append(loop.create_task(self._consume_instances(self._watcher)))
        app = web.Application()
        app.router.add_get("/metrics", self._handle_metrics)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        return self

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass
        for sub in self._subs:
            if hasattr(sub, "aclose"):
                await sub.aclose()
        if self._watcher is not None:
            await self._watcher.aclose()
            self._watcher = None
        if self._runner is not None:
            await self._runner.cleanup()

    async def _consume_metrics(self, sub) -> None:
        try:
            async for msg in sub:
                payload = unpack_message(msg)
                try:
                    self._metrics.put(
                        payload["worker_id"],
                        ForwardPassMetrics.from_dict(payload["metrics"]),
                    )
                except (KeyError, TypeError):
                    pass
        except asyncio.CancelledError:
            pass

    async def _consume_instances(self, watcher) -> None:
        try:
            async for event in watcher:
                if event.type != "delete":
                    continue
                parsed = classify_instance(event.key, event.value)
                if parsed is not None:
                    self._metrics.pop(parsed[0])
        except asyncio.CancelledError:
            pass

    async def _consume_hit_rate(self, sub) -> None:
        try:
            async for msg in sub:
                payload = unpack_message(msg)
                try:
                    self._hit_isl_blocks += payload["isl_blocks"]
                    self._hit_overlap_blocks += payload["overlap_blocks"]
                except (KeyError, TypeError):
                    pass
        except asyncio.CancelledError:
            pass

    def render(self) -> str:
        """Prometheus exposition text (namespace-level, per-worker labels)."""
        lines: List[str] = []

        def gauge(name: str, help_: str, per_worker) -> None:
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} gauge")
            for wid, m in self._metrics.items():
                lines.append(f'{name}{{worker_id="{escape_label(wid)}"}} {per_worker(m)}')

        gauge("dynamo_tpu_worker_active_slots", "Active request slots",
              lambda m: m.request_active_slots)
        gauge("dynamo_tpu_worker_total_slots", "Total request slots",
              lambda m: m.request_total_slots)
        gauge("dynamo_tpu_worker_kv_active_blocks", "Active KV blocks",
              lambda m: m.kv_active_blocks)
        gauge("dynamo_tpu_worker_kv_total_blocks", "Total KV blocks",
              lambda m: m.kv_total_blocks)
        gauge("dynamo_tpu_worker_requests_waiting", "Queued requests",
              lambda m: m.num_requests_waiting)
        gauge("dynamo_tpu_worker_cache_usage", "KV cache usage fraction",
              lambda m: m.gpu_cache_usage_perc)
        gauge("dynamo_tpu_worker_prefix_hit_rate", "Prefix cache hit rate",
              lambda m: m.gpu_prefix_cache_hit_rate)
        lines.append("# HELP dynamo_tpu_router_isl_blocks Router-observed prompt blocks")
        lines.append("# TYPE dynamo_tpu_router_isl_blocks counter")
        lines.append(f"dynamo_tpu_router_isl_blocks {self._hit_isl_blocks}")
        lines.append("# HELP dynamo_tpu_router_overlap_blocks Router-matched prefix blocks")
        lines.append("# TYPE dynamo_tpu_router_overlap_blocks counter")
        lines.append(f"dynamo_tpu_router_overlap_blocks {self._hit_overlap_blocks}")
        return "\n".join(lines) + "\n"

    async def _handle_metrics(self, request: web.Request) -> web.Response:
        return web.Response(text=self.render(), content_type="text/plain")


class MockWorker:
    """Publishes synthetic ForwardPassMetrics + KV events (reference:
    components/metrics/src/bin/mock_worker.rs) — lets the full router +
    observability path run with no engine."""

    def __init__(self, component, worker_id: int, block_size: int = 16,
                 interval: float = 0.5, seed: int = 0):
        self.component = component
        self.worker_id = worker_id
        self.block_size = block_size
        self.interval = interval
        self._rng = random.Random(seed)
        self._task: Optional[asyncio.Task] = None
        self._event_id = 0

    async def start(self) -> "MockWorker":
        self._task = asyncio.get_running_loop().create_task(self._run())
        return self

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    async def _run(self) -> None:
        try:
            while True:
                metrics = ForwardPassMetrics(
                    request_active_slots=self._rng.randint(0, 8),
                    request_total_slots=8,
                    kv_active_blocks=self._rng.randint(0, 256),
                    kv_total_blocks=256,
                    num_requests_waiting=self._rng.randint(0, 4),
                    gpu_cache_usage_perc=self._rng.random(),
                    gpu_prefix_cache_hit_rate=self._rng.random(),
                )
                await self.component.publish(
                    KV_METRICS_TOPIC,
                    {"worker_id": self.worker_id, "metrics": metrics.to_dict()},
                )
                self._event_id += 1
                event = KvCacheEvent.stored(
                    self._event_id,
                    None,
                    [
                        KvCacheStoredBlockData(
                            self._rng.getrandbits(63), self._rng.getrandbits(63)
                        )
                    ],
                )
                await self.component.publish(
                    KV_EVENTS_TOPIC,
                    {"worker_id": self.worker_id, "event": event.to_dict()},
                )
                await asyncio.sleep(self.interval)
        except asyncio.CancelledError:
            pass
