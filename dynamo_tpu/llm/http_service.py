"""OpenAI-compatible HTTP frontend (aiohttp).

Reference semantics: lib/llm/src/http/service/{service_v2,openai}.rs — routes
``/v1/chat/completions``, ``/v1/completions``, ``/v1/models``, ``/metrics``,
``/health``; every downstream engine streams, ``stream=false`` responses are
aggregated at the edge (aggregator.rs); a client disconnect mid-stream calls
``stop_generating`` and records status ``client_drop``; Prometheus metrics via
``InflightGuard`` (metrics.rs:319).

The ``ModelManager`` maps model name → chat/completion pipelines
(http/service.rs:59-120); engines are added statically or by the hub model
watcher (discovery.py).
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Any, Dict, Optional

from aiohttp import web

from ..labels import bounded_label
from ..runtime.client import NoInstancesError, RemoteEngineError
from ..runtime.engine import AsyncEngine, Context
from ..runtime.resilience import (
    AdmissionController,
    AdmissionRejected,
    Deadline,
    DeadlineExceededError,
)
from ..runtime.resilience import metrics as resilience_metrics
from .metrics import Metrics, Status, qos_metrics
from .openai import SSE_DONE, aggregate_chunks, sse_encode
from .protocols import ModelNotFoundError
from .qos import (
    BATCH,
    BrownoutSignals,
    QosController,
    QosShed,
    RUNG_CAP_TOKENS,
    RUNG_SHED_INTERACTIVE,
    RUNG_SPEC_STANDDOWN,
    resolve_priority,
    resolve_tenant,
)
from .tenancy.lora import AdapterCapacityError
from .trace_service import EdgeRequestTrace

logger = logging.getLogger(__name__)


class _TracedGuard:
    """Metrics InflightGuard wrapper that mirrors token/finish callbacks to
    the request's EdgeRequestTrace — one wrapper covers every status path
    in the handlers without touching them individually."""

    __slots__ = ("_guard", "_ert")

    def __init__(self, guard, ert: EdgeRequestTrace):
        self._guard = guard
        self._ert = ert

    def on_token(self, *args, **kwargs) -> None:
        self._ert.on_first_token()
        self._guard.on_token(*args, **kwargs)

    def finish(self, status) -> None:
        self._guard.finish(status)
        self._ert.finish(str(status))


class ModelManager:
    """Model name → engine registry (chat + completion separately)."""

    def __init__(self):
        self._chat: Dict[str, AsyncEngine] = {}
        self._completion: Dict[str, AsyncEngine] = {}

    def add_chat_model(self, name: str, engine: AsyncEngine) -> None:
        self._chat[name] = engine

    def add_completion_model(self, name: str, engine: AsyncEngine) -> None:
        self._completion[name] = engine

    def remove_model(self, name: str) -> None:
        self._chat.pop(name, None)
        self._completion.pop(name, None)

    def chat_engine(self, name: str) -> Optional[AsyncEngine]:
        return self._chat.get(name)

    def completion_engine(self, name: str) -> Optional[AsyncEngine]:
        return self._completion.get(name)

    def model_names(self) -> list:
        return sorted(set(self._chat) | set(self._completion))

    def has_model(self, name: str) -> bool:
        return name in self._chat or name in self._completion


class HttpError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


class HttpService:
    """The OpenAI ingress service."""

    def __init__(
        self,
        host: str = "0.0.0.0",
        port: int = 8000,
        metrics_prefix: str = "dynamo_tpu",
        model_manager: Optional[ModelManager] = None,
        max_inflight: Optional[int] = None,
        admission_queue: int = 0,
        admission_timeout_s: float = 1.0,
        default_deadline_s: Optional[float] = None,
        qos: Optional[QosController] = None,
        kv_usage_fn=None,
        tracing=None,
        trace_aggregator=None,
        hub=None,
    ):
        self.host = host
        self.port = port
        self.models = model_manager or ModelManager()
        self.metrics = Metrics(metrics_prefix)
        self._metrics_prefix = metrics_prefix
        # Admission control (disabled unless max_inflight is set): beyond
        # the in-flight cap requests wait in a bounded FIFO; overflow sheds
        # 429, wait-timeout sheds 503 — latency stays bounded instead of
        # collapsing under burst.  Batch-class requests may only occupy the
        # front half of the queue (llm/qos.py priority classes).
        self.admission = AdmissionController(
            max_inflight=max_inflight,
            max_queue=admission_queue,
            queue_timeout_s=admission_timeout_s,
        )
        # QoS/overload control (llm/qos.py): per-tenant token buckets + the
        # brownout degradation ladder.  None = disabled (zero behaviour
        # change).  ``kv_usage_fn`` optionally feeds the ladder a KV-
        # pressure signal when an engine/collector is colocated.
        self.qos = qos
        self._kv_usage_fn = kv_usage_fn
        self._qos_task: Optional[asyncio.Task] = None
        # Per-request wall-clock budget (None = unbounded, the previous
        # behaviour); exhaustion maps to 504 below.
        self.default_deadline_s = default_deadline_s
        # Distributed request tracing (runtime/tracing.py): ``tracing`` is
        # a TraceSampler (None = edge never samples, zero cost);
        # ``trace_aggregator`` serves assembled traces at /traces (wired by
        # the CLI — a hub subscription for routed fleets, a direct exporter
        # sink when the engine is colocated).
        self.tracing = tracing
        self.trace_aggregator = trace_aggregator
        # Control-plane client (HubClient or ShardedHubClient): /health
        # reports per-shard connectivity so a one-shard outage is visible
        # at the edge before it pages as anything else.  None = the edge
        # runs hub-less (tests, colocated engines) — zero change.
        self.hub = hub
        self.app = web.Application()
        self.app.router.add_post("/v1/chat/completions", self._chat_completions)
        self.app.router.add_post("/v1/completions", self._completions)
        self.app.router.add_get("/v1/models", self._list_models)
        self.app.router.add_get("/metrics", self._metrics)
        self.app.router.add_get("/health", self._health)
        self.app.router.add_get("/live", self._health)
        self.app.router.add_get("/traces", self._traces_recent)
        self.app.router.add_get("/traces/{trace_id}", self._trace_get)
        self._runner: Optional[web.AppRunner] = None

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> "HttpService":
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in site._server.sockets:  # resolve port 0
            self.port = s.getsockname()[1]
            break
        logger.info("HTTP service listening on %s:%s", self.host, self.port)
        if self.qos is not None and self.qos.ladder is not None:
            self._qos_task = asyncio.get_running_loop().create_task(
                self._qos_tick_loop()
            )
        return self

    async def close(self) -> None:
        if self._qos_task is not None:
            self._qos_task.cancel()
            try:
                await self._qos_task
            except asyncio.CancelledError:
                pass
            self._qos_task = None
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    async def _qos_tick_loop(self) -> None:
        """Drive the brownout ladder off live edge signals.  The ladder
        itself is pure (llm/qos.py BrownoutLadder.tick); this loop only
        samples queue depth, rolling TTFT and (optionally) KV usage on the
        configured interval and publishes the rung to metrics."""
        ladder = self.qos.ladder
        while True:
            await asyncio.sleep(self.qos.config.tick_s)
            kv_usage = 0.0
            if self._kv_usage_fn is not None:
                try:
                    kv_usage = float(self._kv_usage_fn())
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 — signal source is optional
                    logger.warning("qos kv_usage_fn failed", exc_info=True)
            # TTFT from the AGE-bounded window (None = no first token in
            # the last few seconds): the count-bounded planner windows
            # would hold a spike's samples long after it ended — at zero
            # traffic forever — and the ladder could never recover.
            ttft_p95_ms = self.metrics.recent_ttft_p95_ms()
            before = ladder.rung
            ladder.tick(
                BrownoutSignals(
                    queue_depth=float(self.admission.queued),
                    kv_usage=kv_usage,
                    ttft_p95_ms=ttft_p95_ms,
                )
            )
            qos_metrics.brownout_rung = ladder.rung
            if ladder.rung != before:
                qos_metrics.brownout_transitions_total += 1
                logger.warning(
                    "brownout rung %d -> %d (queue=%d ttft_p95=%sms)",
                    before, ladder.rung, self.admission.queued,
                    "%.0f" % ttft_p95_ms if ttft_p95_ms is not None else "-",
                )

    async def run(self, shutdown: Optional[asyncio.Event] = None) -> None:
        await self.start()
        try:
            if shutdown is None:
                await asyncio.Event().wait()
            else:
                await shutdown.wait()
        finally:
            await self.close()

    # -- handlers -----------------------------------------------------------

    async def _health(self, request: web.Request) -> web.Response:
        body = {"status": "ok", "models": self.models.model_names()}
        if self.qos is not None and self.qos.ladder is not None:
            body["brownout"] = self.qos.ladder.state()
        if self.hub is not None:
            # Sharded client → per-shard connectivity; plain HubClient →
            # one synthetic shard so the schema is the same either way.
            shard_health = getattr(self.hub, "shard_health", None)
            if shard_health is not None:
                shards = shard_health()
            else:
                shards = [{
                    "shard": getattr(self.hub, "address", ""),
                    "connected": bool(getattr(self.hub, "connected", False)),
                }]
            body["hub_shards"] = shards
            if not all(s["connected"] for s in shards):
                body["status"] = "degraded"
        return web.json_response(body)

    async def _metrics(self, request: web.Request) -> web.Response:
        # Planner decisions/state ride along when a planner runs in this
        # process (module-level singleton, same pattern as resilience), as
        # do the engine's speculative-decoding gauges when the engine is
        # colocated (llm/metrics.py spec_metrics).
        from ..planner.pmetrics import metrics as planner_metrics
        from ..runtime.health import health_metrics
        from .metrics import (
            bulk_metrics,
            engine_dispatch_metrics,
            kv_integrity_metrics,
            kv_tier_metrics,
            migration_metrics,
            objstore_metrics,
            spec_metrics,
            tenancy_metrics,
        )

        from ..runtime.tracing import tracing_metrics
        from ..runtime.transports.shard import shard_metrics

        body = (
            self.metrics.render()
            + resilience_metrics.render(self._metrics_prefix).encode()
            + tracing_metrics.render(self._metrics_prefix).encode()
            + planner_metrics.render(self._metrics_prefix).encode()
            + spec_metrics.render(self._metrics_prefix).encode()
            + migration_metrics.render(self._metrics_prefix).encode()
            + tenancy_metrics.render(self._metrics_prefix).encode()
            + health_metrics.render(self._metrics_prefix).encode()
            + qos_metrics.render(self._metrics_prefix).encode()
            + engine_dispatch_metrics.render(self._metrics_prefix).encode()
            + kv_tier_metrics.render(self._metrics_prefix).encode()
            + kv_integrity_metrics.render(self._metrics_prefix).encode()
            + objstore_metrics.render(self._metrics_prefix).encode()
            + bulk_metrics.render(self._metrics_prefix).encode()
            + shard_metrics.render(self._metrics_prefix).encode()
        )
        return web.Response(body=body, content_type="text/plain")

    async def _traces_recent(self, request: web.Request) -> web.Response:
        """``/traces?recent=N``: the aggregator's most recent assemblies."""
        if self.trace_aggregator is None:
            return _error_response(404, "tracing aggregator not configured")
        try:
            n = int(request.query.get("recent", 20))
        except (TypeError, ValueError):
            n = 20
        return web.json_response({"traces": self.trace_aggregator.recent(n)})

    async def _trace_get(self, request: web.Request) -> web.Response:
        """``/traces/{id}``: one assembled trace + its per-hop rollup."""
        if self.trace_aggregator is None:
            return _error_response(404, "tracing aggregator not configured")
        tid = request.match_info["trace_id"]
        trace = self.trace_aggregator.get(tid)
        if trace is None:
            return _error_response(404, f"trace {tid!r} not assembled here")
        return web.json_response(trace)

    async def _list_models(self, request: web.Request) -> web.Response:
        now = int(time.time())
        return web.json_response(
            {
                "object": "list",
                "data": [
                    {"id": name, "object": "model", "created": now, "owned_by": "dynamo_tpu"}
                    for name in self.models.model_names()
                ],
            }
        )

    async def _chat_completions(self, request: web.Request) -> web.StreamResponse:
        return await self._handle_openai(request, chat=True)

    async def _completions(self, request: web.Request) -> web.StreamResponse:
        return await self._handle_openai(request, chat=False)

    async def _handle_openai(self, request: web.Request, chat: bool) -> web.StreamResponse:
        endpoint = "chat_completions" if chat else "completions"
        try:
            body = await request.json()
        except (json.JSONDecodeError, UnicodeDecodeError):
            return _error_response(400, "invalid JSON body")
        model = body.get("model")
        if not isinstance(model, str) or not model:
            return _error_response(400, "missing 'model'")
        engine = (
            self.models.chat_engine(model) if chat else self.models.completion_engine(model)
        )
        if engine is None:
            # Label with a CONSTANT, not the wire string: every junk model
            # name would otherwise mint a fresh label value — an unbounded-
            # cardinality bomb on requests that cost us nothing else
            # (dynalint DYN201).  The 404 body still names the model.
            self.metrics.requests_total.labels(
                "unknown", endpoint, "stream", Status.REJECTED
            ).inc()
            return _model_not_found(model)
        # Past the served-model check the name is bounded (it resolved to
        # an engine) — not a cardinality hazard.  bounded_label is the
        # auditable identity marker: prometheus_client escapes at
        # exposition itself, so pre-escaping here would double-escape AND
        # split the rejected series from the success path's raw labels.
        model_label = bounded_label(model)

        # Tracing (runtime/tracing.py): the sampling decision is made once
        # here — forced (x-trace / nvext.trace) beats the head rate — and
        # the handle shadows the request even when unsampled so tail-keep
        # can promote an error/SLO-violating request's edge spans later.
        ert = EdgeRequestTrace(self.tracing, request.headers, body)

        # QoS (llm/qos.py): resolve tenant + priority, charge the tenant's
        # quota, apply the brownout rung — all BEFORE a slot is consumed.
        priority = resolve_priority(request.headers, body)
        tenant: Optional[str] = None
        if self.qos is not None:
            tenant = resolve_tenant(request.headers, body)
            if (
                self.qos.rung >= RUNG_SHED_INTERACTIVE
                and self.admission.saturated
            ):
                # Rung 4: admission is saturated — shed instead of queueing
                # (never sheds below the in-flight cap).  Checked BEFORE
                # the quota charge: a shed request consumed no capacity
                # and must not drain the tenant's bucket.
                qos_metrics.interactive_shed_total += 1
                qos_metrics.shed_tenant(tenant)
                self.metrics.requests_total.labels(
                    model_label, endpoint, "stream", Status.REJECTED
                ).inc()
                ert.finish(Status.REJECTED, model=model, endpoint=endpoint)
                return _error_response(
                    503,
                    "server in brownout (interactive overflow)",
                    retry_after_s=self.admission.estimate_retry_after(),
                )
            try:
                self.qos.admit(
                    tenant, priority, self.admission.estimate_retry_after()
                )
            except QosShed as e:
                if e.reason == "quota":
                    qos_metrics.quota_shed_total += 1
                else:
                    qos_metrics.batch_shed_total += 1
                qos_metrics.shed_tenant(tenant)
                self.metrics.requests_total.labels(
                    model_label, endpoint, "stream", Status.REJECTED
                ).inc()
                ert.finish(Status.REJECTED, model=model, endpoint=endpoint)
                return _error_response(
                    e.status, e.message, retry_after_s=e.retry_after_s
                )
            rung = self.qos.rung
            if rung >= RUNG_CAP_TOKENS:
                qos_metrics.capped_requests_total += 1
            if rung >= RUNG_SPEC_STANDDOWN:
                qos_metrics.spec_standdowns_total += 1
            if rung and ert.active:
                # Brownout rewrites are invisible in the response body —
                # record WHICH rung shaped this request on its trace.
                ert.event("brownout_rewrite", rung=rung)
            body = self.qos.shape(body)
            if tenant != model:
                # Thread the RESOLVED identity to the scheduler's WFQ
                # (preprocessor: nvext.tenant → annotations.tenant) — a
                # model-named tenant is the scheduler's own fallback, so
                # only header/credential identities need the stamp.
                # Without it, two API keys sharing a model land in one
                # WFQ flow and noisy-neighbor isolation never engages.
                nvext = body.get("nvext")
                if not isinstance(nvext, dict):
                    nvext = {}
                    body["nvext"] = nvext
                nvext["tenant"] = tenant
        if priority == BATCH or "x-priority" in request.headers:
            # Thread the resolved class to the scheduler (the preprocessor
            # reads nvext.priority into PreprocessedRequest.priority).
            # NOT setdefault: a client-sent ``"nvext": null`` would satisfy
            # it and the batch class would silently run as interactive —
            # bypassing batch-first preemption and the rung-3 shed.
            nvext = body.get("nvext")
            if not isinstance(nvext, dict):
                nvext = {}
                body["nvext"] = nvext
            nvext["priority"] = priority

        # Admission control guards everything that costs engine work; cheap
        # 400/404s above never consume a slot.  Batch-class requests only
        # queue in their reserved fraction (resilience.AdmissionController).
        ert.admission_started()
        try:
            await self.admission.acquire(priority)
        except AdmissionRejected as e:
            if self.qos is not None and tenant is not None:
                # The quota was charged above, but this request was shed
                # before consuming any capacity — credit it back.
                self.qos.quotas.refund(tenant)
            self.metrics.requests_total.labels(
                model_label, endpoint, "stream", Status.REJECTED
            ).inc()
            ert.finish(Status.REJECTED, model=model, endpoint=endpoint)
            # The drain-rate estimate says when a slot frees; a deepening
            # brownout says the estimate is optimistic — back clients off
            # harder the further down the ladder the edge already is.
            retry = e.retry_after_s
            if self.qos is not None and self.qos.rung:
                retry *= 1 + self.qos.rung
            return _error_response(e.status, e.message, retry_after_s=retry)
        except BaseException:
            # Handler cancelled (client gone) or failed while QUEUED: the
            # admission wait it died in is exactly the datum the trace
            # exists to capture — record before propagating.
            ert.finish(Status.ERROR, model=model, endpoint=endpoint)
            raise
        ert.admission_done()
        try:
            return await self._admitted_openai(
                request, body, engine, model, endpoint, ert
            )
        finally:
            self.admission.release()
            # Belt for paths no guard.finish covered (handler cancellation,
            # unexpected escapes): finish is idempotent, so completed
            # requests — already closed by _TracedGuard — are untouched.
            ert.finish(Status.ERROR, model=model, endpoint=endpoint)

    async def _admitted_openai(
        self,
        request: web.Request,
        body: Dict[str, Any],
        engine: AsyncEngine,
        model: str,
        endpoint: str,
        ert: EdgeRequestTrace,
    ) -> web.StreamResponse:
        stream_mode = bool(body.get("stream", False))
        guard = self.metrics.guard(model, endpoint, "stream" if stream_mode else "unary")
        # The caller made the ONE sampling decision for this request; a
        # second EdgeRequestTrace here would mint a new trace id and
        # double-count the sampler metrics.
        ert.model, ert.endpoint = model, endpoint
        # Every guard.finish path (success, error, client drop) also closes
        # the edge trace — one wrapper instead of N call sites.
        guard = _TracedGuard(guard, ert)
        # Request-id correlation (reference: context id propagated in
        # headers): a caller-supplied x-request-id becomes the PREFIX of the
        # engine context id (logs, recorder streams, KV events), uniquified
        # with a server suffix — request ids key the engine's response
        # queues, so a client-chosen id must never collide with a
        # concurrent request's (that would cross-deliver tokens).  The full
        # unique id is echoed on every response, success or error.
        rid = request.headers.get("x-request-id")
        if rid:
            import uuid as _uuid

            ctx = Context.with_id(body, f"{rid}-{_uuid.uuid4().hex[:8]}")
        else:
            ctx = Context(body)
        # Per-request deadline: caller's x-deadline-s header (or body
        # "deadline_s") wins, else the service default; None = unbounded.
        deadline_s = _requested_deadline(request, body, self.default_deadline_s)
        if deadline_s is not None:
            ctx.ctx.deadline = Deadline.after(deadline_s)
        if ert.tc is not None:
            # Downstream propagation: the preprocessor stamps this onto
            # ``annotations.trace``; the service transport ships it in the
            # request header — one trace from edge to decode chunk.
            ctx.ctx.trace = ert.tc
        try:
            stream = await engine.generate(ctx)
        except ModelNotFoundError as e:
            # Engine-level rejection (llm/tenancy): the edge routed by name,
            # but the engine serves a model/adapter allowlist — an unknown
            # name 404s instead of silently running the base model.
            guard.finish(Status.REJECTED)
            return _model_not_found(e.model, rid=ctx.id)
        except AdapterCapacityError as e:
            # Transient: every resident LoRA slot is pinned by running
            # sequences — back off and retry, don't treat as server sickness.
            guard.finish(Status.REJECTED)
            return _error_response(503, str(e), rid=ctx.id, retry_after_s=1.0)
        except RemoteEngineError as e:
            if e.kind == ModelNotFoundError.error_kind:
                guard.finish(Status.REJECTED)
                return _model_not_found(model, rid=ctx.id)
            if e.kind == AdapterCapacityError.error_kind:
                guard.finish(Status.REJECTED)
                return _error_response(
                    503, str(e), rid=ctx.id, retry_after_s=1.0
                )
            guard.finish(Status.ERROR)
            logger.exception("engine rejected request")
            return _error_response(500, str(e), rid=ctx.id)
        except ValueError as e:
            # Request-shape errors (bad sampling params, oversize prompt)
            # are the client's fault: 400, not 500.  Logged with traceback:
            # an internal ValueError misclassified here must still be
            # visible server-side.
            guard.finish(Status.REJECTED)
            logger.warning("request rejected: %s", e, exc_info=True)
            return _error_response(400, str(e), rid=ctx.id)
        except (DeadlineExceededError, asyncio.TimeoutError) as e:
            guard.finish(Status.ERROR)
            logger.warning("request %s deadline exceeded at dispatch", ctx.id)
            return _error_response(504, str(e) or "deadline exceeded", rid=ctx.id)
        except NoInstancesError as e:
            # No live worker right now — transient capacity problem, not an
            # internal fault: 503 so clients retry, never 500.
            guard.finish(Status.REJECTED)
            logger.warning("no instances for %s: %s", model, e)
            return _error_response(503, str(e), rid=ctx.id, retry_after_s=1.0)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — edge boundary
            guard.finish(Status.ERROR)
            logger.exception("engine rejected request")
            return _error_response(500, str(e), rid=ctx.id)

        if stream_mode:
            return await self._stream_response(request, stream, ctx, guard)
        return await self._unary_response(stream, ctx, guard)

    async def _unary_response(self, stream, ctx: Context, guard) -> web.Response:
        # The edge is the enforcement point of last resort for deadlines:
        # engines behind a routed Client already honour them, but a local
        # pipeline streams unbounded — bound every chunk wait here.
        deadline = getattr(ctx.ctx, "deadline", None)
        chunks = []
        try:
            it = stream.__aiter__()
            while True:
                try:
                    if deadline is not None:
                        chunk = await deadline.bound(it.__anext__(), "response")
                    else:
                        chunk = await it.__anext__()
                except StopAsyncIteration:
                    break
                if "__annotations__" in chunk:
                    continue
                if chunk.get("choices") or chunk.get("usage"):
                    guard.on_token(0)
                chunks.append(chunk)
            full = aggregate_chunks(chunks)
        except asyncio.CancelledError:
            ctx.stop_generating()
            guard.finish(Status.CLIENT_DROP)
            raise
        except DeadlineExceededError as e:
            # Abandoning the request must also stop upstream generation —
            # otherwise the engine keeps burning batch slots on a response
            # nobody will read, exactly when the server is already slow.
            ctx.stop_generating()
            guard.finish(Status.ERROR)
            logger.warning("request %s deadline exceeded mid-generation", ctx.id)
            return _error_response(504, str(e) or "deadline exceeded", rid=ctx.id)
        except NoInstancesError as e:
            guard.finish(Status.REJECTED)
            return _error_response(503, str(e), rid=ctx.id, retry_after_s=1.0)
        except Exception as e:  # noqa: BLE001
            guard.finish(Status.ERROR)
            logger.exception("stream failed")
            return _error_response(500, str(e), rid=ctx.id)
        guard.finish(Status.SUCCESS)
        headers = {"x-request-id": ctx.id}
        trace = getattr(ctx.ctx, "trace", None)
        if trace is not None:
            headers["x-trace-id"] = trace.trace_id
        return web.json_response(full, headers=headers)

    async def _stream_response(
        self, request: web.Request, stream, ctx: Context, guard
    ) -> web.StreamResponse:
        headers = {
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            "Connection": "keep-alive",
            "x-request-id": ctx.id,
        }
        trace = getattr(ctx.ctx, "trace", None)
        if trace is not None:
            # The trace id is the lookup key for /traces/{id}; loadgen's
            # --trace-report reads it off this header.  Omitted when
            # untraced — the response byte stream itself never changes.
            headers["x-trace-id"] = trace.trace_id
        resp = web.StreamResponse(status=200, headers=headers)
        await resp.prepare(request)
        deadline = getattr(ctx.ctx, "deadline", None)
        status = Status.SUCCESS
        try:
            it = stream.__aiter__()
            while True:
                try:
                    if deadline is not None:
                        chunk = await deadline.bound(it.__anext__(), "stream")
                    else:
                        chunk = await it.__anext__()
                except StopAsyncIteration:
                    break
                if "__annotations__" in chunk:
                    await resp.write(
                        b"event: annotation\n" + sse_encode(chunk["__annotations__"])
                    )
                    continue
                guard.on_token()
                await resp.write(sse_encode(chunk))
            await resp.write(SSE_DONE)
        except (ConnectionResetError, asyncio.CancelledError):  # dynalint: disable=DYN003
            # Client went away: aiohttp cancels this handler on disconnect.
            # Deliberately absorb it — upstream generation must be stopped
            # and the CLIENT_DROP metric recorded before the handler exits.
            ctx.stop_generating()
            status = Status.CLIENT_DROP
        except DeadlineExceededError:
            # headers are already on the wire (200); all we can do is stop
            # generation and end the SSE stream with a typed error event
            ctx.stop_generating()
            status = Status.ERROR
            try:
                await resp.write(
                    b"event: error\n"
                    + sse_encode({"error": "deadline exceeded", "code": 504})
                )
            except (ConnectionResetError, RuntimeError):
                pass
        except Exception:  # noqa: BLE001
            status = Status.ERROR
            logger.exception("stream failed")
            try:
                await resp.write(
                    b"event: error\n" + sse_encode({"error": "stream failed"})
                )
            except (ConnectionResetError, RuntimeError):
                pass
        finally:
            guard.finish(status)
            await stream.aclose()
        try:
            await resp.write_eof()
        except (ConnectionResetError, RuntimeError):
            pass
        return resp


def _requested_deadline(
    request: web.Request, body: Dict[str, Any], default_s: Optional[float]
) -> Optional[float]:
    raw = request.headers.get("x-deadline-s") or body.get("deadline_s")
    if raw is not None:
        try:
            value = float(raw)
            if value > 0:
                return value
        except (TypeError, ValueError):
            pass
    return default_s


_ERROR_TYPES = {
    429: "overloaded_error",
    503: "overloaded_error",
    504: "timeout_error",
}


def _error_response(
    status: int,
    message: str,
    rid: Optional[str] = None,
    retry_after_s: Optional[float] = None,
    code: Optional[Any] = None,
    param: Optional[str] = None,
) -> web.Response:
    headers = {}
    if rid:
        headers["x-request-id"] = rid
    if retry_after_s is not None:
        headers["Retry-After"] = str(max(1, int(retry_after_s)))
    error: Dict[str, Any] = {
        "message": message,
        "type": _ERROR_TYPES.get(status, "invalid_request_error"),
        # OpenAI uses string codes ("model_not_found"); the numeric status
        # stays the default for errors without one (established behaviour).
        "code": status if code is None else code,
    }
    if param is not None:
        error["param"] = param
    return web.json_response(
        {"error": error},
        status=status,
        headers=headers or None,
    )


def _model_not_found(model: str, rid: Optional[str] = None) -> web.Response:
    """The OpenAI ``model_not_found`` 404 body (llm/tenancy satellite: a
    request naming an unregistered model/adapter must fail loudly, never
    silently fall through to the base model)."""
    return _error_response(
        404,
        f"The model {model!r} does not exist or is not served here",
        rid=rid,
        code="model_not_found",
        param="model",
    )
