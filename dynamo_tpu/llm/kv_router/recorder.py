"""KV event record/replay (JSONL) — offline router analysis + tests.

Reference semantics: lib/llm/src/recorder.rs + kv_router/recorder.rs and the
Python ``KvRecorder.replay_events`` binding (_core.pyi:432-499): capture the
timestamped per-worker event stream to JSONL; replay it later into an
indexer (optionally honouring original timing) to reproduce routing
decisions without a live fleet.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Optional, TextIO, Union

from .indexer import KvIndexer, KvIndexerSharded, WorkerId
from .protocols import KvCacheEvent


class KvRecorder:
    """Append-only JSONL event log: {"ts", "worker_id", "event"}."""

    def __init__(self, path: str):
        self.path = path
        self._fh: Optional[TextIO] = open(path, "a", encoding="utf-8")
        self.count = 0

    def record(self, worker_id: WorkerId, event: KvCacheEvent) -> None:
        assert self._fh is not None, "recorder closed"
        self._fh.write(
            json.dumps(
                {"ts": time.time(), "worker_id": worker_id, "event": event.to_dict()}
            )
            + "\n"
        )
        self.count += 1

    def callback_for(self, worker_id: WorkerId):
        """Engine-compatible event_callback bound to one worker id."""

        def cb(event: KvCacheEvent) -> None:
            self.record(worker_id, event)

        return cb

    def flush(self) -> None:
        if self._fh is not None:
            self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


async def replay_events(
    path: str,
    indexer: Union[KvIndexer, KvIndexerSharded],
    timed: bool = False,
    max_count: Optional[int] = None,
) -> int:
    """Feed a recorded JSONL stream into an indexer; returns events applied.

    ``timed=True`` sleeps to reproduce original inter-event gaps (useful for
    soak-style router tests); default replays as fast as possible.
    """
    applied = 0
    prev_ts: Optional[float] = None
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if timed and prev_ts is not None:
                gap = rec["ts"] - prev_ts
                if gap > 0:
                    await asyncio.sleep(min(gap, 1.0))
            prev_ts = rec["ts"]
            indexer.apply_event(rec["worker_id"], KvCacheEvent.from_dict(rec["event"]))
            applied += 1
            if max_count is not None and applied >= max_count:
                break
    return applied
