"""KV-aware worker selection (the router's cost function).

Reference semantics: lib/llm/src/kv_router/scheduler.rs:236-340 —
``DefaultWorkerSelector``:

    score  = overlap_blocks * block_size / isl_tokens        (prefix hit ratio)
    logit  = 2*score − cache_usage − active_slots/total_slots
    winner = argmax(logit), random tie-break

plus a ``KVHitRateEvent`` published per decision so dashboards/metrics can
track fleet-wide prefix-hit quality.  ``WorkerSelector`` is pluggable
(components/router custom-selector example, src/main.rs:56-95).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Protocol

from .indexer import OverlapScores, WorkerId
from .protocols import ForwardPassMetrics

KV_HIT_RATE_SUBJECT = "kv-hit-rate"


@dataclass(frozen=True)
class KVHitRateEvent:
    worker_id: WorkerId
    isl_blocks: int
    overlap_blocks: int

    def to_dict(self) -> Dict:
        return {
            "worker_id": self.worker_id,
            "isl_blocks": self.isl_blocks,
            "overlap_blocks": self.overlap_blocks,
        }


@dataclass
class WorkerSnapshot:
    """A worker's latest ForwardPassMetrics plus identity."""

    worker_id: WorkerId
    metrics: ForwardPassMetrics = field(default_factory=ForwardPassMetrics)


@dataclass
class SchedulingRequest:
    isl_tokens: int
    overlap: OverlapScores
    workers: List[WorkerSnapshot]
    block_size: int


class WorkerSelector(Protocol):
    def select(self, request: SchedulingRequest) -> Optional[WorkerId]: ...


class DefaultWorkerSelector:
    """The reference cost function (scheduler.rs:236-340)."""

    def __init__(self, rng: Optional[random.Random] = None):
        # Seeded by default: tie-breaks must replay identically run-to-run
        # (router decisions feed the sim/replay planes); callers that want
        # spread pass their own generator.
        self._rng = rng or random.Random(0)

    def select(self, request: SchedulingRequest) -> Optional[WorkerId]:
        if not request.workers:
            return None
        best_logit: Optional[float] = None
        best: List[WorkerId] = []
        for snap in request.workers:
            m = snap.metrics
            # Tier-discounted overlap (indexer.OverlapScores): a block
            # restorable only from host/disk contributes less than a live
            # HBM block, so a deep-but-cold prefix loses to a
            # shallow-but-hot one DETERMINISTICALLY (distinct tier weights
            # break what used to be an exact tie).  Raw block counts are
            # still what KVHitRateEvents report.
            eff_blocks = request.overlap.discounted_for(snap.worker_id)
            score = (
                eff_blocks * request.block_size / request.isl_tokens
                if request.isl_tokens
                else 0.0
            )
            slots = (
                m.request_active_slots / m.request_total_slots
                if m.request_total_slots
                else 0.0
            )
            logit = 2.0 * score - m.gpu_cache_usage_perc - slots
            if best_logit is None or logit > best_logit + 1e-12:
                best_logit, best = logit, [snap.worker_id]
            elif abs(logit - best_logit) <= 1e-12:
                best.append(snap.worker_id)
        return self._rng.choice(best)


class KvScheduler:
    """Applies a selector and reports hit-rate events via a callback."""

    def __init__(
        self,
        block_size: int,
        selector: Optional[WorkerSelector] = None,
        hit_rate_callback: Optional[Callable[[KVHitRateEvent], None]] = None,
    ):
        self.block_size = block_size
        self.selector = selector or DefaultWorkerSelector()
        self._hit_rate_callback = hit_rate_callback

    def schedule(
        self,
        isl_tokens: int,
        overlap: OverlapScores,
        workers: List[WorkerSnapshot],
    ) -> Optional[WorkerId]:
        request = SchedulingRequest(
            isl_tokens=isl_tokens,
            overlap=overlap,
            workers=workers,
            block_size=self.block_size,
        )
        winner = self.selector.select(request)
        if winner is not None and self._hit_rate_callback is not None:
            self._hit_rate_callback(
                KVHitRateEvent(
                    worker_id=winner,
                    isl_blocks=isl_tokens // self.block_size,
                    overlap_blocks=overlap.scores.get(winner, 0),
                )
            )
        return winner
