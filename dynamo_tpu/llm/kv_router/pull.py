"""Cross-worker prefix pull + planner-led prefetch (docs/kv_tiering.md).

Fleet-wide prefix reuse, layer by layer:

- the tier-aware index (indexer.py) knows which worker holds which prefix
  and in which tier;
- the push router (router.py) stamps ``annotations.kv_pull =
  {worker_id, blocks}`` when a PEER holds a strictly deeper raw prefix
  than the chosen worker;
- at admission the engine hands that hint to its ``PrefixPuller`` (below),
  which — only if the peer's depth strictly beats every LOCAL tier —
  fetches the sealed delta blocks over the existing
  ``export_prompt_blocks``/``inject_blocks`` plane, capped by the
  configured byte + latency budgets.  ANY failure (peer gone, timeout,
  payload rejected by inject validation) degrades to local prefill — the
  disagg degraded-mode shape: the request is never lost, only the
  optimization.

Exactness: a pulled block carries the same stored representation
``inject_blocks`` validates (block_size/dtype/kv_scale), and seals under
the same chained hash the donor sealed it under — so a pulled-prefix
stream is byte-identical to a recomputed one (tests/test_kv_tiering.py
gates this).

The prefetch half rides the same plane in the other direction: the router
core tracks the hottest routed chains (router.HotChainTracker) and a
``KvPrefetchPublisher`` pushes them on the ``kv_prefetch`` subject; each
worker's ``KvPrefetchConsumer`` promotes those chains disk→host ahead of
the next arrival (engine.prefetch_hashes) — restore cost paid before the
request exists, not inside its TTFT.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, AsyncIterator, Callable, Dict, List, Optional

from ...runtime.engine import Context

logger = logging.getLogger(__name__)

# Peer-serving endpoint name (registered next to kv_import by the CLI's
# decode role): {token_ids, start_block, max_blocks, salt} → one
# {"payload": export_prompt_blocks(...)} item.
KV_EXPORT_ENDPOINT = "kv_export"
KV_PREFETCH_TOPIC = "kv_prefetch"


def make_kv_export_handler(engine):
    """Build the service handler a worker registers at ``kv_export`` so
    peers can pull its sealed prefix blocks."""
    from ...runtime.tracing import parse_trace, span as trace_span

    async def kv_export_handler(request: Context) -> AsyncIterator[Dict]:
        d = request.data
        tokens = list(d["token_ids"])
        salt = d.get("salt")
        # Donor-side span: the export request ships the puller's trace
        # (``d["trace"]``, omit-when-absent — or the service-transport
        # header via request.ctx), so the donor's restore+gather cost shows
        # up inside the pulling request's timeline.
        tc = parse_trace(d.get("trace")) or getattr(request.ctx, "trace", None)
        with trace_span(tc, "kv.export", "kv_donor") as espan:
            # export_prompt_blocks reads HBM only, but the router hints raw
            # tier-tagged depth — a donor whose blocks were DEMOTED must
            # restore them first or the pull's primary scenario (tiered
            # donors) silently exports nothing.
            if getattr(engine, "host_kv", None) is not None:
                await engine.restore_prefix(tokens, salt)
            payload = await engine.export_prompt_blocks(
                tokens,
                start_block=int(d.get("start_block", 0)),
                max_blocks=int(d.get("max_blocks", 0)),
                salt=salt,
            )
            espan.set(
                blocks=int(payload["n_blocks"]) if payload else 0
            )
        yield {"payload": payload}

    return kv_export_handler


class PrefixPuller:
    """Admission-time cross-worker prefix pull for one engine.

    ``exporter(worker_id, data) -> payload|None`` is the peer transport —
    the CLI wires a direct-routed client on the fleet's ``kv_export``
    endpoint; tests wire peer engines directly.  Budgets come from the
    engine config (kv_pull_max_bytes / kv_pull_timeout_s)."""

    def __init__(
        self,
        engine,
        exporter: Callable[[int, Dict[str, Any]], Any],
        max_bytes: Optional[int] = None,
        timeout_s: Optional[float] = None,
    ):
        self.engine = engine
        self.exporter = exporter
        self.max_bytes = (
            engine.cfg.kv_pull_max_bytes if max_bytes is None else max_bytes
        )
        self.timeout_s = (
            engine.cfg.kv_pull_timeout_s if timeout_s is None else timeout_s
        )

    async def pull(
        self,
        token_ids: List[int],
        salt: Optional[str],
        hint: Dict[str, Any],
        trace=None,
    ) -> int:
        """Pull the delta blocks the hinted peer holds beyond every local
        tier.  Returns tokens covered; 0 on any failure or when the local
        tiers already match the peer's depth (nothing worth moving)."""
        from ..metrics import kv_tier_metrics

        try:
            peer = int(hint["worker_id"])
            peer_blocks = int(hint.get("blocks", 0))
        except (KeyError, TypeError, ValueError):
            return 0
        # Hash the chain ONCE: the local-depth walk and the integrity
        # negative-cache check below both consume it, and chained hashing
        # is O(prompt length) on every admission with a peer hint.
        from ...tokens import hash_token_blocks

        chain = hash_token_blocks(token_ids, self.engine.cfg.block_size, salt)
        local = self.engine.local_prefix_blocks(token_ids, salt, blocks=chain)
        if peer_blocks <= local:
            return 0  # local tiers already reach (or beat) the peer
        block_bytes = max(1, self.engine.block_nbytes())
        budget_blocks = max(0, int(self.max_bytes) // block_bytes)
        want = min(peer_blocks - local, budget_blocks)
        # Integrity negative cache: a recently checksum-failed hash in the
        # wanted delta means a pull would re-ship and re-fail the same
        # poison (the donor still HOLDS its corrupt copy — we can only
        # drop ours); recompute locally until the TTL expires.
        delta = chain[local : local + max(want, 0)]
        if self.engine.integrity.any_banned(
            [tb.sequence_hash for tb in delta]
        ) is not None:
            from ..metrics import kv_integrity_metrics

            kv_integrity_metrics.negative_cache_hits_total += 1
            return 0
        # Count the attempt BEFORE any bail-out so failed can never
        # exceed started (dashboards derive success rate from the pair).
        kv_tier_metrics.pulls_started_total += 1
        if want <= 0:
            kv_tier_metrics.pulls_failed_total += 1
            return 0  # byte budget cannot cover even one block
        t0 = time.perf_counter()
        data = {
            "token_ids": list(token_ids),
            "start_block": local,
            "max_blocks": want,
        }
        if salt:
            data["salt"] = salt
        if trace is not None and trace.sampled:
            # Omit-when-absent wire propagation (runtime/tracing.py): the
            # donor's kv_export handler records its span under this trace.
            data["trace"] = trace.to_dict()
        try:
            payload = await asyncio.wait_for(
                self.exporter(peer, data), self.timeout_s
            )
            if not payload:
                kv_tier_metrics.pulls_failed_total += 1
                return 0
            # donor=peer: a checksum-failed payload is attributed to its
            # sender in the health ledger (runtime/health.py) — repeated
            # poison from one donor feeds the watchdog's quarantine path.
            covered = await self.engine.inject_blocks(
                token_ids, payload, salt, donor=peer
            )
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — degraded mode: prefill locally
            logger.warning(
                "cross-worker prefix pull from %s failed; prefilling locally",
                hint.get("worker_id"),
                exc_info=True,
            )
            kv_tier_metrics.pulls_failed_total += 1
            return 0
        if covered <= 0:
            # inject validated and refused (layout/scale/capacity): the
            # blocks never landed — local prefill covers them.
            kv_tier_metrics.pulls_failed_total += 1
            return 0
        kv_tier_metrics.pulls_completed_total += 1
        kv_tier_metrics.pulled_blocks_total += covered // max(
            1, self.engine.cfg.block_size
        )
        kv_tier_metrics.pulled_bytes_total += (
            covered // max(1, self.engine.cfg.block_size)
        ) * block_bytes
        kv_tier_metrics.pull_latency_ms.observe(
            (time.perf_counter() - t0) * 1e3
        )
        return covered


def make_client_exporter(client):
    """Exporter over the service plane: direct-route the fleet's
    ``kv_export`` endpoint client at the donor worker."""

    async def exporter(worker_id: int, data: Dict[str, Any]):
        stream = await client.generate(Context(data), worker_id=worker_id)
        async for item in stream:
            return (item or {}).get("payload")
        return None

    return exporter


def make_bulk_export_source(engine):
    """Donor-side bulk *source* for ``KV_EXPORT_ENDPOINT``: the same
    restore+export the service handler runs, codec-encoded to one blob for
    the peer-to-peer plane (a ``None`` export encodes/decodes to ``None``)."""
    from ...runtime.tracing import parse_trace, span as trace_span
    from ...runtime.transports import codec

    async def source(meta: Dict[str, Any]) -> bytes:
        tokens = list(meta["token_ids"])
        salt = meta.get("salt")
        tc = parse_trace(meta.get("trace"))
        with trace_span(tc, "kv.export", "kv_donor") as espan:
            if getattr(engine, "host_kv", None) is not None:
                await engine.restore_prefix(tokens, salt)
            payload = await engine.export_prompt_blocks(
                tokens,
                start_block=int(meta.get("start_block", 0)),
                max_blocks=int(meta.get("max_blocks", 0)),
                salt=salt,
            )
            espan.set(blocks=int(payload["n_blocks"]) if payload else 0)
        return codec.encode(payload)

    return source


def make_bulk_exporter(rendezvous, fallback, max_bytes: int = 0):
    """Exporter over the bulk plane (``DYN_BULK_PLANE``): hub rendezvous
    mints the one-shot ticket, the payload itself moves worker↔worker over
    ``transports/bulk.py``.  ANY miss — peer runs no bulk server, ticket
    refused, transfer dead after resumes — counts one
    ``dynamo_tpu_bulk_fallbacks_total`` and delegates to ``fallback`` (the
    hub-path exporter, the byte-identity A/B oracle); the puller's own
    degraded mode (local prefill) stays the final rung."""
    from ...runtime.transports import codec
    from ...runtime.transports.bulk import bulk_fetch
    from ..metrics import bulk_metrics

    async def exporter(worker_id: int, data: Dict[str, Any]):
        salt = data.get("salt")
        try:
            # Budget: the pull byte budget plus framing/metadata slack.
            prep = await rendezvous.prepare(
                worker_id,
                salt=salt,
                budget=(int(max_bytes) * 2 + (1 << 20)) if max_bytes else 0,
            )
            if prep is None:
                raise RuntimeError("bulk rendezvous unavailable")
            address, ticket = prep
            blob = await bulk_fetch(
                address, KV_EXPORT_ENDPOINT, ticket, meta=data, salt=salt
            )
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — fallback ladder: hub path next
            logger.warning(
                "bulk prefix pull from %s failed; falling back to the hub "
                "path",
                worker_id,
                exc_info=True,
            )
            bulk_metrics.fallbacks_total += 1
            return await fallback(worker_id, data)
        return codec.decode(blob) if blob else None

    return exporter


class KvPrefetchPublisher:
    """Router-side: periodically publish the hottest routed prefix chains
    so workers can warm them disk→host ahead of arrivals (planner-led
    prefetch — the same push plane the planner's signal feeds ride)."""

    def __init__(self, core, interval: float = 2.0, top_n: int = 8):
        self.core = core
        self.interval = interval
        self.top_n = top_n
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> "KvPrefetchPublisher":
        self._task = asyncio.get_running_loop().create_task(self._run())
        return self

    async def publish_once(
        self, top_n: Optional[int] = None, persist: bool = False
    ) -> None:
        """One push; the autopilot's warming directive calls this with
        ``persist=True`` so workers ALSO pin the chains into the durable
        object-store tier (engine.persist_hashes) — the next
        scale-from-zero worker restores them instead of recomputing."""
        chains = self.core.hot_chains.top(self.top_n if top_n is None else top_n)
        if chains:
            msg: dict = {"chains": chains}
            if persist:
                msg["persist"] = True
            await self.core.component.publish(KV_PREFETCH_TOPIC, msg)

    async def _run(self) -> None:
        while True:
            try:
                await self.publish_once()
            except asyncio.CancelledError:
                return
            except Exception:  # noqa: BLE001 — prefetch is best-effort
                logger.warning("kv prefetch publish failed", exc_info=True)
            try:
                await asyncio.sleep(self.interval)
            except asyncio.CancelledError:
                return

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None


class KvPrefetchConsumer:
    """Worker-side: subscribe ``kv_prefetch`` and promote the published
    chains disk→host (engine.prefetch_hashes).  Promotion is budgeted and
    skips anything already resident in a faster tier."""

    def __init__(self, component, engine):
        self.component = component
        self.engine = engine
        self._task: Optional[asyncio.Task] = None
        self._sub = None

    async def start(self) -> "KvPrefetchConsumer":
        self._sub = await self.component.subscribe(KV_PREFETCH_TOPIC)
        self._task = asyncio.get_running_loop().create_task(self._run())
        return self

    async def _run(self) -> None:
        from .publisher import unpack_message

        try:
            async for msg in self._sub:
                payload = unpack_message(msg)
                chains = (
                    payload.get("chains") if isinstance(payload, dict) else None
                )
                if not chains:
                    continue
                persist = bool(payload.get("persist"))
                for chain in chains:
                    hashes = [int(h) for h in chain]
                    try:
                        await self.engine.prefetch_hashes(hashes)
                        if persist and hasattr(self.engine, "persist_hashes"):
                            await self.engine.persist_hashes(hashes)
                    except asyncio.CancelledError:
                        raise
                    except Exception:  # noqa: BLE001 — best-effort warmup
                        logger.warning("kv prefetch failed", exc_info=True)
        except asyncio.CancelledError:
            pass

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._sub is not None and hasattr(self._sub, "aclose"):
            await self._sub.aclose()
