"""KV-cache-aware routing stack (reference: lib/llm/src/kv_router/**).

Protocol types (events, metrics) are shared with the engine, which emits
them; the indexer/scheduler consume them to pick workers by prefix overlap.
"""

from .protocols import (  # noqa: F401
    ForwardPassMetrics,
    KvCacheEvent,
    KvCacheRemoveData,
    KvCacheStoreData,
    KvCacheStoredBlockData,
)
