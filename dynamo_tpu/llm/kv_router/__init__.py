"""KV-cache-aware routing stack (reference: lib/llm/src/kv_router/**).

Protocol types (events, metrics) are shared with the engine, which emits
them; the indexer/scheduler consume them to pick workers by prefix overlap.
"""

from .indexer import KvIndexer, KvIndexerSharded, OverlapScores  # noqa: F401
from .protocols import (  # noqa: F401
    ForwardPassMetrics,
    KvCacheEvent,
    KvCacheRemoveData,
    KvCacheStoreData,
    KvCacheStoredBlockData,
)
from .publisher import (  # noqa: F401
    KvEventPublisher,
    KvMetricsAggregator,
    KvMetricsPublisher,
)
from .recorder import KvRecorder, replay_events  # noqa: F401
from .router import KvPushRouter, KvRouter, KvRouterCore, make_kv_router  # noqa: F401
from .scheduler import (  # noqa: F401
    DefaultWorkerSelector,
    KvScheduler,
    WorkerSnapshot,
)
