"""KV-cache-aware routing stack (reference: lib/llm/src/kv_router/**).

Protocol types (events, metrics) are shared with the engine, which emits
them; the indexer/scheduler consume them to pick workers by prefix overlap.
"""

from .indexer import (  # noqa: F401
    DEFAULT_TIER_WEIGHTS,
    KvIndexer,
    KvIndexerSharded,
    OverlapScores,
)
from .protocols import (  # noqa: F401
    ForwardPassMetrics,
    KvCacheEvent,
    KvCacheRemoveData,
    KvCacheStoreData,
    KvCacheStoredBlockData,
    KvCacheTierData,
)
from .publisher import (  # noqa: F401
    KvEventPublisher,
    KvMetricsAggregator,
    KvMetricsPublisher,
)
from .pull import (  # noqa: F401
    KV_EXPORT_ENDPOINT,
    KV_PREFETCH_TOPIC,
    KvPrefetchConsumer,
    KvPrefetchPublisher,
    PrefixPuller,
    make_client_exporter,
    make_kv_export_handler,
)
from .recorder import KvRecorder, replay_events  # noqa: F401
from .router import (  # noqa: F401
    HotChainTracker,
    KvPushRouter,
    KvRouter,
    KvRouterCore,
    PlannerDirectiveWatcher,
    make_kv_router,
)
from .scheduler import (  # noqa: F401
    DefaultWorkerSelector,
    KvScheduler,
    WorkerSnapshot,
)
