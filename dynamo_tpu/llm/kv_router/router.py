"""KvRouter: KV-cache-aware request routing as an AsyncEngine.

Reference semantics: lib/llm/src/kv_router.rs:52-169 — the router subscribes
the worker fleet's ``kv_events``, keeps the global prefix index, and answers
"which worker should run these tokens" by combining prefix overlap with live
worker load (ForwardPassMetrics).  Two faces:

- ``KvRouter``: the standalone service engine (components/router) —
  RouterRequest {"token_ids"} → RouterResponse {"worker_id",
  "overlap_blocks"}.
- ``KvPushRouter``: drop-in pipeline sink that routes a PreprocessedRequest
  to the chosen worker via ``client.direct`` (what the reference's processor
  does in examples/llm/components/kv_router.py + processor.py).

Worker liveness: instance set comes from the endpoint client's hub watch;
workers that disappear are pruned from the index (indexer.remove_worker —
the reference does this on etcd lease loss, kv_router/indexer.rs:380).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, AsyncIterator, Dict, Optional, Tuple

from ...runtime.client import Client
from ...runtime.engine import AsyncEngine, Context, ResponseStream
from ...tokens import fast_sequence_hashes
from .indexer import KvIndexer, KvIndexerSharded, OverlapScores, WorkerId
from .publisher import KV_EVENTS_TOPIC, KvMetricsAggregator, unpack_message
from .scheduler import KvScheduler, KVHitRateEvent, KV_HIT_RATE_SUBJECT, WorkerSelector

logger = logging.getLogger(__name__)


class HotChainTracker:
    """Decayed hit counts over routed prefix NODES — the prefetch plane's
    'hottest chains' source (docs/kv_tiering.md).

    Weight accumulates PER PREFIX NODE (each leading block hash), not per
    full chain: two multi-turn requests over one shared system prompt end
    in different deepest hashes, but their common leading nodes each get
    credited twice — so shared-prefix heat aggregates exactly where reuse
    happens, and per-request tail blocks stay at weight 1 and decay away.
    ``top()`` returns the hottest nodes' chains (deepest first on equal
    weight, strict prefixes of an already-selected chain deduplicated),
    which the KvPrefetchPublisher pushes to workers so they can warm those
    prefixes disk→host AHEAD of the next arrival."""

    def __init__(self, max_chains: int = 256, max_depth: int = 32):
        self.max_chains = max_chains
        self.max_depth = max_depth
        # prefix-node hash → [weight, [leading hashes up to this node]]
        self._chains: Dict[int, list] = {}

    def record(self, seq_hashes) -> None:
        hashes = list(seq_hashes[: self.max_depth])
        decayed = False
        for d, h in enumerate(hashes):
            row = self._chains.get(h)
            if row is not None:
                row[0] += 1.0
                continue
            if len(self._chains) >= self.max_chains:
                # Decay AT MOST ONCE per recorded chain: a single deep
                # never-seen chain must not halve the table per node (32
                # halvings would erase the entire heat history).
                if decayed:
                    continue
                self._decay_and_prune()
                decayed = True
                if len(self._chains) >= self.max_chains:
                    continue  # full of hotter nodes: drop this one
            self._chains[h] = [1.0, hashes[: d + 1]]

    def _decay_and_prune(self) -> None:
        """Make room: drop cold one-hit entries first; only if the table
        is STILL full does every weight halve, pruning what falls under
        1.0 — so the halving pass always frees the warm-but-not-hot band
        and steady per-request tail churn cannot erase genuinely hot
        nodes, while yesterday's hot prompt still fades instead of
        squatting forever."""
        for k in [k for k, row in self._chains.items() if row[0] < 1.5]:
            del self._chains[k]
        if len(self._chains) >= self.max_chains:
            for row in self._chains.values():
                row[0] *= 0.5
            for k in [k for k, row in self._chains.items() if row[0] < 1.0]:
                del self._chains[k]

    def top(self, n: int = 8):
        """The ``n`` hottest distinct chains, hottest first.  On equal
        weight the DEEPER node wins (its chain subsumes the shallower
        ones, which are then deduplicated as strict prefixes); remaining
        ties break on the node hash — fully deterministic."""
        ranked = sorted(
            self._chains.items(),
            key=lambda kv: (-kv[1][0], -len(kv[1][1]), kv[0]),
        )
        out: list = []
        for _, row in ranked:
            chain = row[1]
            if any(sel[: len(chain)] == chain for sel in out):
                continue  # strict prefix of a hotter selected chain
            out.append(chain)
            if len(out) >= n:
                break
        return out


class KvRouterCore:
    """Index + metrics + selection (shared by both router faces)."""

    def __init__(
        self,
        component,
        client: Client,
        block_size: int,
        selector: Optional[WorkerSelector] = None,
        sharded: bool = False,
        publish_hit_rate: bool = True,
    ):
        self.component = component
        self.client = client
        self.block_size = block_size
        self.indexer = (
            KvIndexerSharded(block_size) if sharded else KvIndexer(block_size)
        )
        self.aggregator = KvMetricsAggregator(component)
        self.scheduler = KvScheduler(
            block_size,
            selector=selector,
            hit_rate_callback=self._on_hit_rate if publish_hit_rate else None,
        )
        self._event_task: Optional[asyncio.Task] = None
        self._event_sub = None
        self._known_workers: set = set()
        self._bg: set = set()
        # Prefetch plane input: decayed hit counts over routed prefix
        # chains (KvPrefetchPublisher reads top()).
        self.hot_chains = HotChainTracker()
        self._prefetch_pub = None

    async def start(self) -> "KvRouterCore":
        self._event_sub = await self.component.subscribe(KV_EVENTS_TOPIC)
        self._event_task = asyncio.get_running_loop().create_task(self._event_loop())
        await self.aggregator.start()
        # Prefetch plane (docs/kv_tiering.md): push the hottest routed
        # chains so workers with a disk tier warm them ahead of arrivals.
        from .pull import KvPrefetchPublisher

        self._prefetch_pub = await KvPrefetchPublisher(self).start()
        return self

    async def stop(self) -> None:
        if self._prefetch_pub is not None:
            await self._prefetch_pub.stop()
            self._prefetch_pub = None
        if self._event_task is not None:
            self._event_task.cancel()
            try:
                await self._event_task
            except asyncio.CancelledError:
                pass
            self._event_task = None
        if self._event_sub is not None and hasattr(self._event_sub, "aclose"):
            await self._event_sub.aclose()
        await self.aggregator.stop()

    async def _event_loop(self) -> None:
        from .protocols import KvCacheEvent

        try:
            async for msg in self._event_sub:
                payload = unpack_message(msg)
                try:
                    worker = payload["worker_id"]
                    event = KvCacheEvent.from_dict(payload["event"])
                except (KeyError, TypeError):
                    logger.warning("malformed kv_event payload: %r", payload)
                    continue
                self.indexer.apply_event(worker, event)
        except asyncio.CancelledError:
            pass

    def _on_hit_rate(self, event: KVHitRateEvent) -> None:
        loop = asyncio.get_event_loop()
        task = loop.create_task(
            self.component.publish(KV_HIT_RATE_SUBJECT, event.to_dict())
        )
        self._bg.add(task)
        task.add_done_callback(self._bg.discard)

    def _prune_dead_workers(self, live: set) -> None:
        for gone in self._known_workers - live:
            logger.info("pruning dead worker %s from kv index", gone)
            self.indexer.remove_worker(gone)
            self.aggregator.remove_worker(gone)
        self._known_workers = live

    def select(
        self, token_ids, salt: Optional[str] = None
    ) -> Tuple[Optional[WorkerId], int]:
        """(best worker, overlap_blocks); None if no instances.  ``salt``
        is the tenant KV salt (llm/tenancy) — overlap hashing must match
        the engine's salted sealing or scores diverge from cache state."""
        winner, overlap = self.select_with_scores(token_ids, salt)
        return winner, overlap.scores.get(winner, 0) if winner is not None else 0

    def select_with_scores(
        self, token_ids, salt: Optional[str] = None
    ) -> Tuple[Optional[WorkerId], OverlapScores]:
        """``select`` plus the full per-worker overlap — what the push
        router needs to stamp cross-worker pull hints (a peer with a
        strictly deeper RAW prefix than the winner's)."""
        live = set(self.client.instance_ids)
        if live != self._known_workers:
            self._prune_dead_workers(live)
        if not live:
            return None, OverlapScores()
        hashes = fast_sequence_hashes(token_ids, self.block_size, salt)
        self.hot_chains.record(hashes)
        overlap = self.indexer.find_matches_for_hashes(hashes)
        # Dead workers may linger in the index until their Removed/watch
        # events land; never hint (or route) toward one.
        overlap = OverlapScores(
            {w: n for w, n in overlap.scores.items() if w in live},
            {w: d for w, d in overlap.discounted.items() if w in live},
        )
        workers = self.aggregator.endpoints(sorted(live))
        winner = self.scheduler.schedule(len(token_ids), overlap, workers)
        return winner, overlap

    # ------------------------------------------------- autopilot directives

    async def warm_hot_chains(
        self, top_n: Optional[int] = None, persist: bool = False
    ) -> None:
        """Enact a ``kv_prefetch`` directive: push the hottest routed
        chains NOW (out of band of the publisher's own cadence), with
        ``persist=True`` pinning them into the durable object-store tier."""
        if self._prefetch_pub is not None:
            await self._prefetch_pub.publish_once(top_n=top_n, persist=persist)

    def apply_tier_weights(self, weights: Dict[str, float]) -> None:
        """Enact a ``set_tier_weights`` directive: replace the cold-start
        restore-cost table with the autopilot's measured weights."""
        self.indexer.set_tier_weights(weights)


class KvRouter(AsyncEngine):
    """Standalone routing service (reference: components/router)."""

    def __init__(self, core: KvRouterCore):
        self.core = core

    async def generate(self, request: Context) -> ResponseStream:
        token_ids = request.data["token_ids"]
        worker_id, overlap = self.core.select(
            token_ids, request.data.get("kv_salt")
        )

        async def gen() -> AsyncIterator[Dict[str, Any]]:
            yield {"worker_id": worker_id, "overlap_blocks": overlap}

        return ResponseStream(gen(), request.ctx)


class KvPushRouter(AsyncEngine):
    """Pipeline sink: route PreprocessedRequest to the overlap-best worker.

    Falls back to round-robin when no worker has been selected (e.g. no KV
    events yet) — the client handles that internally via ``generate``.
    """

    def __init__(self, core: KvRouterCore):
        self.core = core

    async def generate(self, request: Context) -> ResponseStream:
        token_ids = request.data.get("token_ids") or []
        # Tenant requests (llm/tenancy) carry their KV salt in annotations;
        # the engine seals their blocks under the same salt, so routing
        # overlap only means anything when hashed identically.
        annotations = request.data.get("annotations") or {}
        worker_id, overlap = self.core.select_with_scores(
            token_ids, annotations.get("kv_salt")
        )
        if worker_id is None:
            return await self.core.client.generate(request)
        # Cross-worker prefix pull hint (docs/kv_tiering.md): when a PEER
        # holds a strictly deeper RAW prefix than the winner (the winner
        # won on tier heat / load), tell the winner who to pull the sealed
        # delta blocks from instead of recomputing prefill.  The engine
        # still compares against its own tiers at admission — the hint is
        # advisory and bounded by the pull budgets.
        donor = overlap.deepest()
        if (
            donor is not None
            and donor != worker_id
            and overlap.scores.get(donor, 0) > overlap.scores.get(worker_id, 0)
        ):
            annotations = dict(annotations)
            annotations["kv_pull"] = {
                "worker_id": donor,
                "blocks": overlap.scores[donor],
            }
            request.data["annotations"] = annotations
        return await self.core.client.generate(request, worker_id=worker_id)


async def make_kv_router(
    endpoint,
    block_size: int,
    selector: Optional[WorkerSelector] = None,
    sharded: bool = False,
) -> KvRouterCore:
    """Build + start a router core watching ``endpoint``'s worker fleet."""
    from ...runtime.client import RouterMode

    client = await endpoint.client(router_mode=RouterMode.ROUND_ROBIN)
    core = KvRouterCore(
        endpoint.component, client, block_size, selector=selector, sharded=sharded
    )
    return await core.start()


class PlannerDirectiveWatcher:
    """Router-side consumer of the autopilot's directive slots
    (planner/actuate.py ``directive_key``): watches
    ``planner/directives/`` and enacts the router-enactable kinds —
    ``kv_prefetch`` (publish the hottest chains now, optionally pinning
    them into the durable object-store tier) and ``set_tier_weights``
    (live restore-cost retune).  ``migrate_out`` / ``tune_decode`` are
    supervisor/operator directives and pass through untouched.

    The watch replays standing slots on start, so a freshly (re)started
    router inherits the fleet's current measured tier weights instead of
    routing on the cold-start table until the next retune."""

    def __init__(self, hub, core: KvRouterCore):
        self.hub = hub
        self.core = core
        self.applied = 0
        self._task: Optional[asyncio.Task] = None
        self._watcher = None

    async def start(self) -> "PlannerDirectiveWatcher":
        from ...planner.actuate import DIRECTIVE_PREFIX

        self._watcher = await self.hub.watch_prefix(DIRECTIVE_PREFIX)
        self._task = asyncio.get_running_loop().create_task(self._run())
        await self._watcher.synced.wait()
        return self

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._watcher is not None:
            await self._watcher.aclose()
            self._watcher = None

    async def _run(self) -> None:
        try:
            async for event in self._watcher:
                if event.type != "put" or not isinstance(event.value, dict):
                    continue
                await self._apply(event.value)
        except asyncio.CancelledError:
            pass

    async def _apply(self, directive: Dict[str, Any]) -> None:
        kind = directive.get("kind")
        params = directive.get("params") or {}
        try:
            if kind == "kv_prefetch":
                top_n = params.get("top_n")
                await self.core.warm_hot_chains(
                    top_n=int(top_n) if top_n is not None else None,
                    persist=bool(params.get("persist")),
                )
            elif kind == "set_tier_weights":
                weights = params.get("weights")
                if not isinstance(weights, dict):
                    return
                self.core.apply_tier_weights(
                    {str(t): float(w) for t, w in weights.items()}
                )
            else:
                return
            self.applied += 1
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — a bad directive must not kill the watch
            logger.warning("planner directive %r failed", kind, exc_info=True)
