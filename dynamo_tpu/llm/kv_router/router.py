"""KvRouter: KV-cache-aware request routing as an AsyncEngine.

Reference semantics: lib/llm/src/kv_router.rs:52-169 — the router subscribes
the worker fleet's ``kv_events``, keeps the global prefix index, and answers
"which worker should run these tokens" by combining prefix overlap with live
worker load (ForwardPassMetrics).  Two faces:

- ``KvRouter``: the standalone service engine (components/router) —
  RouterRequest {"token_ids"} → RouterResponse {"worker_id",
  "overlap_blocks"}.
- ``KvPushRouter``: drop-in pipeline sink that routes a PreprocessedRequest
  to the chosen worker via ``client.direct`` (what the reference's processor
  does in examples/llm/components/kv_router.py + processor.py).

Worker liveness: instance set comes from the endpoint client's hub watch;
workers that disappear are pruned from the index (indexer.remove_worker —
the reference does this on etcd lease loss, kv_router/indexer.rs:380).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, AsyncIterator, Dict, Optional, Tuple

from ...runtime.client import Client
from ...runtime.engine import AsyncEngine, Context, ResponseStream
from .indexer import KvIndexer, KvIndexerSharded, WorkerId
from .publisher import KV_EVENTS_TOPIC, KvMetricsAggregator, unpack_message
from .scheduler import KvScheduler, KVHitRateEvent, KV_HIT_RATE_SUBJECT, WorkerSelector

logger = logging.getLogger(__name__)


class KvRouterCore:
    """Index + metrics + selection (shared by both router faces)."""

    def __init__(
        self,
        component,
        client: Client,
        block_size: int,
        selector: Optional[WorkerSelector] = None,
        sharded: bool = False,
        publish_hit_rate: bool = True,
    ):
        self.component = component
        self.client = client
        self.block_size = block_size
        self.indexer = (
            KvIndexerSharded(block_size) if sharded else KvIndexer(block_size)
        )
        self.aggregator = KvMetricsAggregator(component)
        self.scheduler = KvScheduler(
            block_size,
            selector=selector,
            hit_rate_callback=self._on_hit_rate if publish_hit_rate else None,
        )
        self._event_task: Optional[asyncio.Task] = None
        self._event_sub = None
        self._known_workers: set = set()
        self._bg: set = set()

    async def start(self) -> "KvRouterCore":
        self._event_sub = await self.component.subscribe(KV_EVENTS_TOPIC)
        self._event_task = asyncio.get_running_loop().create_task(self._event_loop())
        await self.aggregator.start()
        return self

    async def stop(self) -> None:
        if self._event_task is not None:
            self._event_task.cancel()
            try:
                await self._event_task
            except asyncio.CancelledError:
                pass
            self._event_task = None
        if self._event_sub is not None and hasattr(self._event_sub, "aclose"):
            await self._event_sub.aclose()
        await self.aggregator.stop()

    async def _event_loop(self) -> None:
        from .protocols import KvCacheEvent

        try:
            async for msg in self._event_sub:
                payload = unpack_message(msg)
                try:
                    worker = payload["worker_id"]
                    event = KvCacheEvent.from_dict(payload["event"])
                except (KeyError, TypeError):
                    logger.warning("malformed kv_event payload: %r", payload)
                    continue
                self.indexer.apply_event(worker, event)
        except asyncio.CancelledError:
            pass

    def _on_hit_rate(self, event: KVHitRateEvent) -> None:
        loop = asyncio.get_event_loop()
        task = loop.create_task(
            self.component.publish(KV_HIT_RATE_SUBJECT, event.to_dict())
        )
        self._bg.add(task)
        task.add_done_callback(self._bg.discard)

    def _prune_dead_workers(self, live: set) -> None:
        for gone in self._known_workers - live:
            logger.info("pruning dead worker %s from kv index", gone)
            self.indexer.remove_worker(gone)
            self.aggregator.remove_worker(gone)
        self._known_workers = live

    def select(
        self, token_ids, salt: Optional[str] = None
    ) -> Tuple[Optional[WorkerId], int]:
        """(best worker, overlap_blocks); None if no instances.  ``salt``
        is the tenant KV salt (llm/tenancy) — overlap hashing must match
        the engine's salted sealing or scores diverge from cache state."""
        live = set(self.client.instance_ids)
        if live != self._known_workers:
            self._prune_dead_workers(live)
        if not live:
            return None, 0
        overlap = self.indexer.find_matches(token_ids, salt)
        workers = self.aggregator.endpoints(sorted(live))
        winner = self.scheduler.schedule(len(token_ids), overlap, workers)
        return winner, overlap.scores.get(winner, 0) if winner is not None else 0


class KvRouter(AsyncEngine):
    """Standalone routing service (reference: components/router)."""

    def __init__(self, core: KvRouterCore):
        self.core = core

    async def generate(self, request: Context) -> ResponseStream:
        token_ids = request.data["token_ids"]
        worker_id, overlap = self.core.select(
            token_ids, request.data.get("kv_salt")
        )

        async def gen() -> AsyncIterator[Dict[str, Any]]:
            yield {"worker_id": worker_id, "overlap_blocks": overlap}

        return ResponseStream(gen(), request.ctx)


class KvPushRouter(AsyncEngine):
    """Pipeline sink: route PreprocessedRequest to the overlap-best worker.

    Falls back to round-robin when no worker has been selected (e.g. no KV
    events yet) — the client handles that internally via ``generate``.
    """

    def __init__(self, core: KvRouterCore):
        self.core = core

    async def generate(self, request: Context) -> ResponseStream:
        token_ids = request.data.get("token_ids") or []
        # Tenant requests (llm/tenancy) carry their KV salt in annotations;
        # the engine seals their blocks under the same salt, so routing
        # overlap only means anything when hashed identically.
        annotations = request.data.get("annotations") or {}
        worker_id, overlap = self.core.select(
            token_ids, annotations.get("kv_salt")
        )
        if worker_id is None:
            return await self.core.client.generate(request)
        return await self.core.client.generate(request, worker_id=worker_id)


async def make_kv_router(
    endpoint,
    block_size: int,
    selector: Optional[WorkerSelector] = None,
    sharded: bool = False,
) -> KvRouterCore:
    """Build + start a router core watching ``endpoint``'s worker fleet."""
    from ...runtime.client import RouterMode

    client = await endpoint.client(router_mode=RouterMode.ROUND_ROBIN)
    core = KvRouterCore(
        endpoint.component, client, block_size, selector=selector, sharded=sharded
    )
    return await core.start()
