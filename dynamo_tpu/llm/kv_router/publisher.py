"""Event/metrics publishers (worker side) and the metrics aggregator
(router side), over the runtime's event plane.

Reference semantics: lib/llm/src/kv_router/publisher.rs (KvEventPublisher:
worker-stamped cache events on subject ``kv_events``; KvMetricsPublisher:
ForwardPassMetrics via watch channel + stats scrape) and
metrics_aggregator.rs / scoring.rs (ProcessedEndpoints{endpoints, load_avg,
load_std}).  The TPU build pushes metrics on the event plane (subject
``kv_metrics``) instead of NATS ``$SRV.STATS`` polling — same data, push
instead of scrape.
"""

from __future__ import annotations

import asyncio
import logging
import statistics
from typing import Callable, Dict, List, Optional

from .indexer import WorkerId
from .protocols import ForwardPassMetrics, KvCacheEvent
from .scheduler import WorkerSnapshot

logger = logging.getLogger(__name__)

KV_EVENTS_TOPIC = "kv_events"
KV_METRICS_TOPIC = "kv_metrics"


def unpack_message(msg) -> dict:
    """Event-plane subscriptions yield ``(subject, payload)`` tuples."""
    if isinstance(msg, tuple) and len(msg) == 2:
        return msg[1]
    return getattr(msg, "payload", msg)


class KvEventPublisher:
    """Worker-side: stamp cache events with worker_id and publish them.

    Sync-callable (``__call__``) so it can be handed directly to the engine's
    ``event_callback``.  Publishes are serialized through one internal queue
    drained by a single sender task: the indexer depends on Stored arriving
    before its Removed (the reference preserves this via a single channel,
    publisher.rs) — independent create_task per event could reorder over a
    TCP hub.
    """

    def __init__(self, component, worker_id: WorkerId):
        self._component = component
        self.worker_id = worker_id
        self._queue: asyncio.Queue = asyncio.Queue()
        self._sender: Optional[asyncio.Task] = None

    def _enqueue(self, event: KvCacheEvent) -> "asyncio.Future":
        done: asyncio.Future = asyncio.get_event_loop().create_future()
        self._queue.put_nowait(
            ({"worker_id": self.worker_id, "event": event.to_dict()}, done)
        )
        if self._sender is None or self._sender.done():
            self._sender = asyncio.get_event_loop().create_task(self._drain())
        return done

    def __call__(self, event: KvCacheEvent) -> None:
        done = self._enqueue(event)
        # Fire-and-forget path: failures are logged by _drain; mark the
        # future's exception as retrieved so it doesn't warn at GC.
        done.add_done_callback(lambda f: f.exception())

    async def _drain(self) -> None:
        while not self._queue.empty():
            payload, done = self._queue.get_nowait()
            try:
                await self._component.publish(KV_EVENTS_TOPIC, payload)
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                logger.exception("kv event publish failed")
                if not done.done():
                    done.set_exception(exc)
            else:
                if not done.done():
                    done.set_result(None)

    async def publish(self, event: KvCacheEvent) -> None:
        """Awaitable publish that preserves queue ordering AND propagates
        transport failures to the caller (unlike the fire-and-forget path)."""
        await self._enqueue(event)

    async def flush(self) -> None:
        if self._sender is not None and not self._sender.done():
            await asyncio.shield(self._sender)


class KvMetricsPublisher:
    """Worker-side: periodically push ForwardPassMetrics snapshots."""

    def __init__(
        self,
        component,
        worker_id: WorkerId,
        source: Callable[[], ForwardPassMetrics],
        interval: float = 1.0,
    ):
        self._component = component
        self.worker_id = worker_id
        self._source = source
        self._interval = interval
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> "KvMetricsPublisher":
        self._task = asyncio.get_running_loop().create_task(self._run())
        return self

    async def publish_once(self) -> None:
        await self._component.publish(
            KV_METRICS_TOPIC,
            {"worker_id": self.worker_id, "metrics": self._source().to_dict()},
        )

    async def _run(self) -> None:
        try:
            while True:
                await self.publish_once()
                await asyncio.sleep(self._interval)
        except asyncio.CancelledError:
            pass
        except Exception:
            logger.exception("metrics publisher failed")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None


class KvMetricsAggregator:
    """Router-side: subscribe to metrics pushes, keep the latest snapshot per
    worker, expose ProcessedEndpoints-style load statistics."""

    def __init__(self, component):
        self._component = component
        self._snapshots: Dict[WorkerId, ForwardPassMetrics] = {}
        self._task: Optional[asyncio.Task] = None
        self._sub = None

    async def start(self) -> "KvMetricsAggregator":
        self._sub = await self._component.subscribe(KV_METRICS_TOPIC)
        self._task = asyncio.get_running_loop().create_task(self._run())
        return self

    async def _run(self) -> None:
        try:
            async for msg in self._sub:
                payload = unpack_message(msg)
                try:
                    wid = payload["worker_id"]
                    self._snapshots[wid] = ForwardPassMetrics.from_dict(
                        payload["metrics"]
                    )
                except (KeyError, TypeError):
                    logger.warning("malformed kv_metrics payload: %r", payload)
        except asyncio.CancelledError:
            pass

    def remove_worker(self, worker_id: WorkerId) -> None:
        self._snapshots.pop(worker_id, None)

    def snapshot(self, worker_id: WorkerId) -> ForwardPassMetrics:
        return self._snapshots.get(worker_id, ForwardPassMetrics())

    def endpoints(self, worker_ids: List[WorkerId]) -> List[WorkerSnapshot]:
        return [WorkerSnapshot(w, self.snapshot(w)) for w in worker_ids]

    def load_stats(self) -> Dict[str, float]:
        """ProcessedEndpoints load_avg/load_std over kv_active_blocks."""
        loads = [m.kv_active_blocks for m in self._snapshots.values()]
        if not loads:
            return {"load_avg": 0.0, "load_std": 0.0}
        return {
            "load_avg": float(statistics.fmean(loads)),
            "load_std": float(statistics.pstdev(loads)) if len(loads) > 1 else 0.0,
        }

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._sub is not None and hasattr(self._sub, "aclose"):
            await self._sub.aclose()
