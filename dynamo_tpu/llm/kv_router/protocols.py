"""KV event + worker metrics protocol types.

Reference semantics: lib/llm/src/kv_router/protocols.rs — ``KvCacheEvent``
(Stored{parent_hash, blocks[{block_hash, tokens_hash}]} / Removed{block_hashes}
/ Cleared) and ``ForwardPassMetrics``.  Hashes are the chained sequence hashes
from dynamo_tpu.tokens, so the router's radix index mirrors engine cache state
exactly (store/evict order included — SURVEY.md §7 hard part (e)).

Wire form is plain dicts (event plane JSON); dataclasses here are the typed
construction/parse helpers.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass(frozen=True)
class KvCacheStoredBlockData:
    block_hash: int  # chained sequence hash — the router index key
    tokens_hash: int  # local hash of the block's tokens

    def to_dict(self) -> Dict[str, Any]:
        return {"block_hash": self.block_hash, "tokens_hash": self.tokens_hash}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "KvCacheStoredBlockData":
        return cls(block_hash=d["block_hash"], tokens_hash=d["tokens_hash"])


@dataclass(frozen=True)
class KvCacheStoreData:
    parent_hash: Optional[int]
    blocks: List[KvCacheStoredBlockData] = field(default_factory=list)


@dataclass(frozen=True)
class KvCacheRemoveData:
    block_hashes: List[int] = field(default_factory=list)


# KV tier names, best (cheapest restore) first.  These label tier-tagged
# cache events and the indexer's discounted overlap weights.
TIER_HBM = "hbm"
TIER_HOST = "host"
TIER_DISK = "disk"
KV_TIERS = (TIER_HBM, TIER_HOST, TIER_DISK)


@dataclass(frozen=True)
class KvCacheTierData:
    """Blocks DEMOTED to (or promoted back up to) a lower tier but still
    restorable — the router keeps them matchable, discounted by restore
    cost, instead of forgetting them as Removed.  ``tier`` names where the
    cheapest surviving copy now lives."""

    tier: str  # one of KV_TIERS (never "hbm": Stored covers that)
    block_hashes: List[int] = field(default_factory=list)


@dataclass(frozen=True)
class KvCacheEvent:
    """One cache mutation; ``data`` is Store, Remove, TierChange, or None
    (= cleared)."""

    event_id: int
    data: Any  # KvCacheStoreData | KvCacheRemoveData | KvCacheTierData | None

    def to_dict(self) -> Dict[str, Any]:
        if isinstance(self.data, KvCacheStoreData):
            payload = {
                "stored": {
                    "parent_hash": self.data.parent_hash,
                    "blocks": [b.to_dict() for b in self.data.blocks],
                }
            }
        elif isinstance(self.data, KvCacheRemoveData):
            payload = {"removed": {"block_hashes": list(self.data.block_hashes)}}
        elif isinstance(self.data, KvCacheTierData):
            payload = {
                "tiered": {
                    "tier": self.data.tier,
                    "block_hashes": list(self.data.block_hashes),
                }
            }
        else:
            payload = {"cleared": {}}
        return {"event_id": self.event_id, "data": payload}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "KvCacheEvent":
        payload = d["data"]
        if "stored" in payload:
            s = payload["stored"]
            data: Any = KvCacheStoreData(
                parent_hash=s.get("parent_hash"),
                blocks=[KvCacheStoredBlockData.from_dict(b) for b in s["blocks"]],
            )
        elif "removed" in payload:
            data = KvCacheRemoveData(block_hashes=list(payload["removed"]["block_hashes"]))
        elif "tiered" in payload:
            t = payload["tiered"]
            data = KvCacheTierData(
                tier=t["tier"], block_hashes=list(t["block_hashes"])
            )
        else:
            data = None
        return cls(event_id=d["event_id"], data=data)

    @classmethod
    def stored(
        cls,
        event_id: int,
        parent_hash: Optional[int],
        blocks: List[KvCacheStoredBlockData],
    ) -> "KvCacheEvent":
        return cls(event_id, KvCacheStoreData(parent_hash, blocks))

    @classmethod
    def removed(cls, event_id: int, block_hashes: List[int]) -> "KvCacheEvent":
        return cls(event_id, KvCacheRemoveData(block_hashes))

    @classmethod
    def tiered(
        cls, event_id: int, tier: str, block_hashes: List[int]
    ) -> "KvCacheEvent":
        return cls(event_id, KvCacheTierData(tier, block_hashes))


@dataclass
class ForwardPassMetrics:
    """Per-worker load snapshot (kv_router/protocols.rs:42-54), published via
    the stats endpoint + event plane; the router's cost function reads it."""

    request_active_slots: int = 0
    request_total_slots: int = 0
    kv_active_blocks: int = 0
    kv_total_blocks: int = 0
    num_requests_waiting: int = 0
    gpu_cache_usage_perc: float = 0.0  # name kept for wire compat
    gpu_prefix_cache_hit_rate: float = 0.0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "request_active_slots": self.request_active_slots,
            "request_total_slots": self.request_total_slots,
            "kv_active_blocks": self.kv_active_blocks,
            "kv_total_blocks": self.kv_total_blocks,
            "num_requests_waiting": self.num_requests_waiting,
            "gpu_cache_usage_perc": self.gpu_cache_usage_perc,
            "gpu_prefix_cache_hit_rate": self.gpu_prefix_cache_hit_rate,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ForwardPassMetrics":
        return cls(**{k: d.get(k, 0) for k in cls().to_dict()})
