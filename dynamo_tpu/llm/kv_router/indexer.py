"""Global KV prefix index: which worker holds which cached blocks, and in
which memory tier.

Reference semantics (not code): lib/llm/src/kv_router/indexer.rs — a radix
structure over *chained* block hashes with a per-node worker set;
``apply_event`` ingests per-worker ``KvCacheEvent``s (Stored/Removed/Cleared)
and ``find_matches`` walks a request's block-hash chain, returning per-worker
overlap counts (how many leading blocks each worker already holds).

Tiered extension (docs/kv_tiering.md): engines with a host/disk tier emit
TIER-TAGGED events on demotion (HBM eviction of a block the host tier
retains publishes ``tiered{host}`` instead of ``Removed``; host→disk
demotion publishes ``tiered{disk}``) so the index keeps the block matchable
— discounted by restore cost.  ``find_matches`` therefore returns BOTH the
raw per-worker overlap (block counts, what a cross-worker pull compares)
and a DISCOUNTED overlap (each block weighted by its tier: hbm 1.0 > host >
disk) that the scheduler's cost function scores with, so a deep-but-cold
prefix loses to a shallow-but-hot one deterministically.

Because block hashes are chained (dynamo_tpu.tokens), one hash already
identifies its whole prefix, so lookup is a flat dict walk rather than an
explicit trie descent; parent links are kept for pruning and diagnostics.
The reference runs this on a dedicated thread fed by channels — here apply/
match are O(blocks) dict ops on the event loop; ``KvIndexerSharded`` spreads
very large indexes over hash shards (indexer.rs:499-796).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Mapping, Optional, Sequence, Set

from ...tokens import fast_sequence_hashes
from .protocols import (
    TIER_HBM,
    KvCacheEvent,
    KvCacheRemoveData,
    KvCacheStoreData,
    KvCacheTierData,
)

WorkerId = int

# Restore-cost discount per tier: one block's contribution to the
# discounted overlap score.  HBM is free (the block is live), host costs
# one scatter, disk costs a file read + promotion + scatter, the durable
# object store costs a multipart object read on top of that.  Unknown
# tier names (forward compat) score like disk — matchable but expensive.
# These are the COLD-START weights: once the autopilot's measured-latency
# policy has real per-hop restore percentiles it overrides them live via
# ``RadixIndex.set_tier_weights`` (docs/autopilot.md).
DEFAULT_TIER_WEIGHTS: Dict[str, float] = {
    "hbm": 1.0,
    "host": 0.75,
    "disk": 0.45,
    "objstore": 0.25,
}


@dataclass
class OverlapScores:
    """worker → number of leading request blocks it already caches.

    ``scores`` is the RAW block count (prefix depth — what a cross-worker
    pull compares and KVHitRateEvents report); ``discounted`` weights each
    block by its tier's restore cost (what the scheduler scores with)."""

    scores: Dict[WorkerId, int] = field(default_factory=dict)
    discounted: Dict[WorkerId, float] = field(default_factory=dict)

    def discounted_for(self, worker: WorkerId) -> float:
        """Tier-discounted overlap; falls back to the raw count for
        overlap sources that never tagged tiers (pre-tier publishers)."""
        got = self.discounted.get(worker)
        return float(self.scores.get(worker, 0)) if got is None else got

    def best(self) -> Optional[WorkerId]:
        if not self.scores:
            return None
        return max(
            self.scores,
            key=lambda w: (self.discounted_for(w), self.scores[w], -w),
        )

    def deepest(self) -> Optional[WorkerId]:
        """Worker with the longest RAW prefix (ties → lowest id,
        deterministic) — the cross-worker pull's donor candidate."""
        if not self.scores:
            return None
        return max(self.scores, key=lambda w: (self.scores[w], -w))


@dataclass
class _Node:
    # worker → tier name currently holding this block ("hbm"/"host"/"disk").
    workers: Dict[WorkerId, str] = field(default_factory=dict)
    parent_hash: Optional[int] = None


class RadixIndex:
    """Hash → worker/tier index with per-worker reverse map for removal."""

    def __init__(self, tier_weights: Optional[Mapping[str, float]] = None):
        self._nodes: Dict[int, _Node] = {}
        self._by_worker: Dict[WorkerId, Set[int]] = {}
        self.tier_weights = dict(tier_weights or DEFAULT_TIER_WEIGHTS)

    def __len__(self) -> int:
        return len(self._nodes)

    def _weight(self, tier: str) -> float:
        return self.tier_weights.get(tier, self.tier_weights.get("disk", 0.45))

    def set_tier_weights(self, weights: Mapping[str, float]) -> None:
        """Live retune from the autopilot's measured-latency routing policy
        (``set_tier_weights`` directives): replaces the static cold-start
        table wholesale.  Takes effect on the next ``find_matches``."""
        self.tier_weights = dict(weights)

    def add_block(
        self,
        worker: WorkerId,
        seq_hash: int,
        parent_hash: Optional[int],
        tier: str = TIER_HBM,
    ) -> None:
        node = self._nodes.get(seq_hash)
        if node is None:
            node = self._nodes[seq_hash] = _Node(parent_hash=parent_hash)
        node.workers[worker] = tier
        self._by_worker.setdefault(worker, set()).add(seq_hash)

    def set_tier(self, worker: WorkerId, seq_hash: int, tier: str) -> None:
        """Apply a tier-tagged event: the block is still restorable on
        ``worker``, now from ``tier``.  Unknown blocks are ADDED — a tier
        event for a block the index missed (e.g. an index started after
        the Stored) still recovers matchable state."""
        self.add_block(worker, seq_hash, None, tier=tier)

    def remove_block(self, worker: WorkerId, seq_hash: int) -> None:
        node = self._nodes.get(seq_hash)
        if node is None:
            return
        node.workers.pop(worker, None)
        owned = self._by_worker.get(worker)
        if owned is not None:
            owned.discard(seq_hash)
        if not node.workers:
            del self._nodes[seq_hash]

    def remove_worker(self, worker: WorkerId) -> None:
        for seq_hash in self._by_worker.pop(worker, set()):
            node = self._nodes.get(seq_hash)
            if node is not None:
                node.workers.pop(worker, None)
                if not node.workers:
                    del self._nodes[seq_hash]

    def workers_for(self, seq_hash: int) -> Dict[WorkerId, str]:
        """worker → tier for one block (empty when unknown)."""
        node = self._nodes.get(seq_hash)
        return node.workers if node is not None else {}

    def find_matches(self, seq_hashes: Sequence[int]) -> OverlapScores:
        """Per-worker count of leading blocks present (a worker's count stops
        at its first missing block — prefix semantics) plus the
        tier-discounted sum over the same run."""
        scores: Dict[WorkerId, int] = {}
        discounted: Dict[WorkerId, float] = {}
        active: Optional[Set[WorkerId]] = None
        for i, h in enumerate(seq_hashes):
            holders = self.workers_for(h)
            active = (
                set(holders) if active is None else active & set(holders)
            )
            if not active:
                break
            for w in active:
                scores[w] = i + 1
                discounted[w] = discounted.get(w, 0.0) + self._weight(
                    holders[w]
                )
        return OverlapScores(scores, discounted)


class KvIndexer:
    """Event-driven index over one worker fleet (one model endpoint)."""

    def __init__(
        self,
        block_size: int,
        tier_weights: Optional[Mapping[str, float]] = None,
    ):
        self.block_size = block_size
        self._index = RadixIndex(tier_weights)
        self.events_applied = 0

    def apply_event(self, worker: WorkerId, event: KvCacheEvent) -> None:
        data = event.data
        if isinstance(data, KvCacheStoreData):
            # Chain within the event: the first block parents on the event's
            # parent_hash, each subsequent block on its predecessor.
            parent = data.parent_hash
            for blk in data.blocks:
                self._index.add_block(worker, blk.block_hash, parent)
                parent = blk.block_hash
        elif isinstance(data, KvCacheRemoveData):
            for h in data.block_hashes:
                self._index.remove_block(worker, h)
        elif isinstance(data, KvCacheTierData):
            for h in data.block_hashes:
                self._index.set_tier(worker, h, data.tier)
        else:  # cleared
            self._index.remove_worker(worker)
        self.events_applied += 1

    def remove_worker(self, worker: WorkerId) -> None:
        self._index.remove_worker(worker)

    def find_matches(
        self, token_ids: Sequence[int], salt: Optional[str] = None
    ) -> OverlapScores:
        """``salt`` is the requesting tenant's KV salt (llm/tenancy —
        ``annotations.kv_salt``): engines seal tenant blocks under salted
        chained hashes, so an unsalted lookup for a tenant request (or vice
        versa) scores structurally zero overlap — exactly the isolation the
        salt exists to provide."""
        return self.find_matches_for_hashes(
            fast_sequence_hashes(token_ids, self.block_size, salt)
        )

    def find_matches_for_hashes(self, seq_hashes: Sequence[int]) -> OverlapScores:
        return self._index.find_matches(seq_hashes)

    def set_tier_weights(self, weights: Mapping[str, float]) -> None:
        self._index.set_tier_weights(weights)

    def __len__(self) -> int:
        return len(self._index)


class KvIndexerSharded:
    """Hash-sharded variant for very large fleets (indexer.rs:499-796): each
    shard owns hashes where ``hash % num_shards == shard_id``.  Matching
    queries every shard per block (cheap dict hits) — the win is bounded
    per-shard memory and, later, per-shard threads/processes."""

    def __init__(
        self,
        block_size: int,
        num_shards: int = 4,
        tier_weights: Optional[Mapping[str, float]] = None,
    ):
        self.block_size = block_size
        self.num_shards = num_shards
        self._shards = [
            KvIndexer(block_size, tier_weights) for _ in range(num_shards)
        ]

    def _shard_for(self, seq_hash: int) -> KvIndexer:
        return self._shards[seq_hash % self.num_shards]

    def apply_event(self, worker: WorkerId, event: KvCacheEvent) -> None:
        data = event.data
        if isinstance(data, KvCacheStoreData):
            for blk in data.blocks:
                self._shard_for(blk.block_hash)._index.add_block(
                    worker, blk.block_hash, data.parent_hash
                )
        elif isinstance(data, KvCacheRemoveData):
            for h in data.block_hashes:
                self._shard_for(h)._index.remove_block(worker, h)
        elif isinstance(data, KvCacheTierData):
            for h in data.block_hashes:
                self._shard_for(h)._index.set_tier(worker, h, data.tier)
        else:
            for shard in self._shards:
                shard.remove_worker(worker)

    def remove_worker(self, worker: WorkerId) -> None:
        for shard in self._shards:
            shard.remove_worker(worker)

    def set_tier_weights(self, weights: Mapping[str, float]) -> None:
        for shard in self._shards:
            shard.set_tier_weights(weights)

    def find_matches(
        self, token_ids: Sequence[int], salt: Optional[str] = None
    ) -> OverlapScores:
        return self.find_matches_for_hashes(
            fast_sequence_hashes(token_ids, self.block_size, salt)
        )

    def find_matches_for_hashes(self, seq_hashes: Sequence[int]) -> OverlapScores:
        scores: Dict[WorkerId, int] = {}
        discounted: Dict[WorkerId, float] = {}
        active: Optional[Set[WorkerId]] = None
        for i, h in enumerate(seq_hashes):
            shard = self._shard_for(h)._index
            holders = shard.workers_for(h)
            active = set(holders) if active is None else active & set(holders)
            if not active:
                break
            for w in active:
                scores[w] = i + 1
                discounted[w] = discounted.get(w, 0.0) + shard._weight(
                    holders[w]
                )
        return OverlapScores(scores, discounted)
