"""Global KV prefix index: which worker holds which cached blocks.

Reference semantics (not code): lib/llm/src/kv_router/indexer.rs — a radix
structure over *chained* block hashes with a per-node worker set;
``apply_event`` ingests per-worker ``KvCacheEvent``s (Stored/Removed/Cleared)
and ``find_matches`` walks a request's block-hash chain, returning per-worker
overlap counts (how many leading blocks each worker already holds).

Because block hashes are chained (dynamo_tpu.tokens), one hash already
identifies its whole prefix, so lookup is a flat dict walk rather than an
explicit trie descent; parent links are kept for pruning and diagnostics.
The reference runs this on a dedicated thread fed by channels — here apply/
match are O(blocks) dict ops on the event loop; ``KvIndexerSharded`` spreads
very large indexes over hash shards (indexer.rs:499-796).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set

from ...tokens import fast_sequence_hashes
from .protocols import KvCacheEvent, KvCacheRemoveData, KvCacheStoreData

WorkerId = int


@dataclass
class OverlapScores:
    """worker → number of leading request blocks it already caches."""

    scores: Dict[WorkerId, int] = field(default_factory=dict)

    def best(self) -> Optional[WorkerId]:
        if not self.scores:
            return None
        return max(self.scores, key=self.scores.get)


@dataclass
class _Node:
    workers: Set[WorkerId] = field(default_factory=set)
    parent_hash: Optional[int] = None


class RadixIndex:
    """Hash → worker-set index with per-worker reverse map for fast removal."""

    def __init__(self) -> None:
        self._nodes: Dict[int, _Node] = {}
        self._by_worker: Dict[WorkerId, Set[int]] = {}

    def __len__(self) -> int:
        return len(self._nodes)

    def add_block(
        self, worker: WorkerId, seq_hash: int, parent_hash: Optional[int]
    ) -> None:
        node = self._nodes.get(seq_hash)
        if node is None:
            node = self._nodes[seq_hash] = _Node(parent_hash=parent_hash)
        node.workers.add(worker)
        self._by_worker.setdefault(worker, set()).add(seq_hash)

    def remove_block(self, worker: WorkerId, seq_hash: int) -> None:
        node = self._nodes.get(seq_hash)
        if node is None:
            return
        node.workers.discard(worker)
        owned = self._by_worker.get(worker)
        if owned is not None:
            owned.discard(seq_hash)
        if not node.workers:
            del self._nodes[seq_hash]

    def remove_worker(self, worker: WorkerId) -> None:
        for seq_hash in self._by_worker.pop(worker, set()):
            node = self._nodes.get(seq_hash)
            if node is not None:
                node.workers.discard(worker)
                if not node.workers:
                    del self._nodes[seq_hash]

    def workers_for(self, seq_hash: int) -> Set[WorkerId]:
        node = self._nodes.get(seq_hash)
        return node.workers if node is not None else set()

    def find_matches(self, seq_hashes: Sequence[int]) -> OverlapScores:
        """Per-worker count of leading blocks present (a worker's count stops
        at its first missing block — prefix semantics)."""
        scores: Dict[WorkerId, int] = {}
        active: Optional[Set[WorkerId]] = None
        for i, h in enumerate(seq_hashes):
            holders = self.workers_for(h)
            active = set(holders) if active is None else active & holders
            if not active:
                break
            for w in active:
                scores[w] = i + 1
        return OverlapScores(scores)


class KvIndexer:
    """Event-driven index over one worker fleet (one model endpoint)."""

    def __init__(self, block_size: int):
        self.block_size = block_size
        self._index = RadixIndex()
        self.events_applied = 0

    def apply_event(self, worker: WorkerId, event: KvCacheEvent) -> None:
        data = event.data
        if isinstance(data, KvCacheStoreData):
            # Chain within the event: the first block parents on the event's
            # parent_hash, each subsequent block on its predecessor.
            parent = data.parent_hash
            for blk in data.blocks:
                self._index.add_block(worker, blk.block_hash, parent)
                parent = blk.block_hash
        elif isinstance(data, KvCacheRemoveData):
            for h in data.block_hashes:
                self._index.remove_block(worker, h)
        else:  # cleared
            self._index.remove_worker(worker)
        self.events_applied += 1

    def remove_worker(self, worker: WorkerId) -> None:
        self._index.remove_worker(worker)

    def find_matches(
        self, token_ids: Sequence[int], salt: Optional[str] = None
    ) -> OverlapScores:
        """``salt`` is the requesting tenant's KV salt (llm/tenancy —
        ``annotations.kv_salt``): engines seal tenant blocks under salted
        chained hashes, so an unsalted lookup for a tenant request (or vice
        versa) scores structurally zero overlap — exactly the isolation the
        salt exists to provide."""
        return self.find_matches_for_hashes(
            fast_sequence_hashes(token_ids, self.block_size, salt)
        )

    def find_matches_for_hashes(self, seq_hashes: Sequence[int]) -> OverlapScores:
        return self._index.find_matches(seq_hashes)

    def __len__(self) -> int:
        return len(self._index)


class KvIndexerSharded:
    """Hash-sharded variant for very large fleets (indexer.rs:499-796): each
    shard owns hashes where ``hash % num_shards == shard_id``.  Matching
    queries every shard per block (cheap dict hits) — the win is bounded
    per-shard memory and, later, per-shard threads/processes."""

    def __init__(self, block_size: int, num_shards: int = 4):
        self.block_size = block_size
        self.num_shards = num_shards
        self._shards = [KvIndexer(block_size) for _ in range(num_shards)]

    def _shard_for(self, seq_hash: int) -> KvIndexer:
        return self._shards[seq_hash % self.num_shards]

    def apply_event(self, worker: WorkerId, event: KvCacheEvent) -> None:
        data = event.data
        if isinstance(data, KvCacheStoreData):
            for blk in data.blocks:
                self._shard_for(blk.block_hash)._index.add_block(
                    worker, blk.block_hash, data.parent_hash
                )
        elif isinstance(data, KvCacheRemoveData):
            for h in data.block_hashes:
                self._shard_for(h)._index.remove_block(worker, h)
        else:
            for shard in self._shards:
                shard.remove_worker(worker)

    def remove_worker(self, worker: WorkerId) -> None:
        for shard in self._shards:
            shard.remove_worker(worker)

    def find_matches(
        self, token_ids: Sequence[int], salt: Optional[str] = None
    ) -> OverlapScores:
        hashes = fast_sequence_hashes(token_ids, self.block_size, salt)
        scores: Dict[WorkerId, int] = {}
        active: Optional[Set[WorkerId]] = None
        for i, h in enumerate(hashes):
            holders = self._shard_for(h)._index.workers_for(h)
            active = set(holders) if active is None else active & holders
            if not active:
                break
            for w in active:
                scores[w] = i + 1
        return OverlapScores(scores)
