"""Minimal sentencepiece runtime: parse ``tokenizer.model`` directly and
tokenize with it — no sentencepiece/protobuf dependency.

Reference counterpart: ``lib/llm/src/tokenizers/sp.rs`` (the reference
serves tokenizer.model-only checkpoints — older Llama/Mistral releases —
natively).  The .model file is a protobuf ``ModelProto``; the subset that
matters for inference is tiny and stable, so this module walks the wire
format directly:

  ModelProto:    field 1 repeated SentencePiece, field 2 TrainerSpec,
                 field 3 NormalizerSpec
  SentencePiece: field 1 piece (string), field 2 score (float),
                 field 3 type (1=NORMAL 2=UNKNOWN 3=CONTROL 4=USER_DEFINED
                 5=UNUSED 6=BYTE)
  TrainerSpec:   field 3 model_type (1=UNIGRAM 2=BPE), fields 40-42,45
                 unk/bos/eos/pad ids
  NormalizerSpec: field 2 precompiled_charsmap, field 3 add_dummy_prefix,
                 field 5 escape_whitespaces

Models whose NormalizerSpec carries a non-empty ``precompiled_charsmap``
(an NFKC-style normalization automaton this module does not execute) or
``escape_whitespaces=false`` (spaces are NOT ▁-escaped) are REFUSED at
parse time rather than silently mis-tokenized — serving a model through
the wrong normalizer corrupts every prompt.

Encoding implements both algorithms over the piece vocabulary:
- **unigram**: Viterbi segmentation maximizing the sum of piece scores;
- **BPE**: greedy highest-score adjacent merge (sentencepiece BPE stores
  merge priority as the piece score).
Unknown characters fall back to BYTE pieces (``<0xNN>``) when the model
ships them, else the unk id.  Decode maps BYTE pieces back to raw bytes
and ``▁`` to space, dropping control pieces — byte-exact round trips for
text the model covers.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

WS = "▁"  # ▁ sentencepiece whitespace marker

NORMAL, UNKNOWN, CONTROL, USER_DEFINED, UNUSED, BYTE = 1, 2, 3, 4, 5, 6


def _walk(buf: bytes, pos: int, end: int):
    """Yield (field_number, wire_type, value, new_pos) over a message."""
    while pos < end:
        tag, pos = _varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:  # varint
            val, pos = _varint(buf, pos)
        elif wire == 1:  # 64-bit
            val, pos = buf[pos:pos + 8], pos + 8
        elif wire == 2:  # length-delimited
            ln, pos = _varint(buf, pos)
            val, pos = buf[pos:pos + ln], pos + ln
        elif wire == 5:  # 32-bit
            val, pos = buf[pos:pos + 4], pos + 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wire}")
        yield field, wire, val, pos


def _varint(buf: bytes, pos: int) -> Tuple[int, int]:
    out = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


class SentencePieceModel:
    """Parsed tokenizer.model: vocabulary, scores, and the two encoders."""

    def __init__(self, blob: bytes):
        self.pieces: List[str] = []
        self.scores: List[float] = []
        self.types: List[int] = []
        self.model_type = 1  # UNIGRAM default
        self.unk_id, self.bos_id, self.eos_id = 0, 1, 2
        self.add_dummy_prefix = True
        self.escape_whitespaces = True
        self.precompiled_charsmap = b""
        for field, wire, val, _ in _walk(blob, 0, len(blob)):
            if field == 1 and wire == 2:  # SentencePiece
                piece, score, typ = "", 0.0, NORMAL
                for f2, w2, v2, _ in _walk(val, 0, len(val)):
                    if f2 == 1 and w2 == 2:
                        piece = v2.decode("utf-8")
                    elif f2 == 2 and w2 == 5:
                        score = struct.unpack("<f", v2)[0]
                    elif f2 == 3 and w2 == 0:
                        typ = v2
                self.pieces.append(piece)
                self.scores.append(score)
                self.types.append(typ)
            elif field == 2 and wire == 2:  # TrainerSpec
                for f2, w2, v2, _ in _walk(val, 0, len(val)):
                    if f2 == 3 and w2 == 0:
                        self.model_type = v2
                    elif f2 == 40 and w2 == 0:
                        self.unk_id = v2
                    elif f2 == 41 and w2 == 0:
                        self.bos_id = v2
                    elif f2 == 42 and w2 == 0:
                        self.eos_id = v2
            elif field == 3 and wire == 2:  # NormalizerSpec
                for f2, w2, v2, _ in _walk(val, 0, len(val)):
                    if f2 == 2 and w2 == 2:
                        self.precompiled_charsmap = v2
                    elif f2 == 3 and w2 == 0:
                        self.add_dummy_prefix = bool(v2)
                    elif f2 == 5 and w2 == 0:
                        self.escape_whitespaces = bool(v2)
        if not self.pieces:
            raise ValueError("tokenizer.model contains no sentencepiece vocab")
        if self.precompiled_charsmap:
            # e.g. T5/ALBERT-style NFKC models.  Tokenizing without running
            # the automaton silently diverges from the training-time
            # normalization; refuse rather than serve a wrong tokenizer.
            raise ValueError(
                "tokenizer.model carries a non-empty NormalizerSpec."
                "precompiled_charsmap (normalization automaton) which this "
                "parser does not execute — refusing to mis-tokenize; use a "
                "tokenizer.json for this model instead"
            )
        if not self.escape_whitespaces:
            raise ValueError(
                "tokenizer.model sets NormalizerSpec.escape_whitespaces="
                "false; this parser assumes ▁-escaped whitespace — "
                "refusing to mis-tokenize"
            )
        self.index: Dict[str, int] = {p: i for i, p in enumerate(self.pieces)}
        self._byte_ids: Dict[int, int] = {}
        for i, (p, t) in enumerate(zip(self.pieces, self.types)):
            if t == BYTE and len(p) == 6 and p.startswith("<0x"):
                self._byte_ids[int(p[3:5], 16)] = i
        self._max_piece_len = max(len(p) for p in self.pieces)
        # Special tokens matched as literal spans BEFORE segmentation —
        # chat templates interpolate "<s>"/"</s>"/"[INST]"-style control
        # and user-defined pieces as text, and those must become their ids,
        # never character pieces (HF's AddedVocabulary role).
        import re

        specials = [
            p for p, t in zip(self.pieces, self.types)
            if t in (CONTROL, USER_DEFINED) and p
        ]
        self._special_re = (
            re.compile("|".join(re.escape(p) for p in
                                sorted(specials, key=len, reverse=True)))
            if specials else None
        )

    # ----------------------------------------------------------- encoding
    def encode(self, text: str) -> List[int]:
        """Text → ids.  Control/user-defined pieces appearing literally in
        the text (chat-template markers) map straight to their ids; the
        spans between them segment per model_type, each with the model's
        dummy-prefix rule (matching sentencepiece's per-call prefix — the
        HF slow-tokenizer "legacy" behavior older checkpoints trained
        with)."""
        if not text:
            return []
        ids: List[int] = []
        pos = 0
        spans: List[Tuple[Optional[int], str]] = []
        if self._special_re is not None:
            for m in self._special_re.finditer(text):
                if m.start() > pos:
                    spans.append((None, text[pos:m.start()]))
                spans.append((self.index[m.group()], ""))
                pos = m.end()
        if pos < len(text):
            spans.append((None, text[pos:]))
        for special_id, chunk in spans:
            if special_id is not None:
                ids.append(special_id)
                continue
            norm = chunk.replace(" ", WS)
            if self.add_dummy_prefix and not norm.startswith(WS):
                norm = WS + norm
            ids.extend(
                self._encode_bpe(norm) if self.model_type == 2
                else self._encode_unigram(norm)
            )
        return ids

    def _char_fallback(self, ch: str) -> List[int]:
        ids = []
        for b in ch.encode("utf-8"):
            bid = self._byte_ids.get(b)
            if bid is None:
                return [self.unk_id]
            ids.append(bid)
        return ids

    def _encode_unigram(self, norm: str) -> List[int]:
        """Viterbi over piece scores (ties break toward longer pieces via
        traversal order, matching sentencepiece's lattice best-path)."""
        n = len(norm)
        NEG = -1e18
        best = [NEG] * (n + 1)
        back: List[Optional[Tuple[int, Optional[int]]]] = [None] * (n + 1)
        best[0] = 0.0
        for i in range(n):
            if best[i] == NEG:
                continue
            for j in range(i + 1, min(n, i + self._max_piece_len) + 1):
                pid = self.index.get(norm[i:j])
                if pid is None or self.types[pid] in (CONTROL, UNUSED):
                    continue
                s = best[i] + self.scores[pid]
                if s > best[j]:
                    best[j], back[j] = s, (i, pid)
            if best[i + 1] == NEG:  # no piece covers norm[i]: byte fallback
                best[i + 1], back[i + 1] = best[i] - 100.0, (i, None)
        ids: List[int] = []
        spans: List[Tuple[int, int, Optional[int]]] = []
        j = n
        while j > 0:
            i, pid = back[j]
            spans.append((i, j, pid))
            j = i
        for i, j, pid in reversed(spans):
            ids.extend(self._char_fallback(norm[i:j]) if pid is None else [pid])
        return ids

    def _encode_bpe(self, norm: str) -> List[int]:
        """Greedy merges: repeatedly join the adjacent pair whose merged
        piece has the highest score (sentencepiece BPE merge priority).

        Heap + doubly-linked symbol list → O(n log n): this is the
        production encode path for Llama-2/Mistral tokenizer.model files
        (model_type=BPE), so prefill-length prompts must not pay a
        rescan-all-pairs O(n^2)."""
        import heapq

        n = len(norm)
        if n == 0:
            return []
        sym: List[Optional[str]] = list(norm)  # None = absorbed slot
        prev = list(range(-1, n - 1))
        nxt = list(range(1, n + 1))  # n = end sentinel
        heap: List[Tuple[float, int, int, str]] = []

        def push(i: int) -> None:
            j = nxt[i]
            if j >= n or sym[i] is None or sym[j] is None:
                return
            merged = sym[i] + sym[j]
            pid = self.index.get(merged)
            if pid is not None:
                # (-score, left position): highest score first, leftmost on
                # ties — sentencepiece's merge order.
                heapq.heappush(heap, (-self.scores[pid], i, j, merged))

        for i in range(n - 1):
            push(i)
        while heap:
            _, i, j, merged = heapq.heappop(heap)
            # Stale entries: either slot absorbed, or no longer adjacent,
            # or the strings changed since this pair was pushed.
            if sym[i] is None or sym[j] is None or nxt[i] != j:
                continue
            if sym[i] + sym[j] != merged:
                continue
            sym[i] = merged
            sym[j] = None
            nxt[i] = nxt[j]
            if nxt[j] < n:
                prev[nxt[j]] = i
            push(i)
            if prev[i] >= 0:
                push(prev[i])
        ids: List[int] = []
        i = 0  # slot 0 is always live (merges keep their left index)
        while i < n:
            s = sym[i]
            pid = self.index.get(s)
            if pid is None or self.types[pid] in (CONTROL, UNUSED):
                ids.extend(self._char_fallback(s))
            else:
                ids.append(pid)
            i = nxt[i]
        return ids

    # ----------------------------------------------------------- decoding
    def decode(self, ids: List[int], sequence_start: bool = True) -> str:
        """Ids → text: BYTE pieces concatenate to raw bytes, ▁ → space,
        control pieces dropped.  ``sequence_start`` governs the
        dummy-prefix strip: only a window that begins the sequence drops
        its leading space — incremental detokenizers decode mid-stream
        windows with ``sequence_start=False`` so inter-token spaces
        survive the prefix-diff (llm/tokenizer.DecodeStream)."""
        out: List[str] = []
        pending: List[int] = []  # byte-piece run

        def flush():
            if pending:
                out.append(bytes(pending).decode("utf-8", errors="replace"))
                pending.clear()

        for i in ids:
            if not 0 <= i < len(self.pieces):
                continue
            t = self.types[i]
            if t == BYTE:
                pending.append(int(self.pieces[i][3:5], 16))
                continue
            flush()
            if t in (CONTROL, UNKNOWN):
                continue
            out.append(self.pieces[i].replace(WS, " "))
        flush()
        text = "".join(out)
        if sequence_start and self.add_dummy_prefix and text.startswith(" "):
            text = text[1:]
        return text

    def id_to_piece(self, i: int) -> str:
        return self.pieces[i]

    @property
    def vocab_size(self) -> int:
        return len(self.pieces)

    @classmethod
    def from_file(cls, path: str) -> "SentencePieceModel":
        with open(path, "rb") as f:
            return cls(f.read())


# ------------------------------------------------------------------ writer
def build_model_proto(
    pieces: List[Tuple[str, float, int]],
    *,
    model_type: int = 1,
    add_dummy_prefix: bool = True,
    unk_id: int = 0,
    bos_id: int = 1,
    eos_id: int = 2,
    escape_whitespaces: bool = True,
    precompiled_charsmap: bytes = b"",
) -> bytes:
    """Serialize a minimal ModelProto — the test-fixture writer (building a
    real .model without the sentencepiece library), kept next to the parser
    so the two stay in sync with the same field map."""

    def varint(v: int) -> bytes:
        out = b""
        while True:
            b7 = v & 0x7F
            v >>= 7
            out += bytes([b7 | (0x80 if v else 0)])
            if not v:
                return out

    def field(num: int, wire: int, payload: bytes) -> bytes:
        return varint((num << 3) | wire) + payload

    blob = b""
    for piece, score, typ in pieces:
        sp = field(1, 2, varint(len(piece.encode())) + piece.encode())
        sp += field(2, 5, struct.pack("<f", score))
        sp += field(3, 0, varint(typ))
        blob += field(1, 2, varint(len(sp)) + sp)
    trainer = (
        field(3, 0, varint(model_type))
        + field(40, 0, varint(unk_id))
        + field(41, 0, varint(bos_id))
        + field(42, 0, varint(eos_id))
    )
    blob += field(2, 2, varint(len(trainer)) + trainer)
    norm = field(3, 0, varint(1 if add_dummy_prefix else 0))
    norm += field(5, 0, varint(1 if escape_whitespaces else 0))
    if precompiled_charsmap:
        norm += field(
            2, 2, varint(len(precompiled_charsmap)) + precompiled_charsmap
        )
    blob += field(3, 2, varint(len(norm)) + norm)
    return blob
