"""MigratableWorker: source/target halves of live sequence migration.

Protocol (Llumnix-style two-phase commit over the service plane):

phase 1 — *copy while decoding*: the source streams the sequence's sealed
KV blocks (``export_prompt_blocks`` from a moving frontier) to the target's
``migrate_in`` endpoint, where ``inject_blocks`` seals them under the same
chained hashes.  The sequence KEEPS DECODING on the source; each round
picks up the blocks sealed since the last, so the un-copied delta shrinks
to at most ``delta_blocks`` regardless of sequence length.

phase 2 — *freeze, final delta, commit*: the source freezes the sequence
(engine ``freeze_sequence`` — planned out, in-flight dispatches drained),
exports the last sealed blocks plus the ``SequenceSnapshot``, and sends a
``commit``.  The target validates config + capacity and acks.

cutover — the source emits one final stream item carrying the ``migrated``
splice marker ({target, resume request}) and releases the sequence.  The
routed client (runtime/client.py) consumes the marker and re-dispatches
the resume request to the target, whose engine admits it against the
transferred blocks as an ordinary prefix hit — decode continues with only
the unsealed tail (< block_size tokens) recomputed, and the client-visible
token stream is byte-identical to the never-migrated run.

rollback — ANY failure after the freeze unfreezes the sequence and returns
the source to sole authority; the client never observes the attempt.
Blocks already copied stay on the target as harmless prefix-cache fills.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import (
    Any,
    AsyncIterator,
    Awaitable,
    Callable,
    Dict,
    List,
    Optional,
)

from ...runtime.client import Client
from ...runtime.engine import AsyncEngine, Context, ResponseStream
from ...runtime.tracing import parse_trace, span as trace_span
from ..metrics import migration_metrics as metrics
from .snapshot import SequenceSnapshot

logger = logging.getLogger(__name__)

MIGRATE_IN_ENDPOINT = "migrate_in"
MIGRATE_OUT_ENDPOINT = "migrate_out"


class MigrationTargetError(RuntimeError):
    """Target refused blocks or the commit (config/capacity mismatch)."""


class MigratableWorker(AsyncEngine):
    """Wraps a TpuEngine (and optionally an inner serving engine such as a
    DisaggDecodeWorker) with the migration protocol's two endpoint handlers
    plus the source-side ``migrate_out`` driver."""

    def __init__(
        self,
        engine,
        serve: Optional[AsyncEngine] = None,
        chunk_blocks: int = 32,
        max_copy_rounds: int = 16,
        delta_blocks: int = 2,
        freeze_timeout: float = 10.0,
        direct: Optional[Dict[str, "MigratableWorker"]] = None,
    ):
        self.engine = engine
        self.serve = serve if serve is not None else engine
        self.chunk_blocks = max(1, chunk_blocks)
        self.max_copy_rounds = max(1, max_copy_rounds)
        # Stop phase-1 looping once the un-copied sealed delta is this
        # small; the remainder rides the final-delta freeze window.
        self.delta_blocks = max(0, delta_blocks)
        self.freeze_timeout = freeze_timeout
        # Co-located peers by address (same process / shared slice): pushes
        # short-circuit the service plane (tests; single-process fleets).
        self.direct = direct or {}
        self._clients: Dict[str, Client] = {}
        # Bulk data plane (transports/bulk.py, DYN_BULK_PLANE): when the
        # CLI wires a BulkRendezvous here, phase-1 copy payloads move
        # worker↔worker instead of through the hub; None = hub path only.
        self.bulk = None
        # Injectable copy-round barrier: awaited once after every phase-1
        # copy round as ``hook(cursor, final=False)`` and once more —
        # ``hook(cursor, final=True)`` — after the loop breaks, immediately
        # before the freeze (no suspension point between the final call
        # returning and ``frozen`` being set, so a gate released on
        # ``final`` cannot lose a plan-new-chunks race).  Tests pair it
        # with engine.pace_hook to make the copy-vs-decode race
        # count-bounded instead of wall-clock raced; None = zero cost.
        self.copy_round_hook: Optional[
            Callable[[int, bool], Awaitable[None]]
        ] = None
        # Accept-time capability gate: a draining worker flips this False
        # BEFORE starting its own migrate-out (cli WorkerRoles.stop_decode),
        # closing the de-advertise propagation race — a peer whose hub
        # snapshot predates the metadata rewrite can still PICK this worker,
        # but the pick is re-checked here at accept time and refused, so two
        # concurrent drains can never migrate into each other.
        self.accepting = True

    # ------------------------------------------------------------- serving
    async def generate(self, request: Context) -> ResponseStream:
        return await self.serve.generate(request)

    def stop_accepting(self) -> None:
        """Refuse future migrate-in traffic (drain/quarantine path)."""
        self.accepting = False

    # ---------------------------------------------------------- target side
    async def migrate_in_handler(self, request: Context) -> AsyncIterator[Dict]:
        yield await self._migrate_in(request.data)

    async def _migrate_in(self, data: Dict[str, Any]) -> Dict[str, Any]:
        if not self.accepting:
            # Sources treat any refusal as abort/rollback: the sequence
            # stays authoritative on the source and another target is
            # picked on the next drain round.
            return {"ok": False, "error": "target draining; migrate-in refused"}
        kind = data.get("kind", "blocks")
        tokens = list(data["token_ids"])
        # Tenant sequences (llm/tenancy) seal KV under a salted hash chain;
        # the source ships the salt so injected blocks land under the same
        # identity the resume request will look them up with.
        salt = data.get("salt")
        cfg = self.engine.cfg
        if int(data.get("block_size", cfg.block_size)) != cfg.block_size:
            return {
                "ok": False,
                "error": f"block_size {data.get('block_size')} != local "
                f"{cfg.block_size}",
            }
        if kind == "blocks":
            payload = data["payload"]
            covered = await self.engine.inject_blocks(tokens, payload, salt)
            if covered == 0 and int(payload.get("n_blocks", 0)) > 0:
                # inject_blocks validated and refused (stored-representation
                # or capacity mismatch): tell the source now, not at commit.
                return {"ok": False, "error": "kv import rejected"}
            return {"ok": True, "tokens_covered": covered}
        if kind == "commit":
            # Capacity gate: the resume request must be admittable — the
            # folded prompt needs room for at least one more token, and its
            # block count must fit the pool even with zero prefix hits.
            if len(tokens) >= cfg.max_model_len:
                return {"ok": False, "error": "no room before max_model_len"}
            need = (len(tokens) + cfg.block_size) // cfg.block_size
            if need > cfg.num_blocks:
                return {"ok": False, "error": "prompt exceeds KV pool"}
            covered = 0
            payload = data.get("payload")
            # Target-side span under the stream's trace (data["trace"],
            # omit-when-absent): the commit validation + final-delta seal
            # is the target's half of the cutover pause.
            with trace_span(
                parse_trace(data.get("trace")), "migrate.in_commit",
                "migration",
            ) as mspan:
                if payload is not None:
                    covered = await self.engine.inject_blocks(
                        tokens, payload, salt
                    )
                    if covered == 0 and int(payload.get("n_blocks", 0)) > 0:
                        return {
                            "ok": False,
                            "error": "final-delta import rejected",
                        }
                metrics.migrated_in_total += 1
                prefix_hit = self.engine.estimate_prefix_hit(tokens, salt)
                mspan.set(prefix_hit=prefix_hit)
            return {
                "ok": True,
                "tokens_covered": covered,
                "prefix_hit": prefix_hit,
            }
        return {"ok": False, "error": f"unknown migrate_in kind {kind!r}"}

    # ---------------------------------------------------------- source side
    async def migrate_out_handler(self, request: Context) -> AsyncIterator[Dict]:
        data = request.data
        target = data["target"]
        rids = (
            [data["request_id"]]
            if data.get("request_id")
            else self.engine.live_request_ids()
        )
        migrated: List[str] = []
        failed: List[str] = []
        for rid in rids:
            (migrated if await self.migrate_out(rid, target) else failed).append(
                rid
            )
        yield {"ok": True, "migrated": migrated, "failed": failed}

    async def migrate_all(self, target: Dict[str, Any]) -> List[str]:
        """Drain helper: migrate every live sequence to ``target``; returns
        the ids that cut over (failures stay live on this worker)."""
        out: List[str] = []
        for rid in self.engine.live_request_ids():
            if await self.migrate_out(rid, target):
                out.append(rid)
        return out

    async def migrate_out(self, request_id: str, target: Dict[str, Any]) -> bool:
        """Drive one sequence through copy → freeze → commit → cutover.

        Returns True on cutover; False leaves the source authoritative
        (sequence unfrozen and still decoding, or already finished)."""
        engine = self.engine
        bs = engine.cfg.block_size
        metrics.started_total += 1
        cursor = 0  # complete blocks already pushed
        # Tracing (runtime/tracing.py): migration spans record under the
        # SEQUENCE's trace — the same one the client stream carries — so a
        # migrated request's timeline shows copy/freeze/cutover inline.
        seq0 = engine.find_sequence(request_id)
        tc = seq0.trace.ctx if seq0 is not None and seq0.trace else None
        cspan = trace_span(
            tc, "migrate.copy", "migration",
            attrs={"target_worker": target.get("worker_id")},
        )
        # -- phase 1: copy while decoding --------------------------------
        salt = None
        for _ in range(self.max_copy_rounds):
            tokens = engine.sequence_tokens(request_id)
            seq = engine.find_sequence(request_id)
            if tokens is None or seq is None or seq.finished:
                metrics.aborted_total += 1
                cspan.set(aborted=True).finish()
                return False  # finished/cancelled under us: nothing to move
            # Tenant sequences (llm/tenancy) seal KV under a salted hash
            # chain: export with the same salt and ship it with every
            # payload so the target seals under the identity the resume
            # request will look blocks up with.
            salt = seq.kv_salt
            try:
                shipped = await self._push_blocks(
                    target, tokens, cursor, salt, trace=tc
                )
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.warning(
                    "migration %s: copy phase failed; aborting "
                    "(source keeps the sequence)", request_id, exc_info=True,
                )
                metrics.aborted_total += 1
                cspan.set(aborted=True).finish()
                return False
            cursor += shipped
            if self.copy_round_hook is not None:
                # Copy-round barrier (tests): one refill of the gated
                # decode budget per completed round — the race becomes
                # count-bounded (decode advances at most N paced ops per
                # shipped round) instead of wall-clock raced.
                await self.copy_round_hook(cursor, False)
            remaining = len(tokens) // bs - cursor
            if remaining <= self.delta_blocks or shipped == 0:
                # shipped == 0 with blocks still remaining means nothing is
                # exportable at the cursor (sealed prefix was LRU-evicted):
                # more rounds would re-hash the whole stream for nothing —
                # go freeze; the target recomputes what never arrived as an
                # ordinary prefix miss.
                break
            await asyncio.sleep(0)  # let decode advance between rounds
        if self.copy_round_hook is not None:
            # final=True: the copy race is decided; the gate must stop
            # PARKING decode before the freeze below — quiescence needs
            # the decode loop to harvest in-flight fetches and retire the
            # row's fused-session membership.  No await sits between this
            # call returning and freeze_sequence setting ``frozen``, so
            # the un-parked loop cannot plan new chunks for the row first.
            await self.copy_round_hook(cursor, True)
        cspan.set(blocks=cursor).finish()
        # -- phase 2: freeze + final delta + commit ----------------------
        fspan = trace_span(tc, "migrate.cutover", "migration")
        seq = await engine.freeze_sequence(request_id, timeout=self.freeze_timeout)
        if seq is None:
            metrics.aborted_total += 1
            fspan.set(aborted=True).finish()
            return False
        fspan.event("frozen")
        pause_t0 = time.perf_counter()
        try:
            snap = engine.snapshot_sequence(request_id)
            if snap is None:
                raise RuntimeError("sequence vanished after freeze")
            tokens = snap.token_ids
            cursor += await self._push_blocks(
                target, tokens, cursor, salt, trace=tc
            )
            # The commit carries only what the target validates against:
            # the decode state itself rides the cutover marker (the client
            # re-dispatches snap.to_resume_request()), so shipping the
            # snapshot here would double the freeze-window payload for
            # bytes the target drops.
            resp = await self._send(
                target,
                {
                    "kind": "commit",
                    "token_ids": tokens,
                    "block_size": bs,
                    "payload": None,
                    **({"salt": salt} if salt else {}),
                    # Omit-when-absent (like salt): the target records its
                    # migrate-in span under the stream's trace.
                    **({"trace": tc.to_dict()} if tc is not None else {}),
                },
            )
            if not resp.get("ok"):
                raise MigrationTargetError(resp.get("error", "commit refused"))
        except asyncio.CancelledError:
            engine.unfreeze_sequence(request_id)
            raise
        except Exception:
            # Rollback: the source never stopped being authoritative — the
            # sequence resumes decoding exactly where it froze, and the
            # client never saw a thing.
            logger.warning(
                "migration %s: commit failed; rolled back", request_id,
                exc_info=True,
            )
            engine.unfreeze_sequence(request_id)
            metrics.rolled_back_total += 1
            fspan.set(rolled_back=True).finish()
            return False
        # -- cutover ------------------------------------------------------
        item = {
            "token_ids": [],
            "text": None,
            "finish_reason": None,
            "migrated": {
                "worker_id": target.get("worker_id"),
                "address": target.get("address"),
                "path": target.get("generate_path") or target.get("path"),
                "request": snap.to_resume_request(),
            },
        }
        engine.finish_migrated(request_id, item)
        pause_ms = (time.perf_counter() - pause_t0) * 1e3
        metrics.cutover_pause_ms.observe(pause_ms)
        metrics.completed_total += 1
        fspan.set(pause_ms=round(pause_ms, 3), blocks=cursor).finish()
        logger.info(
            "migration %s: cut over to worker %s (%d tokens, %d blocks)",
            request_id, target.get("worker_id"), len(tokens), cursor,
        )
        return True

    # ------------------------------------------------------------ transport
    async def _push_blocks(
        self,
        target: Dict[str, Any],
        tokens: List[int],
        cursor: int,
        salt: Optional[str] = None,
        trace=None,
    ) -> int:
        """Export sealed blocks from ``cursor`` and push them; returns the
        number of complete blocks shipped.  Raises on a target refusal.
        ``salt`` is the owning tenant's KV salt (llm/tenancy) — the export
        lookup and the target's sealing must both use it."""
        from ...tokens import hash_token_blocks

        bs = self.engine.cfg.block_size
        sent = 0
        # Seal the chained hashes ONCE per push round: every chunk export
        # below walks the same token list, and recomputing the O(len(tokens))
        # chain inside export_prompt_blocks per chunk made the copy phase
        # quadratic in sequence length (the export asserts the passed chain
        # against a fresh recompute under __debug__).
        chain = hash_token_blocks(tokens, bs, salt)
        while True:
            payload = await self.engine.export_prompt_blocks(
                tokens, start_block=cursor + sent, max_blocks=self.chunk_blocks,
                salt=salt, blocks=chain,
            )
            if payload is None:
                return sent
            # Ship only the tokens the chunk's chained hashes depend on
            # (block 0 through this chunk's end) — resending the full,
            # still-growing list with every push made phase-1 wire cost
            # quadratic in sequence length for zero information.
            cover = (cursor + sent + int(payload["n_blocks"])) * bs
            resp = await self._send(
                target,
                {
                    "kind": "blocks",
                    "token_ids": tokens[:cover],
                    "block_size": bs,
                    "payload": payload,
                    **({"salt": salt} if salt else {}),
                    **({"trace": trace.to_dict()} if trace is not None else {}),
                },
            )
            if not resp.get("ok"):
                raise MigrationTargetError(resp.get("error", "blocks refused"))
            n = int(payload["n_blocks"])
            # The target reports what actually SEALED: integrity
            # verification (engine/integrity.py) may have truncated the
            # import at a corrupt block, and advancing the cursor past
            # unsealed blocks would leave a hole the target's prefix match
            # can never cross.  The next round re-exports from the
            # verified frontier (a fresh HBM gather — transient wire
            # corruption heals; persistent corruption ends the copy phase
            # and the target recomputes the tail as a prefix miss).
            got = min(n, int(resp.get("tokens_covered", n * bs)) // bs)
            sent += got
            metrics.blocks_total += got
            metrics.bytes_total += len(payload.get("k", b"")) + len(
                payload.get("v", b"")
            )
            if got < n or n < self.chunk_blocks:
                return sent

    async def _send(
        self, target: Dict[str, Any], data: Dict[str, Any]
    ) -> Dict[str, Any]:
        if self.bulk is not None and data.get("kind") == "blocks":
            # Bulk plane (DYN_BULK_PLANE): the KV copy stream — the only
            # bulk-sized migrate_in payload — moves worker↔worker; commits
            # stay on the service plane (control-sized, ordering-critical).
            resp = await self._send_bulk(target, data)
            if resp is not None:
                return resp
            from ..metrics import bulk_metrics

            bulk_metrics.fallbacks_total += 1
        peer = self.direct.get(target.get("address", ""))
        if peer is not None:
            return await peer._migrate_in(data)
        client = self._client_for(target["address"], target["import_path"])
        stream = await client.generate(Context(data))
        resp: Dict[str, Any] = {"ok": False, "error": "empty migrate_in reply"}
        async for item in stream:
            resp = item
        return resp

    async def _send_bulk(
        self, target: Dict[str, Any], data: Dict[str, Any]
    ) -> Optional[Dict[str, Any]]:
        """Push one copy-stream payload over the bulk plane; None means
        'use the hub path' (peer without a bulk server, rendezvous outage,
        transfer dead after resumes) — never an error, the stream survives
        on the fallback."""
        from ...runtime.transports import codec
        from ...runtime.transports.bulk import bulk_push

        wid = target.get("worker_id")
        if wid is None:
            return None
        salt = data.get("salt")
        blob = codec.encode(data)
        try:
            prep = await self.bulk.prepare(wid, salt=salt, budget=len(blob))
            if prep is None:
                return None
            address, ticket = prep
            reply = await bulk_push(
                address, MIGRATE_IN_ENDPOINT, ticket, blob, salt=salt
            )
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — fallback ladder: hub path next
            logger.warning(
                "bulk migrate push to worker %s failed; falling back to the "
                "hub path", wid, exc_info=True,
            )
            return None
        return reply if isinstance(reply, dict) else None

    def _client_for(self, address: str, path: str) -> Client:
        key = f"{address}/{path}"
        if key not in self._clients:
            self._clients[key] = Client.static(address, path)
        return self._clients[key]


def make_migrate_in_sink(worker: MigratableWorker):
    """Target-side bulk *sink* for ``MIGRATE_IN_ENDPOINT``: the blob is the
    codec-encoded migrate_in data dict; the reply is ``_migrate_in``'s
    verdict (ok / tokens_covered), which the source consumes exactly as it
    would a service-plane response."""
    from ...runtime.transports import codec

    async def sink(blob: bytes, meta: Dict[str, Any]) -> Dict[str, Any]:
        return await worker._migrate_in(codec.decode(blob))

    return sink
