"""SequenceSnapshot: the serializable decode-state checkpoint of a live
sequence — everything besides the KV pages needed to continue the stream
token-identically on another worker.

The KV pages travel separately over the hash-addressed transfer plane
(engine/transfer.py export/inject); the snapshot is the small control-plane
record: fed tokens, resolved sampler state (seed + rng-stream position via
``orig_prompt_len``), stop conditions, speculative-decoding controller
state, the request's remaining deadline, and — when a detokenizing edge
migrates its own state rather than keeping the stream spliced below it —
the incremental-detok/stop-jail state (llm/backend.py ``Decoder.state_dict``).

``to_resume_request()`` turns a snapshot into an ordinary
PreprocessedRequest wire dict: the target engine needs NO special admission
path — the folded prompt admits against the transferred blocks as a prefix
hit, and the ``resume`` annotation restores the rng-stream position so the
continued sample stream is byte-identical to the never-migrated run (the
engine's seeded sampler keys on (seed, output-index), both preserved).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

SNAPSHOT_VERSION = 1


@dataclass
class SequenceSnapshot:
    request_id: str
    # Full fed-token stream at snapshot time: original prompt + every
    # generated token (the hash-addressed identity KV blocks seal under).
    token_ids: List[int]
    # Length of the ORIGINAL prompt: generated-token accounting (sampler
    # rng steps, max/min_tokens, usage, penalties) counts from here.
    orig_prompt_len: int
    # Resolved sampling state (engine defaults applied — notably the seed,
    # so resume does not depend on the target engine's own seed).
    sampling: Dict[str, Any] = field(default_factory=dict)
    # Stop conditions as the source engine held them.
    stop: Dict[str, Any] = field(default_factory=dict)
    # Speculative-decoding controller state (engine/spec.py): acceptance
    # history is a property of the traffic and travels with the sequence.
    spec: Dict[str, Any] = field(default_factory=dict)
    # Remaining wall-clock budget at snapshot time (informational: the
    # routed client's own Deadline stays authoritative across the splice).
    deadline_s: Optional[float] = None
    # Incremental detokenizer + stop-string jail state (llm/backend.py).
    # None when the edge keeps its Decoder alive across the splice (the
    # normal routed-client path — token ids below the Backend operator are
    # what migrate, so edge detok state never moves).
    detok: Optional[Dict[str, Any]] = None
    # Tenant identity (llm/tenancy): the LoRA adapter serving this
    # sequence and the KV salt its blocks seal under — the target must
    # resume under the SAME adapter (correct forward) and salt
    # (addressable KV), or the stream silently changes tenants.
    adapter: Optional[str] = None
    kv_salt: Optional[str] = None
    # QoS identity (llm/qos.py): the fairness tenant and priority class the
    # source scheduled under — the target must resume in the SAME class
    # and fairness flow, or a migration would silently launder a batch row
    # into the protected interactive band (and vice versa).
    tenant: Optional[str] = None
    priority: Optional[str] = None
    # Structured-output constraint: the serialized TokenMaskAutomaton.
    # The automaton STATE does not travel — the target re-derives it by
    # advancing from the start state through the resumed output tokens
    # (every delivered token was mask-admissible, so the walk cannot
    # fail on an honest snapshot).
    grammar: Optional[Dict[str, Any]] = None
    # Distributed-tracing context (runtime/tracing.py TraceContext wire
    # dict): a migrated stream must stay ONE trace, so the target resumes
    # recording spans under the SAME trace_id the source served.  Omitted
    # for untraced sequences (the overwhelmingly common case).
    trace: Optional[Dict[str, Any]] = None
    version: int = SNAPSHOT_VERSION

    @property
    def emitted(self) -> int:
        """Generated tokens already delivered to the stream."""
        return len(self.token_ids) - self.orig_prompt_len

    def to_dict(self) -> Dict[str, Any]:
        # Optional fields ship omit-when-absent (from_dict tolerates the
        # missing keys): base traffic's snapshots keep the pre-tenancy wire
        # shape, and consumers that predate a field never see it — the
        # same wire-compat contract as PreprocessedRequest.grammar
        # (dynalint DYN302 enforces it for every new optional field).
        out = {
            "version": self.version,
            "request_id": self.request_id,
            "token_ids": list(self.token_ids),
            "orig_prompt_len": self.orig_prompt_len,
            "sampling": dict(self.sampling),
            "stop": dict(self.stop),
            "spec": dict(self.spec),
        }
        if self.deadline_s is not None:
            out["deadline_s"] = self.deadline_s
        if self.detok is not None:
            out["detok"] = self.detok
        if self.adapter is not None:
            out["adapter"] = self.adapter
        if self.kv_salt is not None:
            out["kv_salt"] = self.kv_salt
        if self.tenant is not None:
            out["tenant"] = self.tenant
        if self.priority is not None:
            out["priority"] = self.priority
        if self.grammar is not None:
            out["grammar"] = self.grammar
        if self.trace is not None:
            out["trace"] = self.trace
        return out

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SequenceSnapshot":
        return cls(
            request_id=d["request_id"],
            token_ids=list(d["token_ids"]),
            orig_prompt_len=int(d["orig_prompt_len"]),
            sampling=dict(d.get("sampling") or {}),
            stop=dict(d.get("stop") or {}),
            spec=dict(d.get("spec") or {}),
            deadline_s=d.get("deadline_s"),
            detok=d.get("detok"),
            adapter=d.get("adapter"),
            kv_salt=d.get("kv_salt"),
            tenant=d.get("tenant"),
            priority=d.get("priority"),
            grammar=d.get("grammar"),
            trace=d.get("trace"),
            version=int(d.get("version", SNAPSHOT_VERSION)),
        )

    def to_resume_request(self) -> Dict[str, Any]:
        """PreprocessedRequest wire dict that continues this stream.

        Dispatched by the routed client after the ``migrated`` splice (or
        rebuilt client-side for seeded crash recovery); the target engine's
        ``SequenceState.from_request`` honours the ``resume`` annotation.
        """
        samp = self.sampling
        return {
            "token_ids": list(self.token_ids),
            "sampling_options": {
                "temperature": samp.get("temperature"),
                "top_p": samp.get("top_p"),
                "top_k": samp.get("top_k"),
                "frequency_penalty": samp.get("frequency_penalty"),
                "presence_penalty": samp.get("presence_penalty"),
                # The RESOLVED seed: exact-stream resume must not depend on
                # the target re-deriving an engine-default seed.
                "seed": samp.get("seed"),
                "logprobs": samp.get("logprobs"),
                "spec_decode": samp.get("spec_decode"),
            },
            "stop_conditions": {
                "max_tokens": self.stop.get("max_tokens"),
                "min_tokens": self.stop.get("min_tokens"),
                "stop_token_ids": list(self.stop.get("stop_token_ids") or []),
                "ignore_eos": bool(self.stop.get("ignore_eos", False)),
            },
            "model": None,
            "annotations": {
                "resume": {
                    "orig_prompt_len": self.orig_prompt_len,
                    "spec": dict(self.spec),
                },
                # Tenant identity (llm/tenancy): adapter + salt resume on
                # the target exactly as the source served them.  Keys are
                # omitted for base traffic so pre-tenancy consumers see
                # the old annotation shape.
                **({"adapter": self.adapter} if self.adapter else {}),
                **({"kv_salt": self.kv_salt} if self.kv_salt else {}),
                # QoS fairness flow (llm/qos.py; omitted when default).
                **({"tenant": self.tenant} if self.tenant else {}),
                # Tracing continuity (runtime/tracing.py): the target's
                # engine parses annotations.trace, so the resumed stream's
                # spans join the original trace.
                **({"trace": dict(self.trace)} if self.trace else {}),
            },
            **({"grammar": dict(self.grammar)} if self.grammar else {}),
            **({"priority": self.priority} if self.priority else {}),
        }
