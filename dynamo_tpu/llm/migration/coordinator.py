"""Migration coordination: target discovery + drain-via-migration.

The planner (or an operator, or the hub-native supervisor) decides a worker
should shrink away or flip roles; this module turns that decision into a
cheap action.  ``pick_migration_target`` reads the endpoint's instance
registrations from the hub and returns a peer that advertises the
``migrate`` capability in its metadata (cli worker mode writes it);
``drain_via_migration`` moves every live sequence there, falling back to
the classic wait-out drain only when no peer exists.

Scale-down cost therefore becomes O(KV transfer) instead of O(longest
sequence) — the Llumnix argument — and the planner's actuation latency is
bounded by the control loop again.

``request_migrate_out`` is the remote flavour: given a source worker's
instance record it invokes that worker's ``migrate_out`` control endpoint
over the service plane (used by the supervisor before stopping a process
it does not share memory with).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Dict, List, Optional

from ...runtime.client import Client
from ...runtime.engine import Context

logger = logging.getLogger(__name__)


def target_from_instance(info: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Build a migrate-out target record from an instance registration.

    Requires the instance to advertise ``metadata.migrate`` (import/control
    paths) — workers without the migration endpoints cannot receive."""
    meta = info.get("metadata") or {}
    mig = meta.get("migrate")
    if not isinstance(mig, dict) or not info.get("address"):
        return None
    return {
        "worker_id": info.get("worker_id"),
        "address": info["address"],
        "import_path": mig.get("import_path"),
        "generate_path": mig.get("generate_path") or info.get("path"),
        "out_path": mig.get("out_path"),
    }


async def pick_migration_target(
    hub,
    instance_prefix: str,
    self_worker_id: int,
    exclude: frozenset = frozenset(),
) -> Optional[Dict[str, Any]]:
    """A live migration-capable peer under ``instance_prefix`` (lowest
    worker id wins — deterministic, so concurrent drains converge on the
    same receiver and its prefix cache warms fastest).

    Draining workers de-advertise ``metadata.migrate`` before calling this
    (cli WorkerRoles.stop_decode), so concurrent drains do not pick each
    other.  A hub snapshot read before a peer's de-advertise propagates
    can still name it — but the capability is RE-CHECKED AT ACCEPT TIME:
    a draining target's ``MigratableWorker.accepting`` gate refuses the
    migrate-in, the migration aborts or rolls back harmlessly (the source
    stays authoritative), and the next round picks a live receiver."""
    try:
        snapshot = await hub.kv_get_prefix(instance_prefix)
    except asyncio.CancelledError:
        raise
    except Exception:  # noqa: BLE001 — hub unreachable: no target, not fatal
        logger.warning("migration target discovery failed", exc_info=True)
        return None
    candidates: List[Dict[str, Any]] = []
    for info in snapshot.values():
        if not isinstance(info, dict):
            continue
        wid = info.get("worker_id")
        if wid == self_worker_id or wid in exclude:
            continue
        target = target_from_instance(info)
        if target is not None and target.get("import_path"):
            candidates.append(target)
    if not candidates:
        return None
    return min(candidates, key=lambda t: t.get("worker_id") or 0)


async def drain_via_migration(
    worker,
    hub,
    instance_prefix: str,
    self_worker_id: int,
) -> List[str]:
    """Move every live sequence off ``worker`` (a MigratableWorker) onto a
    discovered peer.  Returns the migrated request ids; sequences that
    could not move (no peer, rollback) stay live — the caller's ordinary
    drain covers them, so nothing is ever dropped."""
    target = await pick_migration_target(hub, instance_prefix, self_worker_id)
    if target is None:
        logger.info("drain: no migration-capable peer; falling back to wait-out")
        return []
    moved = await worker.migrate_all(target)
    logger.info(
        "drain: migrated %d sequence(s) to worker %s",
        len(moved), target.get("worker_id"),
    )
    return moved


async def request_migrate_out(
    info: Dict[str, Any],
    target: Dict[str, Any],
    request_id: Optional[str] = None,
) -> Dict[str, Any]:
    """Invoke a remote worker's ``migrate_out`` control endpoint (its
    instance record must advertise ``metadata.migrate.out_path``)."""
    src = target_from_instance(info)
    if src is None or not src.get("out_path"):
        return {"ok": False, "error": "source is not migration-capable"}
    client = Client.static(info["address"], src["out_path"])
    stream = await client.generate(
        Context({"request_id": request_id, "target": target})
    )
    resp: Dict[str, Any] = {"ok": False, "error": "empty migrate_out reply"}
    async for item in stream:
        resp = item
    return resp
