"""Live sequence migration: preemption-free KV + decode-state handoff.

Reference motivation: Llumnix (OSDI'24) — live cross-instance migration is
the primitive that turns rescheduling decisions (scale-down, role flips,
defragmentation, crash recovery) into cheap actions.  The KV plane reuses
engine/transfer.py's hash-addressed export/inject; the decode state rides a
``SequenceSnapshot``; the stream splice is the routed client's job
(runtime/client.py consumes the ``migrated`` marker and re-dispatches).

See docs/migration.md for the protocol and failure matrix.
"""

from .coordinator import (
    drain_via_migration,
    pick_migration_target,
    request_migrate_out,
    target_from_instance,
)
from .snapshot import SequenceSnapshot
from .worker import (
    MIGRATE_IN_ENDPOINT,
    MIGRATE_OUT_ENDPOINT,
    MigratableWorker,
    MigrationTargetError,
)

__all__ = [
    "SequenceSnapshot",
    "MigratableWorker",
    "MigrationTargetError",
    "MIGRATE_IN_ENDPOINT",
    "MIGRATE_OUT_ENDPOINT",
    "pick_migration_target",
    "target_from_instance",
    "drain_via_migration",
    "request_migrate_out",
]
