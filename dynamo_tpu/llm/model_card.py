"""ModelDeploymentCard: serving metadata published on the control plane.

Reference semantics: lib/llm/src/model_card/model.rs:15-201 + create.rs —
a card describes everything a frontend needs to serve a model (display
name, tokenizer, prompt format, context length) without touching weights;
cards live in shared storage under a TTL and are refreshed by the owning
worker (NATS object store bucket ``mdc`` there; hub KV under the worker's
lease here — same liveness semantics, one less storage system).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ..runtime.transports.shard import hub_key

MDC_PREFIX = "mdc/"


def mdc_key(name: str) -> str:
    """Deployment-card key for one model name (shard-map routed: DYN401)."""
    return hub_key("mdc", name)


@dataclass
class ModelDeploymentCard:
    name: str
    model_type: str = "chat"  # chat | completion | both
    context_length: int = 8192
    kv_block_size: int = 16
    tokenizer: Dict[str, Any] = field(default_factory=lambda: {"kind": "byte"})
    prompt_template: Optional[str] = None  # chat template (jinja text)
    architecture: Optional[str] = None  # config name (models/config.py)
    revision: int = 0
    extra: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "model_type": self.model_type,
            "context_length": self.context_length,
            "kv_block_size": self.kv_block_size,
            "tokenizer": self.tokenizer,
            "prompt_template": self.prompt_template,
            "architecture": self.architecture,
            "revision": self.revision,
            "extra": self.extra,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ModelDeploymentCard":
        return cls(**{k: d.get(k, getattr(cls, k, None)) for k in (
            "name", "model_type", "context_length", "kv_block_size",
            "tokenizer", "prompt_template", "architecture", "revision",
        )}, extra=d.get("extra") or {})

    @classmethod
    def from_local_path(cls, path: str, name: Optional[str] = None) -> "ModelDeploymentCard":
        """Build a card from a HF model directory (config.json + tokenizer)."""
        card = cls(name=name or os.path.basename(path.rstrip("/")))
        cfg_path = os.path.join(path, "config.json")
        if os.path.exists(cfg_path):
            with open(cfg_path) as fh:
                cfg = json.load(fh)
            card.context_length = cfg.get("max_position_embeddings", card.context_length)
            card.architecture = path
        tok = os.path.join(path, "tokenizer.json")
        if os.path.exists(tok):
            card.tokenizer = {"kind": "hf", "file": tok}
        tpl = os.path.join(path, "tokenizer_config.json")
        if os.path.exists(tpl):
            with open(tpl) as fh:
                tc = json.load(fh)
            if tc.get("chat_template"):
                card.prompt_template = tc["chat_template"]
        return card

    @classmethod
    def for_adapter(
        cls, base: "ModelDeploymentCard", adapter: str
    ) -> "ModelDeploymentCard":
        """Card for a LoRA adapter served as its own model name
        (llm/tenancy): everything a frontend needs is the BASE model's
        (tokenizer, template, context length) — the card only differs in
        name and in ``extra["lora"]`` recording the adapter→base link."""
        card = cls.from_dict(base.to_dict())
        card.name = adapter
        card.extra = dict(base.extra)
        card.extra["lora"] = {"adapter": adapter, "base": base.name}
        return card

    # ------------------------------------------------------------- publishing
    def key(self) -> str:
        return mdc_key(self.name)

    async def publish(self, runtime) -> None:
        """Register under the worker's primary lease (auto-refresh + removal
        on worker death via the runtime's lease monitor)."""
        await runtime.register_key(self.key(), self.to_dict())

    @classmethod
    async def load(cls, runtime, name: str) -> Optional["ModelDeploymentCard"]:
        data = await runtime.hub.kv_get(mdc_key(name))
        return cls.from_dict(data) if data else None

    @classmethod
    async def list_all(cls, runtime) -> Dict[str, "ModelDeploymentCard"]:
        kvs = await runtime.hub.kv_get_prefix(MDC_PREFIX)
        return {
            key[len(MDC_PREFIX):]: cls.from_dict(value)
            for key, value in kvs.items()
        }
