"""LLM serving library (reference: lib/llm/)."""

from .backend import Backend, Decoder
from .discovery import ModelWatcher, make_tokenizer, register_model
from .http_service import HttpService, ModelManager
from .engines import EchoEngineCore, EchoEngineFull
from .openai import (
    ChatCompletionRequest,
    CompletionRequest,
    DeltaGenerator,
    aggregate_chunks,
    sse_encode,
)
from .preprocessor import OpenAIPreprocessor
from .protocols import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from .tokenizer import BaseTokenizer, ByteTokenizer, DecodeStream, HFTokenizer

__all__ = [
    "Backend",
    "Decoder",
    "ModelWatcher",
    "make_tokenizer",
    "register_model",
    "HttpService",
    "ModelManager",
    "EchoEngineCore",
    "EchoEngineFull",
    "ChatCompletionRequest",
    "CompletionRequest",
    "DeltaGenerator",
    "aggregate_chunks",
    "sse_encode",
    "OpenAIPreprocessor",
    "FinishReason",
    "LLMEngineOutput",
    "PreprocessedRequest",
    "SamplingOptions",
    "StopConditions",
    "BaseTokenizer",
    "ByteTokenizer",
    "DecodeStream",
    "HFTokenizer",
]
