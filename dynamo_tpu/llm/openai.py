"""OpenAI-compatible API types, delta generation, and aggregation.

Reference semantics: lib/llm/src/protocols/openai/** — chat-completions and
completions request types (with the ``nvext`` extension: ignore_eos,
annotations, use_raw_prompt), the ``DeltaGenerator`` that shapes per-token
engine outputs into ``chat.completion.chunk`` SSE objects, and the stream→full
aggregators used for ``stream=false`` responses.

Requests are validated with pydantic; chunks are plain dicts (hot path).
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Literal, Optional, Union

from pydantic import BaseModel, ConfigDict, Field

from .protocols import SamplingOptions, StopConditions


class NvExt(BaseModel):
    """Extension fields (reference nvext): engine hints + debug annotations."""

    model_config = ConfigDict(extra="allow")
    ignore_eos: Optional[bool] = None
    use_raw_prompt: Optional[bool] = None
    annotations: Optional[List[str]] = None
    greed_sampling: Optional[bool] = None
    # Per-request speculative-decoding opt-out (false disables the engine's
    # draft-free speculation for this request; tokens are identical either
    # way — the knob shapes latency granularity and enables A/B runs).
    spec_decode: Optional[bool] = None
    # Structured-output constraint (llm/tenancy/grammar.py): a regex string
    # (restricted syntax) or a JSON-schema dict.  Wins over the standard
    # ``response_format`` field when both are set.
    grammar: Optional[Union[str, Dict[str, Any]]] = None
    # QoS (llm/qos.py): priority class ("interactive" | "batch"; the
    # x-priority header wins at the edge) and an explicit tenant identity
    # override for quota/fairness accounting (default: API key / model).
    priority: Optional[str] = None
    tenant: Optional[str] = None


class ChatMessage(BaseModel):
    model_config = ConfigDict(extra="allow")
    role: str
    content: Optional[Union[str, List[Dict[str, Any]]]] = None
    name: Optional[str] = None

    def text(self) -> str:
        if isinstance(self.content, list):
            return "".join(
                part.get("text", "") for part in self.content if part.get("type") == "text"
            )
        return self.content or ""


class CommonFields(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    stream: bool = False
    max_tokens: Optional[int] = None
    max_completion_tokens: Optional[int] = None
    min_tokens: Optional[int] = None
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    seed: Optional[int] = None
    stop: Optional[Union[str, List[str]]] = None
    n: int = 1
    nvext: Optional[NvExt] = None
    # Structured output (OpenAI shape): {"type": "text" | "json_object" |
    # "json_schema", "json_schema": {"name": ..., "schema": {...}}}.
    # Compiled to a token-mask automaton at the preprocessor
    # (llm/tenancy/grammar.py) and enforced as a per-row logit mask.
    response_format: Optional[Dict[str, Any]] = None

    def stop_conditions(self) -> StopConditions:
        stop = self.stop
        if isinstance(stop, str):
            stop = [stop]
        return StopConditions(
            max_tokens=self.max_tokens or self.max_completion_tokens,
            min_tokens=self.min_tokens,
            stop=list(stop or []),
            ignore_eos=bool(self.nvext and self.nvext.ignore_eos),
        )

    def sampling_options(self) -> SamplingOptions:
        return SamplingOptions(
            temperature=self.temperature,
            top_p=self.top_p,
            top_k=self.top_k,
            frequency_penalty=self.frequency_penalty,
            presence_penalty=self.presence_penalty,
            seed=self.seed,
            spec_decode=self.nvext.spec_decode if self.nvext else None,
        )


class ChatCompletionRequest(CommonFields):
    messages: List[ChatMessage]
    logprobs: Optional[bool] = None
    top_logprobs: Optional[int] = None
    tools: Optional[List[Dict[str, Any]]] = None
    stream_options: Optional[Dict[str, Any]] = None

    def sampling_options(self) -> SamplingOptions:
        opts = super().sampling_options()
        if self.top_logprobs is not None and not 0 <= self.top_logprobs <= 20:
            # OpenAI's documented range; the sampler computes exactly this
            # many alternatives (ops/sampling.py TOPK_LOGPROBS), so anything
            # larger must be rejected, not silently clamped.
            raise ValueError("top_logprobs must be between 0 and 20")
        if self.logprobs:
            opts.logprobs = self.top_logprobs or 0
        return opts


class CompletionRequest(CommonFields):
    prompt: Union[str, List[str], List[int], List[List[int]]]
    echo: Optional[bool] = None
    logprobs: Optional[int] = None
    stream_options: Optional[Dict[str, Any]] = None

    def sampling_options(self) -> SamplingOptions:
        opts = super().sampling_options()
        if self.logprobs is not None:
            if not 0 <= self.logprobs <= 20:
                raise ValueError("logprobs must be between 0 and 20")
            opts.logprobs = self.logprobs
        return opts


def _now() -> int:
    return int(time.time())


class DeltaGenerator:
    """Shapes backend text deltas into OpenAI streaming chunks.

    Reference: protocols/openai/chat_completions/delta.rs — one object per
    request, stamps a stable completion id/created, emits the role on the
    first chunk, finish_reason on the last, optional usage chunk.
    """

    def __init__(
        self,
        model: str,
        chat: bool = True,
        request_id: Optional[str] = None,
        index: int = 0,
    ):
        self.chat = chat
        self.model = model
        self.id = ("chatcmpl-" if chat else "cmpl-") + (request_id or uuid.uuid4().hex)
        self.created = _now()
        self.object = "chat.completion.chunk" if chat else "text_completion"
        self.index = index  # choice index (n > 1 fan-out)
        self._first = True

    def _base(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "object": self.object,
            "created": self.created,
            "model": self.model,
        }

    def text_chunk(
        self, text: str, logprobs: Optional[Dict[str, Any]] = None
    ) -> Dict[str, Any]:
        out = self._base()
        if self.chat:
            delta: Dict[str, Any] = {"content": text}
            if self._first:
                delta["role"] = "assistant"
                self._first = False
            choice: Dict[str, Any] = {
                "index": self.index, "delta": delta, "finish_reason": None
            }
            if logprobs is not None:
                choice["logprobs"] = {
                    "content": [
                        {
                            "token": logprobs["token"],
                            "logprob": logprobs["logprob"],
                            "top_logprobs": logprobs.get("top", []),
                        }
                    ]
                }
            out["choices"] = [choice]
        else:
            choice = {"index": self.index, "text": text, "finish_reason": None}
            if logprobs is not None:
                choice["logprobs"] = {
                    "tokens": [logprobs["token"]],
                    "token_logprobs": [logprobs["logprob"]],
                    "top_logprobs": [
                        {t["token"]: t["logprob"] for t in logprobs.get("top", [])}
                    ],
                }
            out["choices"] = [choice]
        return out

    def finish_chunk(self, finish_reason: str) -> Dict[str, Any]:
        out = self._base()
        if self.chat:
            out["choices"] = [{"index": self.index, "delta": {}, "finish_reason": finish_reason}]
        else:
            out["choices"] = [{"index": self.index, "text": "", "finish_reason": finish_reason}]
        return out

    def usage_chunk(self, usage: Dict[str, int]) -> Dict[str, Any]:
        out = self._base()
        out["choices"] = []
        out["usage"] = usage
        return out


def aggregate_chunks(chunks: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold a chunk stream into a full (non-streaming) response.

    Reference: protocols/openai/chat_completions/aggregator.rs — used at the
    HTTP edge for ``stream=false`` (everything downstream always streams).
    """
    if not chunks:
        raise ValueError("empty stream")
    first = chunks[0]
    chat = first.get("object") == "chat.completion.chunk"

    class _Acc:
        def __init__(self):
            self.text: List[str] = []
            self.finish: Optional[str] = None
            self.role = "assistant"
            self.lp_content: List[Dict[str, Any]] = []  # chat logprobs
            self.lp_tokens: List[str] = []  # completions logprobs
            self.lp_vals: List[float] = []
            self.lp_top: List[Dict[str, float]] = []

    accs: Dict[int, _Acc] = {}
    usage: Optional[Dict[str, int]] = None
    for ch in chunks:
        if ch.get("usage"):
            u = ch["usage"]
            if usage is None:
                usage = dict(u)
            else:  # n > 1: completions sum, the shared prompt counts once
                usage["completion_tokens"] = usage.get(
                    "completion_tokens", 0
                ) + u.get("completion_tokens", 0)
                usage["total_tokens"] = (
                    usage.get("prompt_tokens", 0) + usage["completion_tokens"]
                )
        for choice in ch.get("choices", []):
            acc = accs.setdefault(int(choice.get("index", 0)), _Acc())
            lp = choice.get("logprobs")
            if chat:
                delta = choice.get("delta", {})
                if delta.get("role"):
                    acc.role = delta["role"]
                if delta.get("content"):
                    acc.text.append(delta["content"])
                if lp and lp.get("content"):
                    acc.lp_content.extend(lp["content"])
            else:
                if choice.get("text"):
                    acc.text.append(choice["text"])
                if lp:
                    acc.lp_tokens.extend(lp.get("tokens", []))
                    acc.lp_vals.extend(lp.get("token_logprobs", []))
                    acc.lp_top.extend(lp.get("top_logprobs", []))
            if choice.get("finish_reason"):
                acc.finish = choice["finish_reason"]
    out = {
        "id": first["id"],
        "object": "chat.completion" if chat else "text_completion",
        "created": first["created"],
        "model": first["model"],
    }
    choices = []
    for idx in sorted(accs) or [0]:
        acc = accs.get(idx, _Acc())
        full_text = "".join(acc.text)
        if chat:
            c: Dict[str, Any] = {
                "index": idx,
                "message": {"role": acc.role, "content": full_text},
                "finish_reason": acc.finish,
            }
            if acc.lp_content:
                c["logprobs"] = {"content": acc.lp_content}
        else:
            c = {"index": idx, "text": full_text, "finish_reason": acc.finish}
            if acc.lp_tokens:
                c["logprobs"] = {
                    "tokens": acc.lp_tokens,
                    "token_logprobs": acc.lp_vals,
                    "top_logprobs": acc.lp_top,
                }
        choices.append(c)
    out["choices"] = choices
    if usage is not None:
        out["usage"] = usage
    return out


def sse_encode(data: Any) -> bytes:
    """One SSE event (reference codec.rs)."""
    import json

    return b"data: " + json.dumps(data, separators=(",", ":")).encode() + b"\n\n"


SSE_DONE = b"data: [DONE]\n\n"
