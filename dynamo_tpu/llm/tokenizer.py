"""Tokenizers + incremental detokenization.

Reference semantics: lib/llm/src/tokenizers.rs (Encoding, HF/sentencepiece
backends, incremental ``DecodeStream``) and the preprocessor's prompt
templating (lib/llm/src/preprocessor/prompt/).

Two implementations:
- ``HFTokenizer`` — wraps a ``tokenizers.Tokenizer`` json file (the HF format
  every target model ships) + a jinja2 chat template from
  tokenizer_config.json.
- ``ByteTokenizer`` — fully self-contained byte-level tokenizer (ids 0-255 are
  raw bytes + special tokens above).  Used for tests, echo serving, and
  synthetic benchmarks: no model files required anywhere in the stack.

``DecodeStream`` performs incremental detokenization by decoding a sliding
window of accumulated ids and diffing against the previously emitted prefix,
holding back trailing bytes that form an incomplete UTF-8 sequence — same
behaviour as the reference's DecodeStream (tokenizers.rs) where a multi-token
unicode glyph must not be emitted until complete.
"""

from __future__ import annotations

import json
import os
from abc import ABC, abstractmethod
from typing import Any, Dict, List, Optional, Sequence


class BaseTokenizer(ABC):
    """Minimal tokenizer interface used by the preprocessor and backend."""

    @abstractmethod
    def encode(self, text: str, add_special_tokens: bool = True) -> List[int]:
        ...

    @abstractmethod
    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        ...

    @property
    @abstractmethod
    def eos_token_id(self) -> Optional[int]:
        ...

    @property
    @abstractmethod
    def bos_token_id(self) -> Optional[int]:
        ...

    @property
    @abstractmethod
    def vocab_size(self) -> int:
        ...

    # -- chat templating ----------------------------------------------------

    @property
    def chat_template(self) -> Optional[str]:
        return None

    def apply_chat_template(
        self,
        messages: List[Dict[str, Any]],
        add_generation_prompt: bool = True,
        **kwargs: Any,
    ) -> str:
        """Render messages to a prompt string (reference: minijinja templates,
        lib/llm/src/preprocessor/prompt/template/)."""
        template = self.chat_template
        if template is None:
            # simple role-tagged fallback (mirrors no-template GGUF models)
            parts = [f"<|{m['role']}|>\n{m.get('content') or ''}" for m in messages]
            if add_generation_prompt:
                parts.append("<|assistant|>\n")
            return "\n".join(parts)
        import jinja2

        env = jinja2.Environment(trim_blocks=True, lstrip_blocks=True)
        env.globals["raise_exception"] = _raise_exception
        return env.from_string(template).render(
            messages=messages,
            add_generation_prompt=add_generation_prompt,
            bos_token=getattr(self, "bos_token", "") or "",
            eos_token=getattr(self, "eos_token", "") or "",
            **kwargs,
        )

    def decode_stream(self, skip_special_tokens: bool = True) -> "DecodeStream":
        return DecodeStream(self, skip_special_tokens=skip_special_tokens)


def _raise_exception(message: str) -> None:
    raise ValueError(message)


class HFTokenizer(BaseTokenizer):
    """HuggingFace ``tokenizer.json`` backend (+ chat template/config)."""

    def __init__(
        self,
        tokenizer_file: Optional[str] = None,
        config_file: Optional[str] = None,
        *,
        tokenizer: Optional[Any] = None,  # in-memory tokenizers.Tokenizer
        bos_token_id: Optional[int] = None,
        eos_token_id: Optional[int] = None,
    ):
        from tokenizers import Tokenizer

        if tokenizer is not None:
            self._tok = tokenizer  # e.g. built from GGUF metadata
        elif tokenizer_file is not None:
            self._tok = Tokenizer.from_file(tokenizer_file)
        else:
            raise ValueError("need tokenizer_file or tokenizer")
        self._chat_template: Optional[str] = None
        self.bos_token: Optional[str] = None
        self.eos_token: Optional[str] = None
        self._bos_id: Optional[int] = None
        self._eos_id: Optional[int] = None

        if config_file is None and tokenizer_file is not None:
            candidate = os.path.join(os.path.dirname(tokenizer_file), "tokenizer_config.json")
            config_file = candidate if os.path.exists(candidate) else None
        if config_file is not None:
            with open(config_file) as f:
                cfg = json.load(f)
            self._chat_template = cfg.get("chat_template")
            self.bos_token = _token_str(cfg.get("bos_token"))
            self.eos_token = _token_str(cfg.get("eos_token"))
        if self.bos_token:
            self._bos_id = self._tok.token_to_id(self.bos_token)
        if self.eos_token:
            self._eos_id = self._tok.token_to_id(self.eos_token)
        if bos_token_id is not None:
            self._bos_id = int(bos_token_id)
        if eos_token_id is not None:
            self._eos_id = int(eos_token_id)

    @classmethod
    def from_pretrained_dir(cls, model_dir: str) -> "HFTokenizer":
        return cls(os.path.join(model_dir, "tokenizer.json"))

    def encode(self, text: str, add_special_tokens: bool = True) -> List[int]:
        return self._tok.encode(text, add_special_tokens=add_special_tokens).ids

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=skip_special_tokens)

    @property
    def eos_token_id(self) -> Optional[int]:
        return self._eos_id

    @property
    def bos_token_id(self) -> Optional[int]:
        return self._bos_id

    @property
    def vocab_size(self) -> int:
        return self._tok.get_vocab_size()

    @property
    def chat_template(self) -> Optional[str]:
        return self._chat_template


class SentencePieceTokenizer(BaseTokenizer):
    """``tokenizer.model``-only checkpoints (older Llama/Mistral) served
    natively via the vendored sentencepiece runtime (llm/sp.py; reference:
    lib/llm/src/tokenizers/sp.rs).  Chat template / special tokens come
    from a sibling tokenizer_config.json when present."""

    def __init__(self, model_file: str, config_file: Optional[str] = None):
        from .sp import SentencePieceModel

        self._sp = SentencePieceModel.from_file(model_file)
        self._chat_template: Optional[str] = None
        self.bos_token: Optional[str] = None
        self.eos_token: Optional[str] = None
        if config_file is None:
            candidate = os.path.join(
                os.path.dirname(model_file), "tokenizer_config.json"
            )
            config_file = candidate if os.path.exists(candidate) else None
        if config_file is not None:
            with open(config_file) as f:
                cfg = json.load(f)
            self._chat_template = cfg.get("chat_template")
            self.bos_token = _token_str(cfg.get("bos_token"))
            self.eos_token = _token_str(cfg.get("eos_token"))

    def encode(self, text: str, add_special_tokens: bool = True) -> List[int]:
        ids = self._sp.encode(text)
        if add_special_tokens and self._sp.bos_id >= 0:
            ids = [self._sp.bos_id] + ids
        return ids

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        # sp.decode always drops CONTROL/UNKNOWN pieces (sentencepiece
        # semantics); NORMAL pieces are never "special".
        return self._sp.decode(list(ids))

    def decode_window(
        self, ids: Sequence[int], skip_special_tokens: bool = True,
        *, sequence_start: bool = True,
    ) -> str:
        """Window decode for incremental detokenization: a window that does
        not begin the sequence keeps its leading ▁-space so prefix-diff
        deltas preserve inter-token spaces (DecodeStream)."""
        return self._sp.decode(list(ids), sequence_start=sequence_start)

    @property
    def eos_token_id(self) -> Optional[int]:
        return self._sp.eos_id if self._sp.eos_id >= 0 else None

    @property
    def bos_token_id(self) -> Optional[int]:
        return self._sp.bos_id if self._sp.bos_id >= 0 else None

    @property
    def vocab_size(self) -> int:
        return self._sp.vocab_size

    @property
    def chat_template(self) -> Optional[str]:
        return self._chat_template


def _token_str(value: Any) -> Optional[str]:
    """tokenizer_config tokens are either "..." or {"content": "..."}."""
    if isinstance(value, dict):
        return value.get("content")
    return value


class ByteTokenizer(BaseTokenizer):
    """Self-contained byte-level tokenizer: ids 0-255 = bytes, then specials.

    Deterministic, lossless, zero files.  Specials: BOS=256, EOS=257, PAD=258,
    then one id per extra special token (e.g. role markers).
    """

    BOS = 256
    EOS = 257
    PAD = 258

    def __init__(self, extra_specials: Optional[List[str]] = None):
        self._specials: Dict[str, int] = {"<bos>": self.BOS, "<eos>": self.EOS, "<pad>": self.PAD}
        for i, tok in enumerate(extra_specials or []):
            self._specials[tok] = 259 + i
        self._special_by_id = {v: k for k, v in self._specials.items()}
        self.bos_token = "<bos>"
        self.eos_token = "<eos>"

    def encode(self, text: str, add_special_tokens: bool = True) -> List[int]:
        ids = list(text.encode("utf-8"))
        if add_special_tokens:
            ids = [self.BOS] + ids
        return ids

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        out: List[str] = []
        buf = bytearray()
        for i in ids:
            if i < 256:
                buf.append(i)
            else:
                if buf:
                    out.append(buf.decode("utf-8", errors="replace"))
                    buf = bytearray()
                if i in self._special_by_id:
                    if not skip_special_tokens:
                        out.append(self._special_by_id[i])
                else:
                    # Ids past the byte+special range (a model vocab larger
                    # than this tokenizer's) decode lossily, never silently:
                    # downstream consumers (streaming clients, stop-string
                    # scan) must see one glyph per token.
                    out.append("�")
        if buf:
            out.append(buf.decode("utf-8", errors="replace"))
        return "".join(out)

    @property
    def eos_token_id(self) -> int:
        return self.EOS

    @property
    def bos_token_id(self) -> int:
        return self.BOS

    @property
    def vocab_size(self) -> int:
        return 259 + len(self._specials) - 3


class DecodeStream:
    """Incremental detokenizer: feed ids one at a time, get stable text deltas.

    Offset-based incremental decode: decode the tail since the last stable
    boundary; if it ends in U+FFFD the final token(s) form an incomplete
    multi-byte sequence, so the delta is held back until a later token
    completes it (reference DecodeStream semantics, lib/llm/src/tokenizers.rs).
    """

    def __init__(self, tokenizer: BaseTokenizer, skip_special_tokens: bool = True):
        self._tok = tokenizer
        self._skip = skip_special_tokens
        self._ids: List[int] = []
        self._prefix_offset = 0  # start of the decode window (last boundary)
        self._read_offset = 0  # ids before this are already emitted

    def _decode(self, ids: List[int]) -> str:
        # Mid-stream windows must keep a leading ▁-space (sentencepiece
        # dummy prefix) or the prefix-diff silently eats inter-token
        # spaces; tokenizers exposing decode_window get told whether the
        # window starts the sequence.
        win = getattr(self._tok, "decode_window", None)
        if win is not None:
            return win(
                ids, skip_special_tokens=self._skip,
                sequence_start=self._prefix_offset == 0,
            )
        return self._tok.decode(ids, skip_special_tokens=self._skip)

    def step(self, token_id: int) -> str:
        """Feed one token id; return newly-stable text (may be empty)."""
        self._ids.append(token_id)
        tail = self._ids[self._prefix_offset :]
        text = self._decode(tail)
        if text.endswith("�"):
            if len(self._ids) - self._read_offset < 4:
                # Possibly an incomplete multi-byte sequence: hold the
                # delta.  A UTF-8 character resolves within 4 bytes, so a
                # longer unresolved window is a DELIBERATE replacement
                # glyph (e.g. an id outside a lossy tokenizer's range) —
                # holding forever would jail the whole stream until finish.
                return ""
            # Force-emit the held window and COMMIT past it (both offsets
            # to the end): re-decoding these ids later could resolve
            # differently than what we just emitted and garble the diff.
            prev = self._decode(self._ids[self._prefix_offset : self._read_offset])
            self._prefix_offset = len(self._ids)
            self._read_offset = len(self._ids)
            return text[len(prev) :]
        prev = self._decode(self._ids[self._prefix_offset : self._read_offset])
        delta = text[len(prev) :]
        self._prefix_offset = self._read_offset
        self._read_offset = len(self._ids)
        return delta

    def flush(self) -> str:
        """Emit any held-back text at end of stream (replacement chars kept)."""
        if self._read_offset >= len(self._ids):
            return ""
        text = self._decode(self._ids[self._prefix_offset :])
        prev = self._decode(self._ids[self._prefix_offset : self._read_offset])
        self._read_offset = len(self._ids)
        self._prefix_offset = len(self._ids)
        return text[len(prev) :]
