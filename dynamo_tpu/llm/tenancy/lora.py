"""Batched multi-LoRA serving: adapter registry + resident device slots.

Reference semantics: S-LoRA (Sheng et al., arXiv:2311.03285) — one base model
serves many low-rank adapters by keeping adapters in a host-side pool,
promoting the actively-used ones into device memory, and applying them
*batched* so one forward pass serves rows from many adapters.  The TPU
mapping here (models/llama.py):

- the engine owns a fixed-shape DEVICE BANK per target projection —
  ``[L, in, R*r]`` A-factors and ``[L, R*r, out]`` B-factors for R resident
  slots of rank ceiling r — so hot-swapping an adapter is a host→device
  column write, never a recompile (shapes are static, which is what keeps
  the unified ragged program's compile count flat);
- every batch row carries an adapter SLOT id (-1 = base model); the forward
  computes ``(x @ A_all) * slot_mask @ B_all`` — two dense matmuls plus a
  segment mask, the TPU-friendly equivalent of S-LoRA's segmented gather
  (no scatter/gather, MXU-shaped, exact per-row isolation);
- adapters are MERGE-FREE: base weights (possibly int8-quantized —
  models/quant.py) are never touched, so any quantization calibration stays
  valid and eviction is free.

This module is host-side policy: the ``AdapterRegistry`` holds loaded
adapters (numpy factors, alpha/r folded into B), manages the LRU-bounded
resident set with refcounts (an adapter is never evicted while a sequence
uses it), and promotes asynchronously through an engine-supplied apply hook.
KV isolation: ``kv_salt_for_adapter`` is the ONE derivation of the tenant
salt mixed into the chained block hashes (dynamo_tpu.tokens) — engine
sealing, host offload, the transfer plane, and the kv_router all key on
those hashes, so salting the root isolates every tier at once.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..metrics import tenancy_metrics

logger = logging.getLogger(__name__)

# Projections adapters apply to (attention q/k/v/o — the S-LoRA default).
LORA_TARGETS = ("wq", "wk", "wv", "wo")

_HF_TARGET_MAP = {
    "q_proj": "wq",
    "k_proj": "wk",
    "v_proj": "wv",
    "o_proj": "wo",
}


def kv_salt_for_adapter(name: str) -> str:
    """Tenant salt mixed into KV block hashes (tokens.py salt_hash).  The
    single source of truth — engine and router must agree or routing overlap
    scores diverge from engine cache state."""
    return f"lora/{name}"


def target_dims(model_config) -> Dict[str, Tuple[int, int]]:
    """(in, out) dims per LoRA target projection."""
    D = model_config.hidden_size
    q = model_config.num_heads * model_config.head_dim
    kv = model_config.num_kv_heads * model_config.head_dim
    return {"wq": (D, q), "wk": (D, kv), "wv": (D, kv), "wo": (q, D)}


class AdapterError(ValueError):
    """Malformed adapter (shape/rank mismatch)."""


class AdapterCapacityError(RuntimeError):
    """All resident slots pinned by active sequences; promotion timed out.

    Transient by construction (a slot frees when any pinning sequence
    finishes): the HTTP edge maps it to 503 + Retry-After, and the wire
    tag below lets remote edges do the same without importing us."""

    error_kind = "adapter_capacity"


@dataclass
class LoraAdapter:
    """One adapter's host-side factors.

    ``factors[target] = (A, B)`` with A ``[L, in, r]`` and B ``[L, r, out]``
    float32 numpy; the LoRA scale (alpha/r) is already folded into B.
    Missing targets are simply identity (zero delta).
    """

    name: str
    rank: int
    factors: Dict[str, Tuple[np.ndarray, np.ndarray]] = field(default_factory=dict)

    def validate(self, model_config, max_rank: int) -> None:
        if self.rank < 1 or self.rank > max_rank:
            raise AdapterError(
                f"adapter {self.name!r} rank {self.rank} outside [1, {max_rank}]"
            )
        dims = target_dims(model_config)
        L = model_config.num_layers
        for tgt, (a, b) in self.factors.items():
            if tgt not in dims:
                raise AdapterError(f"adapter {self.name!r}: unknown target {tgt!r}")
            din, dout = dims[tgt]
            if a.shape != (L, din, self.rank) or b.shape != (L, self.rank, dout):
                raise AdapterError(
                    f"adapter {self.name!r} target {tgt}: shapes "
                    f"{a.shape}/{b.shape} != {(L, din, self.rank)}/"
                    f"{(L, self.rank, dout)}"
                )

    @classmethod
    def random(
        cls,
        model_config,
        name: str,
        rank: int = 4,
        seed: int = 0,
        scale: float = 0.05,
        targets: Tuple[str, ...] = LORA_TARGETS,
    ) -> "LoraAdapter":
        """Synthetic adapter for tests/benchmarks.  Unlike training-time
        LoRA init (B=0, a no-op), BOTH factors are non-zero so distinct
        adapters produce distinct streams — the property the multi-tenant
        correctness gates assert."""
        rng = np.random.default_rng(seed)
        dims = target_dims(model_config)
        L = model_config.num_layers
        factors = {}
        for tgt in targets:
            din, dout = dims[tgt]
            a = rng.standard_normal((L, din, rank)).astype(np.float32) * scale
            b = rng.standard_normal((L, rank, dout)).astype(np.float32) * scale
            factors[tgt] = (a, b)
        return cls(name=name, rank=rank, factors=factors)


def load_lora_adapter(path: str, model_config, name: Optional[str] = None) -> LoraAdapter:
    """Load a PEFT-format adapter directory (adapter_config.json +
    adapter_model.safetensors).  HF torch layouts map to the matmul layout:
    lora_A ``[r, in]`` → A ``[in, r]``, lora_B ``[out, r]`` → B ``[r, out]``;
    the LoRA scale alpha/r folds into B at load."""
    cfg_path = os.path.join(path, "adapter_config.json")
    rank, alpha = 8, 8.0
    if os.path.exists(cfg_path):
        with open(cfg_path) as fh:
            cfg = json.load(fh)
        rank = int(cfg.get("r", rank))
        alpha = float(cfg.get("lora_alpha", rank))
    weights = os.path.join(path, "adapter_model.safetensors")
    if not os.path.exists(weights):
        raise AdapterError(f"no adapter_model.safetensors under {path}")
    from safetensors import safe_open

    L = model_config.num_layers
    grids: Dict[str, Dict[str, List[Optional[np.ndarray]]]] = {}
    with safe_open(weights, framework="numpy") as f:
        for key in f.keys():
            # ...model.layers.{i}.self_attn.{q_proj}.lora_{A|B}.weight
            parts = key.split(".")
            try:
                li = parts.index("layers")
                layer = int(parts[li + 1])
                proj = parts[li + 3]
                which = parts[li + 4]  # lora_A | lora_B
            except (ValueError, IndexError):
                continue
            tgt = _HF_TARGET_MAP.get(proj)
            if tgt is None or which not in ("lora_A", "lora_B"):
                continue
            t = f.get_tensor(key).astype(np.float32)
            grid = grids.setdefault(tgt, {"A": [None] * L, "B": [None] * L})
            grid["A" if which == "lora_A" else "B"][layer] = t
    factors: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
    scaling = alpha / rank
    for tgt, grid in grids.items():
        missing = [
            i for i in range(L) if grid["A"][i] is None or grid["B"][i] is None
        ]
        if missing:
            raise AdapterError(
                f"adapter at {path}: target {tgt} missing layers {missing[:8]}"
            )
        a = np.stack([t.T for t in grid["A"]])  # [L, in, r]
        b = np.stack([t.T for t in grid["B"]]) * scaling  # [L, r, out]
        factors[tgt] = (a, b)
    if not factors:
        raise AdapterError(f"adapter at {path} has no q/k/v/o lora tensors")
    adapter = LoraAdapter(
        name=name or os.path.basename(path.rstrip("/")), rank=rank, factors=factors
    )
    adapter.validate(model_config, max_rank=rank)
    return adapter


def bank_leaves(model_config, max_adapters: int, rank: int) -> Dict[str, np.ndarray]:
    """Zero-initialized device-bank leaves for ``params["layers"]``:
    ``lora_a_{t}`` [L, in, R*r] and ``lora_b_{t}`` [L, R*r, out] per target.
    All-zero columns are an exact no-op, so freshly-created slots and the
    base model share one code path (slot mask -1 never matches anyway)."""
    dims = target_dims(model_config)
    L = model_config.num_layers
    Rr = max_adapters * rank
    out: Dict[str, np.ndarray] = {}
    for tgt in LORA_TARGETS:
        din, dout = dims[tgt]
        out[f"lora_a_{tgt}"] = np.zeros((L, din, Rr), np.float32)
        out[f"lora_b_{tgt}"] = np.zeros((L, Rr, dout), np.float32)
    return out


def padded_factors(
    adapter: Optional[LoraAdapter], model_config, target: str, rank: int
) -> Tuple[np.ndarray, np.ndarray]:
    """One slot's column block for ``target``, rank-padded to the bank's
    per-slot ceiling (None adapter or missing target → zeros = no-op)."""
    dims = target_dims(model_config)
    din, dout = dims[target]
    L = model_config.num_layers
    a = np.zeros((L, din, rank), np.float32)
    b = np.zeros((L, rank, dout), np.float32)
    if adapter is not None:
        pair = adapter.factors.get(target)
        if pair is not None:
            ra = min(adapter.rank, rank)
            a[:, :, :ra] = pair[0][:, :, :ra]
            b[:, :ra, :] = pair[1][:, :ra, :]
    return a, b


# ApplyFn(slot, adapter_or_None) promotes an adapter's (padded) factors into
# the device bank's slot columns; awaited under the engine's device lock.
ApplyFn = Callable[[int, Optional[LoraAdapter]], Awaitable[None]]


class AdapterRegistry:
    """Host-side adapter pool + LRU-bounded resident device slots.

    - ``register``/``unregister``: host bookkeeping only (numpy factors).
    - ``acquire(name)``: resolve the adapter to a resident slot, promoting
      (async H2D through ``apply_fn``) and LRU-evicting an idle resident if
      needed; takes a refcount that pins the slot for the sequence's life.
    - ``release(name)``: drop the ref; zero-ref residents become eviction
      candidates (factors stay on device — re-acquiring is free until a
      promotion overwrites the slot).

    A slot is NEVER rewritten while its refcount is non-zero: in-flight
    batch rows address slots by index, so overwriting a live slot would
    silently switch a running sequence's adapter mid-stream.
    """

    def __init__(self, max_resident: int, max_rank: int, apply_fn: ApplyFn,
                 promote_timeout_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        if max_resident < 1:
            raise ValueError("lora max_adapters must be >= 1")
        self.max_resident = max_resident
        self.max_rank = max_rank
        self._apply = apply_fn
        self.promote_timeout_s = promote_timeout_s
        # Injectable clock: promotion deadlines must be testable without
        # real waiting and identical under sim/replay.
        self._clock = clock
        self._adapters: Dict[str, LoraAdapter] = {}
        self._slot_of: Dict[str, int] = {}  # resident name → slot
        self._owner: List[Optional[str]] = [None] * max_resident
        self._refs: List[int] = [0] * max_resident
        # Residents LRU (oldest first) — eviction order among ref==0 slots.
        self._lru: "OrderedDict[str, None]" = OrderedDict()
        self._claim_lock = asyncio.Lock()
        self._freed = asyncio.Event()

    # ----------------------------------------------------------- host pool
    def register(self, adapter: LoraAdapter, model_config) -> None:
        adapter.validate(model_config, self.max_rank)
        fresh = adapter.name not in self._adapters
        self._adapters[adapter.name] = adapter
        if not fresh and adapter.name in self._slot_of:
            # Re-registration with new factors: invalidate the resident copy
            # (promoted again on next acquire).  Refused while in use.
            slot = self._slot_of[adapter.name]
            if self._refs[slot]:
                raise AdapterError(
                    f"adapter {adapter.name!r} is serving sequences; "
                    "cannot replace its factors in place"
                )
            self._evict_slot(slot)
        if fresh:
            tenancy_metrics.adapters_registered += 1

    def unregister(self, name: str) -> None:
        if name not in self._adapters:
            return
        slot = self._slot_of.get(name)
        if slot is not None:
            if self._refs[slot]:
                raise AdapterError(
                    f"adapter {name!r} is serving sequences; drain first"
                )
            self._evict_slot(slot)
        del self._adapters[name]
        tenancy_metrics.adapters_registered -= 1

    def has(self, name: str) -> bool:
        return name in self._adapters

    def get(self, name: str) -> Optional[LoraAdapter]:
        return self._adapters.get(name)

    def names(self) -> List[str]:
        return sorted(self._adapters)

    def resident(self) -> Dict[str, int]:
        return dict(self._slot_of)

    # -------------------------------------------------------- device slots
    def _evict_slot(self, slot: int) -> None:
        owner = self._owner[slot]
        if owner is not None:
            self._slot_of.pop(owner, None)
            self._lru.pop(owner, None)
            self._owner[slot] = None
            tenancy_metrics.adapter_evictions += 1

    def _find_free_slot(self) -> Optional[int]:
        for slot, owner in enumerate(self._owner):
            if owner is None:
                return slot
        # LRU-evict the coldest idle resident.
        for name in self._lru:
            slot = self._slot_of[name]
            if self._refs[slot] == 0:
                self._evict_slot(slot)
                return slot
        return None

    async def acquire(self, name: str) -> int:
        """Resident slot for ``name`` with a ref taken.  Raises KeyError for
        unknown adapters (callers map it to their model-not-found error) and
        AdapterCapacityError when every slot stays pinned past the
        promotion timeout."""
        if name not in self._adapters:
            raise KeyError(name)
        deadline = self._clock() + self.promote_timeout_s
        while True:
            # Serialize claims so two concurrent acquires cannot race one
            # slot; the H2D promotion happens inside the claim.
            async with self._claim_lock:
                adapter = self._adapters.get(name)
                if adapter is None:
                    raise KeyError(name)
                slot = self._slot_of.get(name)
                if slot is not None:
                    self._refs[slot] += 1
                    self._lru.pop(name, None)
                    self._lru[name] = None
                    return slot
                slot = self._find_free_slot()
                if slot is not None:
                    self._owner[slot] = name
                    self._slot_of[name] = slot
                    self._refs[slot] = 1
                    self._lru[name] = None
                    try:
                        await self._apply(slot, adapter)
                    except BaseException:
                        # Failed promotion must not leave a claimed slot
                        # pointing at garbage factors.
                        self._refs[slot] = 0
                        self._evict_slot(slot)
                        raise
                    tenancy_metrics.adapter_promotions += 1
                    return slot
                self._freed.clear()
            timeout = deadline - self._clock()
            if timeout <= 0:
                raise AdapterCapacityError(
                    f"all {self.max_resident} adapter slots are pinned by "
                    f"active sequences; cannot promote {name!r}"
                )
            try:
                await asyncio.wait_for(self._freed.wait(), timeout)
            except asyncio.TimeoutError:
                raise AdapterCapacityError(
                    f"all {self.max_resident} adapter slots are pinned by "
                    f"active sequences; cannot promote {name!r}"
                ) from None

    def release(self, name: str) -> None:
        slot = self._slot_of.get(name)
        if slot is None:
            return
        self._refs[slot] = max(0, self._refs[slot] - 1)
        if self._refs[slot] == 0:
            self._freed.set()  # wake acquire() waiters to re-scan
