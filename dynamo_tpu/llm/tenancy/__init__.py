"""Multi-tenant serving on one resident engine (PAPER.md layer 2 scenarios):

- ``grammar.py`` — structured output: JSON-schema/regex constraints compiled
  to token-mask automata (Outlines, arXiv:2307.09702), applied as per-row
  logit masks inside the existing unified ragged program.
- ``lora.py`` — batched multi-LoRA: hot-swappable per-request adapters
  (S-LoRA, arXiv:2311.03285) applied merge-free through fixed-shape device
  banks, with tenant-salted KV hashing for cache isolation.
"""

from .grammar import (  # noqa: F401
    GrammarCompiler,
    GrammarError,
    TokenMaskAutomaton,
    build_regex_from_schema,
    compile_token_automaton,
    constraint_spec,
)
from .lora import (  # noqa: F401
    LORA_TARGETS,
    AdapterCapacityError,
    AdapterError,
    AdapterRegistry,
    LoraAdapter,
    kv_salt_for_adapter,
    load_lora_adapter,
    target_dims,
)
