"""Grammar-constrained decoding: JSON schema / regex → token-mask automaton.

Reference semantics: Outlines (Willard & Louf, arXiv:2307.09702) — structured
generation reduces to a finite-state machine over the *token* vocabulary:
compile the constraint to a character-level DFA, then index every vocabulary
token against every reachable DFA state.  At decode time the engine holds one
integer (the automaton state) per constrained sequence, masks the logits with
the state's admissible-token set, and advances the state on each accepted
token — no per-step re-parsing, no device-side state, and the whole thing
rides the existing unified ragged program as a per-row logit mask
(ops/sampling.py).

Pipeline stages here (all host-side, all cached):

  JSON schema ──build_regex_from_schema──▶ regex (restricted syntax)
  regex ──parse──▶ AST ──Thompson──▶ NFA ──subset──▶ lazy char-DFA
  char-DFA × tokenizer ──token walk──▶ TokenMaskAutomaton

The ``TokenMaskAutomaton`` is plain data (per-state token→next edges +
accepting flags), so the PREPROCESSOR — the only layer holding the tokenizer
— compiles it once per (constraint, tokenizer) and ships it inside the
``PreprocessedRequest``; engines (possibly in another process, holding no
tokenizer) just walk integers.  EOS handling is the engine's: EOS is
admissible exactly in accepting states (the engine knows the model's eos ids;
the automaton only flags which states accept).

Canonical whitespace: generated regexes allow optional blanks around JSON
structural characters, so models keep their natural " " after ':' and ','.

Cost shape: indexing is O(states × vocab × token_len) once per constraint —
sub-millisecond for test vocabularies, seconds for 128k-token vocabularies,
which is why the compile cache (preprocessor) and the automaton cache
(engine, by content hash) both exist.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, FrozenSet, List, Optional, Sequence, Tuple

import numpy as np

# Hard cap on token-automaton states: a runaway schema must fail loudly at
# compile time, never OOM the preprocessor.
MAX_STATES = 4096


class GrammarError(ValueError):
    """Unsupported/invalid constraint (maps to HTTP 400 at the edge)."""


class GrammarCacheMissError(ValueError):
    """A hash-only grammar stub missed the engine's content-hash LRU.

    Not a request error in the usual sense: the DISPATCHER (preprocessor)
    owns the full automaton and re-sends it on this signal.  ``error_kind``
    rides the service-plane prologue so the remote flavour surfaces as a
    non-retryable ``RemoteEngineError(kind="grammar_miss")`` — replaying
    the stub on other workers would just collect more misses."""

    error_kind = "grammar_miss"

    def __init__(self, content_hash: str):
        super().__init__(
            f"grammar {content_hash!r} not in engine cache; resend full table"
        )
        self.content_hash = content_hash


# --------------------------------------------------------------------------
# Restricted regex syntax: literals, escapes, [...] classes (ranges,
# negation), ( ) grouping, |, *, +, ?, {m}, {m,n}, {m,}.  This is the syntax
# build_regex_from_schema emits; user-supplied nvext.grammar regexes are held
# to the same subset.
# --------------------------------------------------------------------------

_ESCAPES = {
    "n": "\n",
    "t": "\t",
    "r": "\r",
    "f": "\f",
    "b": "\b",
    "0": "\0",
}

# Perl-style shorthand classes usable both inline and inside [...].
_SHORTHAND = {
    "d": frozenset("0123456789"),
    "s": frozenset(" \t\n\r\f"),
    "w": frozenset(
        "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_"
    ),
}

# AST nodes: ("lit", chars, negated) | ("cat", [n]) | ("alt", [n]) |
# ("star", n) | ("plus", n) | ("opt", n)


class _Parser:
    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0

    def error(self, msg: str) -> GrammarError:
        return GrammarError(f"regex error at {self.i}: {msg} in {self.p!r}")

    def peek(self) -> Optional[str]:
        return self.p[self.i] if self.i < len(self.p) else None

    def next(self) -> str:
        ch = self.p[self.i]
        self.i += 1
        return ch

    def parse(self):
        node = self._alt()
        if self.i != len(self.p):
            raise self.error("unbalanced ')'")
        return node

    def _alt(self):
        branches = [self._cat()]
        while self.peek() == "|":
            self.next()
            branches.append(self._cat())
        return branches[0] if len(branches) == 1 else ("alt", branches)

    def _cat(self):
        parts: List[Any] = []
        while self.peek() is not None and self.peek() not in "|)":
            parts.append(self._repeat())
        return ("cat", parts)

    def _repeat(self):
        node = self._atom()
        while True:
            ch = self.peek()
            if ch == "*":
                self.next()
                node = ("star", node)
            elif ch == "+":
                self.next()
                node = ("plus", node)
            elif ch == "?":
                self.next()
                node = ("opt", node)
            elif ch == "{":
                node = self._bounded(node)
            else:
                return node

    def _bounded(self, node):
        self.next()  # '{'
        spec = ""
        while self.peek() is not None and self.peek() != "}":
            spec += self.next()
        if self.peek() != "}":
            raise self.error("unterminated {m,n}")
        self.next()
        parts = spec.split(",")
        try:
            lo = int(parts[0])
            hi = int(parts[1]) if len(parts) > 1 and parts[1] else (
                lo if len(parts) == 1 else None
            )
        except ValueError as e:
            raise self.error(f"bad repetition {spec!r}") from e
        if lo < 0 or (hi is not None and hi < lo):
            raise self.error(f"bad repetition bounds {spec!r}")
        # {m,n} → m copies + (n-m) optionals; {m,} → m copies + star.
        out: List[Any] = [node] * lo
        if hi is None:
            out.append(("star", node))
        else:
            out.extend(("opt", node) for _ in range(hi - lo))
        return ("cat", out)

    def _atom(self):
        ch = self.next()
        if ch == "(":
            node = self._alt()
            if self.peek() != ")":
                raise self.error("unterminated group")
            self.next()
            return node
        if ch == "[":
            return self._char_class()
        if ch == ".":
            return ("lit", frozenset("\n"), True)  # any char but newline
        if ch == "\\":
            return self._escape(in_class=False)
        if ch in "*+?{":
            raise self.error(f"dangling quantifier {ch!r}")
        return ("lit", frozenset(ch), False)

    def _escape(self, in_class: bool):
        if self.peek() is None:
            raise self.error("dangling backslash")
        ch = self.next()
        if ch in _SHORTHAND:
            return ("lit", _SHORTHAND[ch], False)
        if ch.isupper() and ch.lower() in _SHORTHAND:
            return ("lit", _SHORTHAND[ch.lower()], True)
        return ("lit", frozenset(_ESCAPES.get(ch, ch)), False)

    def _char_class(self):
        negated = False
        if self.peek() == "^":
            self.next()
            negated = True
        chars: set = set()
        first = True
        while True:
            ch = self.peek()
            if ch is None:
                raise self.error("unterminated character class")
            if ch == "]" and not first:
                self.next()
                return ("lit", frozenset(chars), negated)
            first = False
            self.next()
            if ch == "\\":
                lit = self._escape(in_class=True)
                if lit[2]:
                    raise self.error("negated shorthand inside class")
                chars |= lit[1]
                continue
            if self.peek() == "-" and self.i + 1 < len(self.p) and (
                self.p[self.i + 1] != "]"
            ):
                self.next()  # '-'
                hi = self.next()
                if hi == "\\":
                    hi_lit = self._escape(in_class=True)
                    (hi,) = hi_lit[1]
                if ord(hi) < ord(ch):
                    raise self.error(f"bad range {ch}-{hi}")
                chars |= {chr(c) for c in range(ord(ch), ord(hi) + 1)}
            else:
                chars.add(ch)


# --------------------------------------------------------------------------
# Thompson NFA + lazy subset-construction DFA
# --------------------------------------------------------------------------


class _NFA:
    def __init__(self):
        # per state: [(chars, negated, target)], [eps targets]
        self.trans: List[List[Tuple[FrozenSet[str], bool, int]]] = []
        self.eps: List[List[int]] = []

    def state(self) -> int:
        self.trans.append([])
        self.eps.append([])
        return len(self.trans) - 1

    def build(self, node) -> Tuple[int, int]:
        kind = node[0]
        if kind == "lit":
            s, a = self.state(), self.state()
            self.trans[s].append((node[1], node[2], a))
            return s, a
        if kind == "cat":
            if not node[1]:
                s = self.state()
                return s, s
            start, acc = self.build(node[1][0])
            for part in node[1][1:]:
                s2, a2 = self.build(part)
                self.eps[acc].append(s2)
                acc = a2
            return start, acc
        if kind == "alt":
            s, a = self.state(), self.state()
            for branch in node[1]:
                bs, ba = self.build(branch)
                self.eps[s].append(bs)
                self.eps[ba].append(a)
            return s, a
        if kind == "star":
            s, a = self.state(), self.state()
            bs, ba = self.build(node[1])
            self.eps[s] += [bs, a]
            self.eps[ba] += [bs, a]
            return s, a
        if kind == "plus":
            bs, ba = self.build(node[1])
            s, a = self.state(), self.state()
            self.eps[s].append(bs)
            self.eps[ba] += [bs, a]
            return s, a
        if kind == "opt":
            s, a = self.state(), self.state()
            bs, ba = self.build(node[1])
            self.eps[s] += [bs, a]
            self.eps[ba].append(a)
            return s, a
        raise GrammarError(f"unknown AST node {kind!r}")


class _CharDFA:
    """Lazy subset-construction DFA over the NFA (states = frozensets)."""

    def __init__(self, pattern: str):
        nfa = _NFA()
        start, accept = nfa.build(_Parser(pattern).parse())
        self._nfa = nfa
        self._accept = accept
        self.start = self._closure(frozenset([start]))
        self._move_memo: Dict[Tuple[FrozenSet[int], str], Optional[FrozenSet[int]]] = {}

    def _closure(self, states: FrozenSet[int]) -> FrozenSet[int]:
        out = set(states)
        stack = list(states)
        while stack:
            s = stack.pop()
            for t in self._nfa.eps[s]:
                if t not in out:
                    out.add(t)
                    stack.append(t)
        return frozenset(out)

    def move(self, states: FrozenSet[int], ch: str) -> Optional[FrozenSet[int]]:
        key = (states, ch)
        hit = self._move_memo.get(key, _MISS)
        if hit is not _MISS:
            return hit
        targets = {
            t
            for s in states
            for chars, negated, t in self._nfa.trans[s]
            if (ch in chars) != negated
        }
        out = self._closure(frozenset(targets)) if targets else None
        self._move_memo[key] = out
        return out

    def walk(self, states: FrozenSet[int], text: str) -> Optional[FrozenSet[int]]:
        for ch in text:
            states = self.move(states, ch)
            if states is None:
                return None
        return states

    def accepting(self, states: FrozenSet[int]) -> bool:
        return self._accept in states


_MISS = object()


# --------------------------------------------------------------------------
# Token-level automaton (the serializable artifact the engine consumes)
# --------------------------------------------------------------------------


class TokenMaskAutomaton:
    """Per-state admissible-token sets + transitions over TOKEN ids.

    ``edges[state]`` maps token id → next state; ``accepting`` states may end
    the value (EOS admissible there — the ENGINE adds the model's eos ids to
    accepting states' masks, since the automaton is tokenizer-level data and
    the model's eos ids are engine knowledge).  A state with no outgoing
    edges is *terminal*: the constrained value is complete and only EOS can
    follow (the engine finishes the stream).
    """

    def __init__(
        self,
        start: int,
        edges: List[Dict[int, int]],
        accepting: Sequence[int],
        content_hash: Optional[str] = None,
    ):
        self.start = start
        self.edges = edges
        self.accepting = frozenset(accepting)
        self.hash = content_hash or self._compute_hash()
        # Engine-side packed-mask cache (set_mask_context fixes vocab/eos).
        self._vocab: Optional[int] = None
        self._eos_ids: Tuple[int, ...] = ()
        self._packed: Dict[int, np.ndarray] = {}
        # Wire-form cache: edges are immutable after construction and
        # serializing them is O(total edges log edges) — per-request callers
        # (preprocessor) must not pay that on every compile-cache hit.
        self._wire: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------------- identity
    def _compute_hash(self) -> str:
        payload = json.dumps(
            {
                "start": self.start,
                "edges": [sorted(e.items()) for e in self.edges],
                "accepting": sorted(self.accepting),
            },
            separators=(",", ":"),
        )
        return hashlib.sha256(payload.encode()).hexdigest()[:16]

    # ------------------------------------------------------------ traversal
    def advance(self, state: int, token_id: int) -> Optional[int]:
        """Next state after ``token_id``, or None if inadmissible."""
        if not 0 <= state < len(self.edges):
            return None
        return self.edges[state].get(token_id)

    def is_accepting(self, state: int) -> bool:
        return state in self.accepting

    def is_terminal(self, state: int) -> bool:
        """Complete: no token may follow (only EOS).  Requires ACCEPTING —
        compile-time pruning removes non-accepting dead ends, but a
        hand-built or corrupted automaton must not let one end a stream
        as a clean stop."""
        return (
            0 <= state < len(self.edges)
            and not self.edges[state]
            and state in self.accepting
        )

    def allowed(self, state: int) -> Sequence[int]:
        return list(self.edges[state].keys()) if 0 <= state < len(self.edges) else []

    # ------------------------------------------------------- engine masking
    def set_mask_context(self, vocab_size: int, eos_ids: Sequence[int]) -> None:
        """Fix the packed-mask geometry (per engine); resets the cache when
        it changes (same automaton dict can serve engines with different
        vocab/eos)."""
        ctx = (vocab_size, tuple(sorted(eos_ids)))
        if (self._vocab, self._eos_ids) != ctx:
            self._vocab, self._eos_ids = ctx
            self._packed = {}

    def packed_mask(self, state: int) -> np.ndarray:
        """uint32[ceil(vocab/32)] bitmask of admissible tokens at ``state``
        (bit i of word i//32 = token i admissible); EOS bits set in
        accepting states.  Cached per state."""
        if self._vocab is None:
            raise RuntimeError("set_mask_context before packed_mask")
        cached = self._packed.get(state)
        if cached is not None:
            return cached
        V = self._vocab
        words = np.zeros(((V + 31) // 32,), np.uint32)
        ids = [t for t in self.allowed(state) if 0 <= t < V]
        if self.is_accepting(state):
            ids += [e for e in self._eos_ids if 0 <= e < V]
        if ids:
            arr = np.asarray(ids, np.int64)
            np.bitwise_or.at(
                words, arr // 32, (np.uint32(1) << (arr % 32).astype(np.uint32))
            )
        self._packed[state] = words
        return words

    # ---------------------------------------------------------------- wire
    def to_dict(self) -> Dict[str, Any]:
        if self._wire is None:
            self._wire = {
                "start": self.start,
                "edges": [sorted(e.items()) for e in self.edges],
                "accepting": sorted(self.accepting),
                "hash": self.hash,
            }
        return self._wire

    def wire_stub(self) -> Dict[str, Any]:
        """Hash-only wire form (content-addressed dispatch): engines whose
        LRU already holds this automaton resolve it from the hash alone —
        the full edge table (KBs per constrained request on real vocabs)
        ships only after an explicit ``GrammarCacheMissError`` round trip
        (the preprocessor's fallback)."""
        return {"hash": self.hash, "stub": True}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TokenMaskAutomaton":
        edges = [
            {int(t): int(n) for t, n in state_edges}
            for state_edges in d.get("edges", [])
        ]
        return cls(
            start=int(d.get("start", 0)),
            edges=edges,
            accepting=[int(s) for s in d.get("accepting", [])],
            content_hash=d.get("hash"),
        )


def _token_strings(tokenizer) -> Dict[int, str]:
    """Content tokens only: id → decoded piece.  Special tokens (bos/eos/
    pad/role markers) and empty pieces are excluded — a special token's
    surface text ("<eos>") must never satisfy a grammar's string class."""
    out: Dict[int, str] = {}
    for i in range(tokenizer.vocab_size):
        s = tokenizer.decode([i], skip_special_tokens=True)
        if s:
            out[i] = s
    return out


def compile_token_automaton(
    pattern: str,
    tokenizer,
    max_states: int = MAX_STATES,
    token_strings: Optional[Dict[int, str]] = None,
) -> TokenMaskAutomaton:
    """Index the whole vocabulary against the pattern's char-DFA (module
    docstring stage 3).  States are discovered breadth-first from the start
    state through token transitions; each reachable state's edge map is the
    per-state token mask the engine applies.  ``token_strings`` lets callers
    with a pinned tokenizer (GrammarCompiler) decode the vocabulary once."""
    dfa = _CharDFA(pattern)
    vocab = token_strings if token_strings is not None else _token_strings(tokenizer)
    id_of: Dict[FrozenSet[int], int] = {dfa.start: 0}
    order: List[FrozenSet[int]] = [dfa.start]
    edges: List[Dict[int, int]] = [{}]
    accepting: List[int] = []
    if dfa.accepting(dfa.start):
        accepting.append(0)
    from collections import deque as _deque

    queue = _deque([dfa.start])
    while queue:
        cur = queue.popleft()
        cur_id = id_of[cur]
        for tok, text in vocab.items():
            nxt = dfa.walk(cur, text)
            if nxt is None:
                continue
            nid = id_of.get(nxt)
            if nid is None:
                nid = id_of[nxt] = len(order)
                if nid >= max_states:
                    raise GrammarError(
                        f"grammar exceeds {max_states} token-automaton states"
                    )
                order.append(nxt)
                edges.append({})
                if dfa.accepting(nxt):
                    accepting.append(nid)
                queue.append(nxt)
            edges[cur_id][tok] = nid
    # Prune dead ends: a token edge into a state from which NO accepting
    # state is reachable (over token transitions) must not be admissible —
    # the vocabulary may lack the pieces a char-path needs (special tokens
    # and undecodable ids are excluded from indexing), and following such
    # an edge would strand the stream in an uncompletable value.
    live = set(accepting)
    changed = True
    while changed:
        changed = False
        for sid, e in enumerate(edges):
            if sid not in live and any(t in live for t in e.values()):
                live.add(sid)
                changed = True
    if 0 not in live:
        raise GrammarError(
            "grammar is unsatisfiable over this vocabulary: no token "
            "sequence can complete the constrained value"
        )
    edges = [
        {tok: nxt for tok, nxt in e.items() if nxt in live} for e in edges
    ]
    return TokenMaskAutomaton(0, edges, accepting)


# --------------------------------------------------------------------------
# JSON schema → regex
# --------------------------------------------------------------------------

_WS = "[ \t\n\r]*"
# RFC 8259: control characters (U+0000–U+001F) MUST be escaped inside JSON
# strings — excluding them from the unescaped-char class keeps "guaranteed
# valid" output actually json.loads-able (a raw newline in a mask-admissible
# token would otherwise end a clean STOP with unparseable JSON).
_JSON_CONTROL = "".join(chr(c) for c in range(0x20))
_STRING_INNER = (
    '([^"\\\\' + _JSON_CONTROL + ']|\\\\["\\\\/bfnrt]|\\\\u[0-9a-fA-F]{4})*'
)
_STRING = '"' + _STRING_INNER + '"'
_INTEGER = "-?(0|[1-9][0-9]*)"
_NUMBER = _INTEGER + "(\\.[0-9]+)?([eE][+-]?[0-9]+)?"
_BOOLEAN = "(true|false)"
_NULL = "null"

_RE_META = set("\\^$.|?*+()[]{}-")


def _re_escape(text: str) -> str:
    return "".join("\\" + c if c in _RE_META else c for c in text)


def _literal_regex(value: Any) -> str:
    """Regex matching exactly one JSON literal (enum/const values)."""
    return _re_escape(json.dumps(value, separators=(",", ":")))


def build_regex_from_schema(schema: Dict[str, Any], depth: int = 6) -> str:
    """JSON schema (subset) → regex over the value's serialized form.

    Supported: type object (properties serialized in declaration order, all
    emitted — optional-property subsets would blow the regex up
    combinatorially), array (items, minItems/maxItems), string (enum,
    minLength/maxLength unsupported), integer, number, boolean, null,
    enum/const at any level, anyOf/oneOf (alternation), nested to ``depth``.
    Free-form nesting ({} / json_object mode) is depth-bounded: beyond
    ``depth`` only scalar values are admitted.
    """
    if depth < 0:
        raise GrammarError("schema nesting exceeds the supported depth")
    if not isinstance(schema, dict):
        raise GrammarError(f"schema must be an object, got {type(schema).__name__}")
    if "const" in schema:
        return _literal_regex(schema["const"])
    if "enum" in schema:
        opts = schema["enum"]
        if not opts:
            raise GrammarError("empty enum")
        return "(" + "|".join(_literal_regex(v) for v in opts) + ")"
    for key in ("anyOf", "oneOf"):
        if key in schema:
            branches = schema[key]
            if not branches:
                raise GrammarError(f"empty {key}")
            return (
                "("
                + "|".join(
                    build_regex_from_schema(b, depth - 1) for b in branches
                )
                + ")"
            )
    t = schema.get("type")
    if isinstance(t, list):
        return "(" + "|".join(
            build_regex_from_schema({**schema, "type": one}, depth) for one in t
        ) + ")"
    if t == "string":
        return _STRING
    if t == "integer":
        return _INTEGER
    if t == "number":
        return _NUMBER
    if t == "boolean":
        return _BOOLEAN
    if t == "null":
        return _NULL
    if t == "array":
        items = schema.get("items")
        item_re = (
            build_regex_from_schema(items, depth - 1)
            if isinstance(items, dict)
            else _any_value_regex(depth - 1)
        )
        min_items = int(schema.get("minItems", 0))
        max_items = schema.get("maxItems")
        one = item_re
        sep = _WS + "," + _WS
        if max_items is not None:
            max_items = int(max_items)
            if max_items < min_items:
                raise GrammarError("maxItems < minItems")
            if max_items == 0:
                body = ""
            else:
                reps = "(" + sep + one + "){%d,%d}" % (
                    max(0, min_items - 1),
                    max_items - 1,
                )
                body = one + reps
                if min_items == 0:
                    body = "(" + body + ")?"
        else:
            reps = "(" + sep + one + ")" + (
                "{%d,}" % (min_items - 1) if min_items > 1 else "*"
            )
            body = one + reps
            if min_items == 0:
                body = "(" + body + ")?"
        return "\\[" + _WS + body + _WS + "\\]"
    if t == "object" and schema.get("properties"):
        props = schema["properties"]
        parts = []
        for name, sub in props.items():
            parts.append(
                _re_escape(json.dumps(name))
                + _WS
                + ":"
                + _WS
                + build_regex_from_schema(sub, depth - 1)
            )
        sep = _WS + "," + _WS
        return "\\{" + _WS + sep.join(parts) + _WS + "\\}"
    if t == "object":
        # Free-form OBJECT (json_object mode / no properties): the top
        # level must still be an object — only the property VALUES are
        # generic JSON.  The generic grammar duplicates the value regex
        # ~4x per level, so its depth is capped harder than structured
        # schemas (which grow linearly).
        return _any_object_regex(min(depth, 2))
    if schema == {} or t is None:
        # Free-form VALUE: any bounded-depth JSON.
        return _any_value_regex(min(depth, 2))
    raise GrammarError(f"unsupported schema: {json.dumps(schema)[:120]}")


def _any_object_regex(depth: int) -> str:
    """Generic JSON OBJECT grammar: `{...}` at the top level, generic
    values (nesting bounded at ``depth``) inside."""
    value = _any_value_regex(max(0, depth))
    member = _STRING + _WS + ":" + _WS + value
    return (
        "\\{" + _WS + "(" + member
        + "(" + _WS + "," + _WS + member + ")*)?" + _WS + "\\}"
    )


def _any_value_regex(depth: int) -> str:
    """Generic JSON value grammar, nesting bounded at ``depth``."""
    scalar = "(" + "|".join((_STRING, _NUMBER, _BOOLEAN, _NULL)) + ")"
    value = scalar
    for _ in range(max(0, depth)):
        arr = "\\[" + _WS + "(" + value + "(" + _WS + "," + _WS + value + ")*)?" + _WS + "\\]"
        obj = (
            "\\{" + _WS + "(" + _STRING + _WS + ":" + _WS + value
            + "(" + _WS + "," + _WS + _STRING + _WS + ":" + _WS + value + ")*)?"
            + _WS + "\\}"
        )
        value = "(" + "|".join((scalar, arr, obj)) + ")"
    return value


# --------------------------------------------------------------------------
# Front door: constraint spec → automaton (with compile caching)
# --------------------------------------------------------------------------


def constraint_spec(
    response_format: Optional[Dict[str, Any]], nvext_grammar: Any
) -> Optional[Dict[str, Any]]:
    """Normalize the two request surfaces into one constraint spec dict:
    ``{"kind": "json_schema"|"json_object"|"regex", ...}``; None = no
    constraint.  ``nvext.grammar`` accepts a regex string or a JSON schema
    dict; ``response_format`` follows the OpenAI shape."""
    if nvext_grammar is not None:
        if isinstance(nvext_grammar, str):
            return {"kind": "regex", "pattern": nvext_grammar}
        if isinstance(nvext_grammar, dict):
            return {"kind": "json_schema", "schema": nvext_grammar}
        raise GrammarError("nvext.grammar must be a regex string or a schema")
    if not response_format:
        return None
    kind = response_format.get("type")
    if kind in (None, "text"):
        return None
    if kind == "json_object":
        return {"kind": "json_object"}
    if kind == "json_schema":
        js = response_format.get("json_schema") or {}
        schema = js.get("schema", js if "type" in js or "enum" in js else None)
        if not isinstance(schema, dict):
            raise GrammarError("response_format.json_schema.schema missing")
        return {"kind": "json_schema", "schema": schema}
    raise GrammarError(f"unsupported response_format type {kind!r}")


def spec_regex(spec: Dict[str, Any]) -> str:
    kind = spec.get("kind")
    if kind == "regex":
        return spec["pattern"]
    if kind == "json_object":
        return build_regex_from_schema({"type": "object"})
    if kind == "json_schema":
        return build_regex_from_schema(spec["schema"])
    raise GrammarError(f"unknown constraint kind {kind!r}")


class GrammarCompiler:
    """Spec → TokenMaskAutomaton with an LRU compile cache.

    One instance per preprocessor (the tokenizer is fixed); the cache key is
    the canonical spec JSON.  Compilation is the expensive step (token
    indexing) — repeated agent/tool-calling traffic reuses the entry."""

    def __init__(self, tokenizer, max_entries: int = 64):
        import threading

        self._tokenizer = tokenizer
        self._max = max_entries
        self._cache: Dict[str, TokenMaskAutomaton] = {}
        # compile() may run off the event loop (preprocessor offloads cache
        # misses to a thread); the lock keeps the shared LRU coherent and
        # collapses concurrent same-spec compiles into one.
        self._lock = threading.Lock()
        # id → decoded piece, computed once per tokenizer: vocabulary
        # decoding costs as much as the DFA walk and is identical across
        # every constraint this compiler ever sees.
        self._token_strings: Optional[Dict[int, str]] = None
        self.compiles = 0
        self.hits = 0

    def compile(self, spec: Dict[str, Any]) -> TokenMaskAutomaton:
        key = json.dumps(spec, sort_keys=True, separators=(",", ":"))
        with self._lock:
            cached = self._cache.pop(key, None)
            if cached is not None:
                self._cache[key] = cached  # LRU refresh
                self.hits += 1
                return cached
            if self._token_strings is None:
                self._token_strings = _token_strings(self._tokenizer)
            automaton = compile_token_automaton(
                spec_regex(spec), self._tokenizer,
                token_strings=self._token_strings,
            )
            self.compiles += 1
            self._cache[key] = automaton
            while len(self._cache) > self._max:
                self._cache.pop(next(iter(self._cache)))
            return automaton
