"""Trace assembly + the edge's request-trace glue (ISSUE 15).

``TraceAggregator`` subscribes to the hub event plane's ``traces`` subject
(runtime/tracing.SpanExporter publishes batches there), assembles spans by
trace_id with a TTL, and serves the ``/traces/{id}`` / ``/traces?recent=N``
JSON views plus the per-hop TTFT decomposition rollup the v5e carry-over
runs need (DistServe-style TTFT-vs-TPOT attribution per phase).

``EdgeRequestTrace`` is the HTTP edge's per-request handle: it owns the
root span (``edge.request``), the admission-wait span, the first-token
event, and the tail-keep decision — head-unsampled requests that error or
violate the TTFT SLO still leave their edge spans behind (tail-keep is
edge-scoped by construction: downstream hops never recorded anything for
an unsampled context, so only the edge's own timeline can be kept
retroactively; docs/tracing.md states the contract).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Dict, List, Optional

from ..runtime.tracing import (
    TraceContext,
    TraceSampler,
    collector,
    span,
    tracing_metrics,
)

logger = logging.getLogger(__name__)

# The TTFT decomposition hops, in request order.  Each maps a rollup key to
# the span names that attribute it (first match wins per span).
TTFT_HOPS = (
    ("edge_queue", ("edge.admission_wait",)),
    ("preprocess", ("edge.preprocess",)),
    ("route", ("client.route",)),
    ("engine_queue", ("engine.queue_wait",)),
    ("prefill_or_pull", (
        "engine.prefill",
        "engine.kv_pull",
        "engine.kv_restore",
        "disagg.remote_prefill_wait",
    )),
    ("first_decode", ("engine.decode_chunk",)),
)


def ttft_decomposition(spans: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Per-hop duration rollup over one trace's spans.

    ``hops`` sums each decomposition phase's span wall; ``ttft_ms`` is the
    root span start → first ``first_token`` event; ``unattributed_ms`` is
    the TTFT window time covered by NO hop span (interval union, clipped to
    the window) — the gap-free bar the CPU smoke asserts on."""
    root = next((s for s in spans if s.get("parent_id") is None), None)
    hops: Dict[str, float] = {}
    intervals: List[List[float]] = []
    first_token_ms: Optional[float] = None
    for s in spans:
        for ev in s.get("events") or ():
            if ev.get("name") == "first_token":
                t = float(ev["t_ms"])
                if first_token_ms is None or t < first_token_ms:
                    first_token_ms = t
    window_start = float(root["start_ms"]) if root else None
    windowed = window_start is not None and first_token_ms is not None
    for s in spans:
        name = s.get("name", "")
        for hop, names in TTFT_HOPS:
            if name in names:
                start, dur = float(s["start_ms"]), float(s["dur_ms"])
                if windowed:
                    # Clip each hop's contribution to the TTFT window: a
                    # migrated/preempted trace records post-first-token
                    # prefill/queue spans (the target's resume admission)
                    # that would otherwise inflate a hop past TTFT itself.
                    dur = min(start + dur, first_token_ms) - max(
                        start, window_start
                    )
                    if dur <= 0:
                        break  # entirely outside TTFT: not a TTFT hop
                if hop == "first_decode" and hop in hops:
                    break  # only the FIRST decode chunk is TTFT
                hops[hop] = round(hops.get(hop, 0.0) + dur, 3)
                intervals.append([start, start + float(s["dur_ms"])])
                break
    out: Dict[str, Any] = {"hops": hops}
    if window_start is not None and first_token_ms is not None:
        ttft = max(first_token_ms - window_start, 0.0)
        covered = 0.0
        cur: Optional[List[float]] = None
        for lo, hi in sorted(intervals):
            lo = max(lo, window_start)
            hi = min(hi, first_token_ms)
            if hi <= lo:
                continue
            if cur is None or lo > cur[1]:
                if cur is not None:
                    covered += cur[1] - cur[0]
                cur = [lo, hi]
            else:
                cur[1] = max(cur[1], hi)
        if cur is not None:
            covered += cur[1] - cur[0]
        out["ttft_ms"] = round(ttft, 3)
        out["unattributed_ms"] = round(max(ttft - covered, 0.0), 3)
    return out


class TraceAggregator:
    """Assemble exported span batches by trace_id with TTL eviction.

    Feed it either by subscribing to the event plane (``start``) or
    directly as an exporter sink (``ingest``) when edge and engine share a
    process.  A trace is ROOTED once a span with ``parent_id == None``
    arrives (the edge/loadgen root); a trace whose TTL expires without one
    counts its spans as orphans — the cross-process-assembly health signal
    the goodput ladder's ``tracing`` block reports."""

    def __init__(
        self,
        ttl_s: float = 120.0,
        max_traces: int = 2048,
        clock=time.monotonic,
    ):
        self.ttl_s = ttl_s
        self.max_traces = max_traces
        self._clock = clock
        # trace_id → {"spans": [...], "t_first", "t_last"} (insertion order
        # = recency order for /traces?recent=N)
        self._traces: Dict[str, Dict[str, Any]] = {}
        self.orphan_spans_total = 0
        self.evicted_total = 0
        self._sub = None
        self._task: Optional[asyncio.Task] = None
        tracing_metrics.set_aggregator_source(self.stats)

    # ------------------------------------------------------------- ingest
    def ingest(self, payload: Any) -> None:
        spans = payload.get("spans") if isinstance(payload, dict) else None
        if not spans:
            return
        now = self._clock()
        for s in spans:
            tid = s.get("trace_id")
            if not tid:
                continue
            entry = self._traces.get(tid)
            if entry is None:
                entry = {"spans": [], "t_first": now}
                self._traces[tid] = entry
            entry["spans"].append(s)
            entry["t_last"] = now
            # Recency order: move to the end on update.
            self._traces[tid] = self._traces.pop(tid)
        self._prune(now)

    def _prune(self, now: Optional[float] = None) -> None:
        now = self._clock() if now is None else now
        dead = [
            tid
            for tid, e in self._traces.items()
            if now - e["t_last"] > self.ttl_s
        ]
        for tid in dead:
            self._evict(tid)
        while len(self._traces) > self.max_traces:
            self._evict(next(iter(self._traces)))

    def _evict(self, trace_id: str) -> None:
        entry = self._traces.pop(trace_id, None)
        if entry is None:
            return
        self.evicted_total += 1
        if not any(
            s.get("parent_id") is None for s in entry["spans"]
        ):
            # Expired without a root: the exporting side never delivered
            # the edge's span (or nothing at the edge sampled it) — these
            # spans can never assemble into a request timeline.
            self.orphan_spans_total += len(entry["spans"])

    # -------------------------------------------------------------- views
    def get(self, trace_id: str) -> Optional[Dict[str, Any]]:
        # Prune on read too: on a quiet edge no ingest runs, and the TTL
        # contract must hold for /traces/{id} as well as /traces?recent.
        self._prune()
        entry = self._traces.get(trace_id)
        if entry is None:
            return None
        spans = sorted(entry["spans"], key=lambda s: s.get("start_ms", 0.0))
        return {
            "trace_id": trace_id,
            "spans": spans,
            "components": sorted({s.get("component", "") for s in spans}),
            "procs": sorted({s.get("proc", "") for s in spans}),
            "rollup": ttft_decomposition(spans),
        }

    def recent(self, n: int = 20) -> List[Dict[str, Any]]:
        self._prune()
        if int(n) <= 0:
            return []  # list[-0:] would be the WHOLE list
        out = []
        for tid in list(self._traces)[-int(n):][::-1]:
            entry = self._traces[tid]
            root = next(
                (s for s in entry["spans"] if s.get("parent_id") is None),
                None,
            )
            out.append({
                "trace_id": tid,
                "spans": len(entry["spans"]),
                "components": sorted(
                    {s.get("component", "") for s in entry["spans"]}
                ),
                "root": (root or {}).get("name"),
                "dur_ms": (root or {}).get("dur_ms"),
            })
        return out

    def stats(self) -> Dict[str, Any]:
        return {
            "traces": len(self._traces),
            "orphan_spans": self.orphan_spans_total,
            "evicted": self.evicted_total,
        }

    # ---------------------------------------------------------- event plane
    async def start(self, namespace) -> "TraceAggregator":
        """Subscribe to ``{namespace}.traces`` and assemble everything the
        fleet publishes (the hub client re-arms the subscription across
        hub restarts — transports/hub.py)."""
        from ..runtime.tracing import TRACES_TOPIC

        self._sub = await namespace.subscribe(TRACES_TOPIC)
        self._task = asyncio.get_running_loop().create_task(self._run())
        return self

    async def _run(self) -> None:
        from .kv_router.publisher import unpack_message

        try:
            async for msg in self._sub:
                try:
                    self.ingest(unpack_message(msg))
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 — malformed batch
                    logger.warning("malformed span batch", exc_info=True)
        except asyncio.CancelledError:
            pass

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._sub is not None and hasattr(self._sub, "aclose"):
            await self._sub.aclose()
            self._sub = None
        # Detach the /metrics gauge source IF it is still ours (a newer
        # aggregator may have replaced it): a stopped aggregator must not
        # keep feeding /metrics or be pinned in memory by the singleton.
        if tracing_metrics._aggregator_source == self.stats:
            tracing_metrics.set_aggregator_source(None)


#: Bulk-sink kind the edge aggregator registers under (``bulk_sink_key``);
#: worker SpanExporters rendezvous on it when ``DYN_BULK_PLANE`` is on.
BULK_TRACES_SINK = "traces"


def make_bulk_span_sink(rendezvous, fallback):
    """SpanExporter sink over the bulk plane (``DYN_BULK_PLANE``): the
    batch pushes directly to a registered ``traces`` bulk sink (the edge
    aggregator's ingest) instead of fanning through the hub's pub/sub
    plane.  Any miss counts one ``dynamo_tpu_bulk_fallbacks_total`` and
    delegates to ``fallback`` (the hub-publish sink, the A/B oracle) — a
    span batch is never dropped by the bulk plane."""
    from ..runtime.transports import codec
    from ..runtime.transports.bulk import bulk_push
    from .metrics import bulk_metrics

    async def sink(payload: Dict[str, Any]) -> None:
        blob = codec.encode(payload)
        try:
            prep = await rendezvous.prepare_sink(
                BULK_TRACES_SINK, budget=len(blob)
            )
            if prep is None:
                raise RuntimeError("no bulk traces sink registered")
            address, ticket = prep
            await bulk_push(address, BULK_TRACES_SINK, ticket, blob)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — fallback ladder: hub path next
            logger.warning(
                "bulk span export failed; falling back to the hub path",
                exc_info=True,
            )
            bulk_metrics.fallbacks_total += 1
            await fallback(payload)

    return sink


async def start_bulk_ingest(aggregator: TraceAggregator, runtime,
                            host: str = "127.0.0.1"):
    """Run a bulk *sink* server in front of ``aggregator`` and register it
    in the hub under ``bulk/sink/traces/<worker>`` so worker exporters can
    rendezvous with it; returns the started ``BulkServer``."""
    from ..runtime.transports import codec
    from ..runtime.transports.bulk import BulkServer, bulk_sink_key

    async def sink(blob: bytes, meta: Dict[str, Any]) -> Dict[str, Any]:
        aggregator.ingest(codec.decode(blob))
        return {"ok": True}

    server = BulkServer(
        host, worker_id=runtime.worker_id, hub=runtime.hub
    )
    server.register_sink(BULK_TRACES_SINK, sink)
    await server.start()
    await runtime.register_key(
        bulk_sink_key(BULK_TRACES_SINK, runtime.worker_id),
        {"address": server.address, "worker_id": str(runtime.worker_id)},
    )
    return server


class EdgeRequestTrace:
    """Per-request edge tracing handle (llm/http_service.py).

    Created for EVERY request when a sampler is configured; when the head
    decision said no, the handle records edge timestamps locally (cheap:
    two floats) so tail-keep can still materialize the edge spans for an
    error / SLO-violating request after the fact."""

    __slots__ = ("sampler", "tc", "t0", "model", "endpoint", "_admit_t0",
                 "_admit_t1", "_first_token_t", "_events", "_finished")

    def __init__(self, sampler: Optional[TraceSampler], headers, body):
        self.sampler = sampler
        self.tc: Optional[TraceContext] = (
            sampler.decide(headers, body) if sampler is not None else None
        )
        self.t0 = time.perf_counter()
        self.model = ""
        self.endpoint = ""
        self._admit_t0: Optional[float] = None
        self._admit_t1: Optional[float] = None
        self._first_token_t: Optional[float] = None
        self._events: List[Dict[str, Any]] = []
        self._finished = False

    @property
    def active(self) -> bool:
        return self.tc is not None

    def admission_started(self) -> None:
        self._admit_t0 = time.perf_counter()

    def admission_done(self) -> None:
        self._admit_t1 = time.perf_counter()

    def event(self, name: str, **attrs) -> None:
        from ..runtime.tracing import _wall_ms

        ev: Dict[str, Any] = {
            "name": name,
            "t_ms": round(_wall_ms(time.perf_counter()), 3),
        }
        if attrs:
            ev.update(attrs)
        self._events.append(ev)

    def on_first_token(self) -> None:
        if self._first_token_t is None:
            self._first_token_t = time.perf_counter()
            self.event("first_token")

    @property
    def ttft_ms(self) -> Optional[float]:
        if self._first_token_t is None:
            return None
        return (self._first_token_t - self.t0) * 1e3

    def finish(self, status: str, model: str = "", endpoint: str = "") -> None:
        """Record the edge spans.  Head/forced traces always record; an
        untraced request records only if tail-keep promotes it."""
        if self._finished:
            return
        self._finished = True
        tc = self.tc
        if tc is None:
            # NOT "rejected": shedding is deliberate and high-volume by
            # design — tail-keeping every 429/503 during an overload storm
            # would turn over the span ring and evict the sampled traces
            # exactly when they matter (forced x-trace requests still
            # capture shed timelines; they never rely on tail-keep).
            if self.sampler is None or not self.sampler.tail_eligible(
                error=status == "error", ttft_ms=self.ttft_ms
            ):
                return
            tc = TraceContext.new()
            tracing_metrics.tail_kept_total += 1
            self.event("tail_kept", status=status)
        end = time.perf_counter()
        if self._admit_t0 is not None:
            # A request REJECTED while queued never saw admission_done():
            # the wait it died in ends at finish time, not at zero.
            collector.record(
                tc, "edge.admission_wait", "edge",
                self._admit_t0,
                self._admit_t1 if self._admit_t1 is not None else end,
            )
        attrs: Dict[str, Any] = {"status": status}
        if model or self.model:
            attrs["model"] = model or self.model
        if endpoint or self.endpoint:
            attrs["endpoint"] = endpoint or self.endpoint
        if self.ttft_ms is not None:
            attrs["ttft_ms"] = round(self.ttft_ms, 3)
        collector.record(
            tc, "edge.request", "edge", self.t0, end,
            attrs=attrs, events=self._events or None, parent_id=None,
        )


def preprocess_span(ctx):
    """The preprocessor's span under the request context's trace (None-safe;
    llm/preprocessor.py wraps template+tokenize+grammar-compile in it)."""
    return span(getattr(ctx, "trace", None), "edge.preprocess", "edge")
