"""Backend operator: incremental detokenization + stop-condition evaluation.

Reference semantics: lib/llm/src/backend.rs — wraps the token-in/token-out
engine; on the response path it incrementally detokenizes, evaluates stop
conditions (eos, stop_token_ids, max_tokens, stop strings), and implements the
hidden partial-match "jail": text that might be the start of a stop sequence
is held back until the match resolves, so stop strings never leak to clients
(backend.rs:234-423 ``Decoder::step``).

The backend stamps ``text`` onto each engine output dict and emits a final
item with ``finish_reason``.  When a stop triggers here (engine didn't know),
it calls ``stop_generating()`` so the device loop frees the request's slot.
"""

from __future__ import annotations

from typing import Any, AsyncIterator, Dict, List, Optional, Tuple

from ..runtime.engine import AsyncEngine, Context, ResponseStream
from ..runtime.pipeline import Operator
from .protocols import FinishReason, PreprocessedRequest, StopConditions
from .tokenizer import BaseTokenizer


class Decoder:
    """Per-request decode state: detok stream + stop evaluation + jail."""

    def __init__(self, tokenizer: BaseTokenizer, stop: StopConditions):
        self._stream = tokenizer.decode_stream()
        self._stop = stop
        self._eos_id = tokenizer.eos_token_id
        self._generated = 0
        self._jail = ""  # held-back text that may prefix a stop string

    def step(self, token_id: int) -> Tuple[str, Optional[FinishReason]]:
        """Feed one generated token → (emit_text, finish_reason|None)."""
        self._generated += 1
        stop = self._stop

        past_min = stop.min_tokens is None or self._generated > stop.min_tokens
        if past_min:
            if not stop.ignore_eos and self._eos_id is not None and token_id == self._eos_id:
                return self._jail_flush_on_stop(), FinishReason.STOP
            if token_id in stop.stop_token_ids:
                return self._jail_flush_on_stop(), FinishReason.STOP

        text = self._stream.step(token_id)
        emit, finished = self._eval_stop_strings(text)
        if finished:
            return emit, FinishReason.STOP

        if stop.max_tokens is not None and self._generated >= stop.max_tokens:
            # at the length limit, release anything jailed — it is real text
            return emit + self._release_jail(), FinishReason.LENGTH
        return emit, None

    def finish(self) -> str:
        """Engine ended the stream: flush detok + jail."""
        return self._stream.flush() + self._release_jail()

    # -- migration (llm/migration SequenceSnapshot.detok) -------------------
    #
    # The routed client splices migrated streams BELOW this operator, so in
    # the normal path Decoder state never moves.  An edge that itself hands
    # a stream to another frontend (or replays a recorded one) snapshots
    # here instead: the detok byte-stream state is reconstructed by
    # replaying the generated token ids (decode_stream is deterministic),
    # and the jail/counters restore exactly.

    def state_dict(self) -> dict:
        return {"generated": self._generated, "jail": self._jail}

    def load_state(self, state: dict, token_ids=()) -> None:
        """Restore from ``state_dict()`` output; ``token_ids`` replays the
        already-generated tokens through a FRESH detok stream (emitted text
        is discarded — it was already delivered)."""
        for tok in token_ids:
            self._stream.step(tok)
        self._generated = int(state.get("generated", 0))
        self._jail = str(state.get("jail", ""))

    # -- stop strings -------------------------------------------------------

    def _eval_stop_strings(self, new_text: str) -> Tuple[str, bool]:
        if not self._stop.stop:
            return new_text, False
        pending = self._jail + new_text
        # full match anywhere → truncate before it, stop
        for s in self._stop.stop:
            idx = pending.find(s)
            if idx != -1:
                self._jail = ""
                return pending[:idx], True
        # hold the longest tail that is a proper prefix of any stop string
        hold = 0
        for s in self._stop.stop:
            for k in range(min(len(s) - 1, len(pending)), 0, -1):
                if pending.endswith(s[:k]):
                    hold = max(hold, k)
                    break
        if hold:
            self._jail = pending[-hold:]
            return pending[:-hold], False
        self._jail = ""
        return pending, False

    def _release_jail(self) -> str:
        jail, self._jail = self._jail, ""
        return jail

    def _jail_flush_on_stop(self) -> str:
        # a stop token ends generation; jailed text was never part of a stop
        # string match, so it is real output
        return self._release_jail()


class Backend(Operator):
    """Pipeline operator wrapping a token-in/token-out engine."""

    def __init__(self, tokenizer: BaseTokenizer):
        self._tokenizer = tokenizer

    async def generate(self, request: Context, next: AsyncEngine) -> ResponseStream:
        pre = PreprocessedRequest.from_dict(request.data)
        stream = await next.generate(request)
        return ResponseStream(self._postprocess(pre, stream, request), request.ctx)

    async def _postprocess(
        self, pre: PreprocessedRequest, stream: ResponseStream, request: Context
    ) -> AsyncIterator[Dict[str, Any]]:
        decoder = Decoder(self._tokenizer, pre.stop_conditions)
        prompt_tokens = len(pre.token_ids)
        completion_tokens = 0
        finished = False
        try:
            async for out in stream:
                if finished:
                    break
                engine_finish = out.get("finish_reason")
                emit_text = ""
                finish: Optional[FinishReason] = None
                for tok in out.get("token_ids", ()):  # usually exactly one
                    completion_tokens += 1
                    text, finish = decoder.step(tok)
                    emit_text += text
                    if finish is not None:
                        break
                if finish is None and engine_finish is not None:
                    emit_text += decoder.finish()
                    finish = FinishReason(engine_finish)
                if emit_text or finish is None:
                    item = dict(out)
                    item["text"] = emit_text
                    item["finish_reason"] = None
                    lp = out.get("logprobs")
                    if lp is not None:
                        # Render token ids to strings here — the only layer
                        # holding the tokenizer (OpenAI logprobs carry text).
                        toks = out.get("token_ids") or [0]
                        item["logprobs"] = {
                            "token": self._tokenizer.decode([toks[0]]),
                            "logprob": lp["logprob"],
                            "top": [
                                {
                                    "token": self._tokenizer.decode([tid]),
                                    "logprob": l,
                                }
                                for tid, l in lp.get("top", [])
                            ],
                        }
                    yield item
                if finish is not None:
                    finished = True
                    # tell the engine to release the slot if it doesn't know
                    request.stop_generating()
                    yield {
                        "token_ids": [],
                        "text": None,
                        "finish_reason": str(finish),
                        "usage": {
                            "prompt_tokens": prompt_tokens,
                            "completion_tokens": completion_tokens,
                            "total_tokens": prompt_tokens + completion_tokens,
                        },
                    }
            if not finished:
                # engine stream ended without a finish reason (e.g. cancelled)
                tail = decoder.finish()
                reason = (
                    FinishReason.CANCELLED if request.is_stopped else FinishReason.STOP
                )
                if tail:
                    yield {"token_ids": [], "text": tail, "finish_reason": None}
                yield {
                    "token_ids": [],
                    "text": None,
                    "finish_reason": str(reason),
                    "usage": {
                        "prompt_tokens": prompt_tokens,
                        "completion_tokens": completion_tokens,
                        "total_tokens": prompt_tokens + completion_tokens,
                    },
                }
        finally:
            await stream.aclose()
