"""Process supervisor: spawn + watch one subprocess per service worker.

Reference semantics: deploy/dynamo/sdk cli/serving.py:209-330 — circus there
(arbiter + one watcher per service); here an asyncio supervisor with
exponential-backoff restarts, graceful SIGTERM fan-out, and per-worker env
from the TPU allocator.  Also launches the hub (unless --hub given) and,
optionally, the OpenAI HTTP frontend, so ``python -m dynamo_tpu.sdk.runner
examples.graphs:Frontend -f cfg.yaml`` is a one-command deployment like
``dynamo serve``.
"""

from __future__ import annotations

import asyncio
import logging
import os
import signal
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .allocator import TpuAllocator
from .config import ENV_VAR, ServiceConfigStore
from .graph import discover_services, load_target
from .service import ServiceMeta

logger = logging.getLogger(__name__)


@dataclass
class WorkerProc:
    service: str
    index: int
    argv: List[str]
    env: Dict[str, str]
    proc: Optional[asyncio.subprocess.Process] = None
    restarts: int = 0


class Supervisor:
    MAX_RESTARTS = 5

    def __init__(self) -> None:
        self._workers: List[WorkerProc] = []
        self._stopping = False

    def add(self, service: str, index: int, argv: List[str], env: Dict[str, str]) -> None:
        self._workers.append(WorkerProc(service, index, argv, env))

    async def run(self) -> None:
        for w in self._workers:
            await self._spawn(w)
        watchers = [asyncio.create_task(self._watch(w)) for w in self._workers]

        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:
                pass
        await stop.wait()
        await self.shutdown()
        for t in watchers:
            t.cancel()

    async def _spawn(self, w: WorkerProc) -> None:
        env = dict(os.environ)
        env.update(w.env)
        w.proc = await asyncio.create_subprocess_exec(*w.argv, env=env)
        logger.info("spawned %s[%d] pid=%d", w.service, w.index, w.proc.pid)

    async def _watch(self, w: WorkerProc) -> None:
        try:
            while not self._stopping:
                assert w.proc is not None
                rc = await w.proc.wait()
                if self._stopping:
                    return
                w.restarts += 1
                if w.restarts > self.MAX_RESTARTS:
                    logger.error(
                        "%s[%d] exited rc=%s too many times; giving up",
                        w.service, w.index, rc,
                    )
                    return
                delay = min(30.0, 0.5 * (2 ** w.restarts))
                logger.warning(
                    "%s[%d] exited rc=%s; restart %d in %.1fs",
                    w.service, w.index, rc, w.restarts, delay,
                )
                await asyncio.sleep(delay)
                await self._spawn(w)
        except asyncio.CancelledError:
            pass

    async def shutdown(self, timeout: float = 10.0) -> None:
        self._stopping = True
        for w in self._workers:
            if w.proc and w.proc.returncode is None:
                w.proc.terminate()
        deadline = asyncio.get_running_loop().time() + timeout
        for w in self._workers:
            if w.proc is None:
                continue
            remaining = max(0.1, deadline - asyncio.get_running_loop().time())
            try:
                await asyncio.wait_for(w.proc.wait(), remaining)
            except asyncio.TimeoutError:
                w.proc.kill()  # reference exits 911 on shutdown timeout


async def serve_graph(
    target_spec: str,
    hub: Optional[str],
    config_file: Optional[str],
    http_port: Optional[int],
    router: str = "round_robin",
) -> None:
    entry = load_target(target_spec)
    services = discover_services(entry)
    configs = ServiceConfigStore.load(config_file)

    hub_proc: Optional[asyncio.subprocess.Process] = None
    if hub is None:
        hub = "127.0.0.1:6650"
        hub_proc = await asyncio.create_subprocess_exec(
            sys.executable, "-m", "dynamo_tpu.cli", "hub",
            "--host", "127.0.0.1", "--port", "6650",
        )
        await asyncio.sleep(1.0)

    allocator = TpuAllocator()
    sup = Supervisor()
    for cls in services:
        meta: ServiceMeta = cls._dynamo_meta
        svc_cfg = configs.for_service(meta.name)
        workers = int(svc_cfg.get("workers", meta.workers))
        module = cls.__module__
        for idx in range(workers):
            alloc = allocator.assign(meta.resources)
            env = dict(alloc.env)
            env[ENV_VAR] = configs.to_env()
            sup.add(
                meta.name,
                idx,
                [
                    sys.executable,
                    "-m",
                    "dynamo_tpu.sdk.worker_main",
                    f"{module}:{cls.__name__}",
                    "--hub",
                    hub,
                ],
                env,
            )

    if http_port is not None:
        sup.add(
            "http-frontend",
            0,
            [
                sys.executable, "-m", "dynamo_tpu.cli", "http",
                "--hub", hub, "--port", str(http_port), "--router", router,
            ],
            {"JAX_PLATFORMS": "cpu"},
        )

    print(
        f"serving graph {target_spec}: "
        + ", ".join(c._dynamo_meta.name for c in services)
        + (f" + OpenAI frontend :{http_port}" if http_port else ""),
        flush=True,
    )
    try:
        await sup.run()
    finally:
        if hub_proc is not None and hub_proc.returncode is None:
            hub_proc.terminate()


def main(argv=None) -> None:
    import argparse

    logging.basicConfig(level=logging.INFO)
    parser = argparse.ArgumentParser(prog="dynamo-tpu-serve")
    parser.add_argument("target", help="module:ServiceClassOrGraph")
    parser.add_argument("-f", "--config", default=None, help="service config YAML")
    parser.add_argument("--hub", default=None, help="existing hub (default: spawn one)")
    parser.add_argument("--http-port", type=int, default=None, help="also run the OpenAI frontend")
    parser.add_argument("--router", default="round_robin", choices=["random", "round_robin", "kv"])
    args = parser.parse_args(argv)
    try:
        asyncio.run(serve_graph(args.target, args.hub, args.config, args.http_port, args.router))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
