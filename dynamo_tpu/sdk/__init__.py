"""Serving SDK: declare component graphs in Python, run them supervised.

Reference semantics (not code): deploy/dynamo/sdk — ``@service`` classes with
``@dynamo_endpoint`` methods, ``depends()`` edges resolved to remote clients,
``link()`` graph composition, YAML per-service config, and a process
supervisor (circus there) that spawns one OS process per service worker and
registers each on the distributed runtime.  The TPU build replaces BentoML
with a plain dataclass service model and circus with an asyncio subprocess
supervisor, and the GPU allocator with a TPU chip allocator.
"""

from .config import ServiceConfigStore, load_service_configs  # noqa: F401
from .graph import Graph, discover_services  # noqa: F401
from .service import (  # noqa: F401
    DynamoService,
    async_on_start,
    depends,
    dynamo_endpoint,
    service,
)
