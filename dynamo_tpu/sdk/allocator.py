"""TPU resource allocator: assign chips to service workers.

Reference semantics: deploy/dynamo/sdk cli/allocator.py:35-136 — the
reference pins GPUs per worker via CUDA_VISIBLE_DEVICES; the TPU equivalent
pins chips via TPU runtime env (TPU_VISIBLE_CHIPS / JAX platform selection).
Workers that request no accelerator get JAX_PLATFORMS=cpu so they never
touch (or lock) the TPU runtime — important because a TPU chip is held
exclusively by one process.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class Allocation:
    env: Dict[str, str] = field(default_factory=dict)
    chips: List[int] = field(default_factory=list)


class TpuAllocator:
    """Hands out chip sets worker by worker; oversubscription is an error."""

    def __init__(self, total_chips: Optional[int] = None):
        if total_chips is None:
            total_chips = int(os.environ.get("DYN_TPU_CHIPS", "0") or 0)
            if total_chips == 0:
                try:
                    import jax

                    total_chips = sum(
                        1 for d in jax.devices() if d.platform == "tpu"
                    )
                except Exception:
                    total_chips = 0
        self.total_chips = total_chips
        self._next = 0

    def assign(self, resources: Dict) -> Allocation:
        want = int(resources.get("tpu", 0) or 0)
        if want == 0:
            return Allocation(env={"JAX_PLATFORMS": "cpu"})
        if self._next + want > self.total_chips:
            raise RuntimeError(
                f"TPU oversubscribed: need {want}, "
                f"{self.total_chips - self._next} of {self.total_chips} left"
            )
        chips = list(range(self._next, self._next + want))
        self._next += want
        return Allocation(
            env={"TPU_VISIBLE_CHIPS": ",".join(map(str, chips)),
                 "TPU_CHIPS_PER_PROCESS_BOUNDS": f"1,1,{want}"},
            chips=chips,
        )
