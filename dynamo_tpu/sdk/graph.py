"""Graph discovery + link composition.

Reference semantics: deploy/dynamo/sdk lib/service.py:36-56 (LinkedServices)
and the ``Graph.link()`` pattern in examples/llm/graphs/*.py — an entry
service plus its transitive ``depends()`` closure forms the deployable
graph; ``link`` can add edges dynamically (e.g. choosing which worker
implementation backs a processor at deploy time).
"""

from __future__ import annotations

import importlib
from typing import Dict, List, Set, Tuple, Type

from .service import Dependency, ServiceMeta, collect_dependencies


class Graph:
    """An entry service + extra linked edges."""

    def __init__(self, entry: Type):
        assert hasattr(entry, "_dynamo_meta"), f"{entry} is not a @service"
        self.entry = entry
        self._extra_edges: List[Tuple[Type, Type]] = []

    def link(self, frm: Type, to: Type, endpoint: str | None = None) -> "Graph":
        """Add a depends edge frm → to at graph-composition time."""
        dep = Dependency(to, endpoint)
        # Attach as a class attribute so workers resolve it like static deps.
        attr = f"_linked_{to.__name__.lower()}"
        setattr(frm, attr, dep)
        self._extra_edges.append((frm, to))
        return self

    def services(self) -> List[Type]:
        return discover_services(self.entry)


def discover_services(entry: Type) -> List[Type]:
    """Transitive closure over depends() edges, entry first, deterministic."""
    seen: Set[Type] = set()
    order: List[Type] = []

    def visit(cls: Type) -> None:
        if cls in seen:
            return
        seen.add(cls)
        order.append(cls)
        for dep in collect_dependencies(cls).values():
            visit(dep.target)
        # linked edges attached by Graph.link
        for name, member in vars(cls).items():
            if name.startswith("_linked_") and isinstance(member, Dependency):
                visit(member.target)

    visit(entry)
    return order


def load_target(spec: str) -> Type:
    """Resolve ``pkg.module:ClassName`` to the service class."""
    module_name, _, attr = spec.partition(":")
    if not attr:
        raise ValueError(f"graph target must be module:Class, got {spec!r}")
    module = importlib.import_module(module_name)
    target = getattr(module, attr)
    if isinstance(target, Graph):
        return target.entry
    return target
