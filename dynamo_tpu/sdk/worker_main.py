"""Worker entrypoint: one process serving one SDK service instance.

Reference semantics: deploy/dynamo/sdk cli/serve_dynamo.py:61-224 — connect
the DistributedRuntime, create the namespace/component, bind every
``@dynamo_endpoint`` method, run ``@async_on_start`` hooks, then serve until
signalled.  Spawned by the supervisor (runner.py) with config passed via the
DYN_SERVICE_CONFIG env var.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal
import sys
from typing import Any

from ..runtime.component import DistributedRuntime
from .config import load_service_configs
from .graph import load_target
from .service import ServiceMeta, collect_dependencies

logger = logging.getLogger(__name__)


async def run_worker(target_spec: str, hub: str) -> None:
    cls = load_target(target_spec)
    meta: ServiceMeta = cls._dynamo_meta
    configs = load_service_configs()
    svc_config = configs.for_service(meta.name)

    runtime = await DistributedRuntime.connect(hub)
    try:
        # Instantiate: pass config when the ctor accepts it.
        try:
            instance = cls(config=svc_config)
        except TypeError:
            instance = cls()
            instance.config = svc_config

        instance.runtime = runtime  # services may use it (queues, kv, ...)

        # Resolve depends() edges (class-level Dependency descriptors).
        for name, dep in collect_dependencies(cls).items():
            await dep.resolve(runtime)
        for name, member in vars(cls).items():
            if name.startswith("_linked_") and hasattr(member, "resolve"):
                await member.resolve(runtime)

        component = runtime.namespace(meta.namespace).component(meta.name)
        for ep_name in meta.endpoints:
            handler = getattr(instance, ep_name)
            await component.endpoint(
                getattr(handler, "_dynamo_endpoint", ep_name)
            ).serve_endpoint(handler)

        for hook_name in meta.on_start:
            await getattr(instance, hook_name)()

        print(f"service {meta.name} up ({len(meta.endpoints)} endpoints)", flush=True)

        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:
                pass
        await stop.wait()
    finally:
        await runtime.close()


def main(argv: Any = None) -> None:
    logging.basicConfig(level=logging.INFO)
    parser = argparse.ArgumentParser(prog="dynamo-tpu-worker")
    parser.add_argument("target", help="module:ServiceClass")
    parser.add_argument("--hub", required=True)
    args = parser.parse_args(argv)
    try:
        asyncio.run(run_worker(args.target, args.hub))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    sys.exit(main())
