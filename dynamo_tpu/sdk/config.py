"""Per-service configuration: YAML file + env JSON, merged per service.

Reference semantics: deploy/dynamo/sdk lib/config.py + cli/serving.py:228-243
— a ``-f config.yaml`` keyed by service name, distributed to worker
subprocesses through one env var (there DYNAMO_SERVICE_CONFIG, here
DYN_SERVICE_CONFIG) so every worker sees the same merged view.

YAML parsing: PyYAML when available, else a built-in reader for the strict
subset used by service configs (nested maps + scalars + flat lists).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

ENV_VAR = "DYN_SERVICE_CONFIG"


def _parse_scalar(text: str) -> Any:
    t = text.strip()
    if not t or t == "null" or t == "~":
        return None
    if t in ("true", "True"):
        return True
    if t in ("false", "False"):
        return False
    try:
        return int(t)
    except ValueError:
        pass
    try:
        return float(t)
    except ValueError:
        pass
    if len(t) >= 2 and t[0] == t[-1] and t[0] in "\"'":
        return t[1:-1]
    if t.startswith("[") and t.endswith("]"):
        inner = t[1:-1].strip()
        return [_parse_scalar(p) for p in inner.split(",")] if inner else []
    return t


def _parse_simple_yaml(text: str) -> Dict[str, Any]:
    """Indentation-based nested maps; enough for service config files."""
    root: Dict[str, Any] = {}
    stack = [(-1, root)]
    for raw in text.splitlines():
        line = raw.split("#", 1)[0].rstrip()
        if not line.strip():
            continue
        indent = len(line) - len(line.lstrip())
        key, sep, value = line.strip().partition(":")
        if not sep:
            continue
        while stack and indent <= stack[-1][0]:
            stack.pop()
        parent = stack[-1][1]
        if value.strip():
            parent[key.strip()] = _parse_scalar(value)
        else:
            child: Dict[str, Any] = {}
            parent[key.strip()] = child
            stack.append((indent, child))
    return root


def _load_yaml(path: str) -> Dict[str, Any]:
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    try:
        import yaml  # type: ignore

        return yaml.safe_load(text) or {}
    except ImportError:
        return _parse_simple_yaml(text)


class ServiceConfigStore:
    """Merged per-service config: file < env < explicit overrides."""

    def __init__(self, data: Optional[Dict[str, Dict[str, Any]]] = None):
        self._data: Dict[str, Dict[str, Any]] = data or {}

    @classmethod
    def load(cls, path: Optional[str] = None) -> "ServiceConfigStore":
        data: Dict[str, Dict[str, Any]] = {}
        if path:
            for svc, cfg in (_load_yaml(path) or {}).items():
                data.setdefault(svc, {}).update(cfg or {})
        env = os.environ.get(ENV_VAR)
        if env:
            for svc, cfg in json.loads(env).items():
                data.setdefault(svc, {}).update(cfg or {})
        return cls(data)

    def for_service(self, name: str) -> Dict[str, Any]:
        return dict(self._data.get(name, {}))

    def set(self, service: str, key: str, value: Any) -> None:
        self._data.setdefault(service, {})[key] = value

    def to_env(self) -> str:
        return json.dumps(self._data)

    def as_dict(self) -> Dict[str, Dict[str, Any]]:
        return {k: dict(v) for k, v in self._data.items()}


def load_service_configs(path: Optional[str] = None) -> ServiceConfigStore:
    return ServiceConfigStore.load(path)
