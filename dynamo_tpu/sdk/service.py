"""@service / @dynamo_endpoint / depends() — the SDK's declaration surface.

Reference semantics: deploy/dynamo/sdk/src/dynamo/sdk/lib/{service,
decorators,dependency}.py — a service is a class whose decorated methods
become distributed endpoints; ``depends(Other)`` declares a graph edge and
resolves, inside a running worker, to a routed client on the dependency's
endpoint.
"""

from __future__ import annotations

import inspect
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Type

from ..runtime.client import Client, RouterMode
from ..runtime.component import DistributedRuntime
from ..runtime.engine import Context, ResponseStream


@dataclass
class ServiceMeta:
    name: str
    namespace: str = "dynamo"
    workers: int = 1
    resources: Dict[str, Any] = field(default_factory=dict)  # e.g. {"tpu": 1}
    endpoints: List[str] = field(default_factory=list)
    on_start: List[str] = field(default_factory=list)
    config: Dict[str, Any] = field(default_factory=dict)  # merged YAML/env


def service(
    cls: Optional[Type] = None,
    *,
    namespace: str = "dynamo",
    workers: int = 1,
    resources: Optional[Dict[str, Any]] = None,
):
    """Class decorator: mark a class as a dynamo service."""

    def wrap(klass: Type) -> Type:
        endpoints = [
            name
            for name, member in inspect.getmembers(klass)
            if getattr(member, "_dynamo_endpoint", None)
        ]
        hooks = [
            name
            for name, member in inspect.getmembers(klass)
            if getattr(member, "_dynamo_on_start", False)
        ]
        klass._dynamo_meta = ServiceMeta(
            name=klass.__name__,
            namespace=namespace,
            workers=workers,
            resources=resources or {},
            endpoints=endpoints,
            on_start=hooks,
        )
        return klass

    return wrap(cls) if cls is not None else wrap


def dynamo_endpoint(fn: Optional[Callable] = None, *, name: Optional[str] = None):
    """Method decorator: expose an async-generator method as an endpoint."""

    def wrap(func: Callable) -> Callable:
        func._dynamo_endpoint = name or func.__name__
        return func

    return wrap(fn) if fn is not None else wrap


def async_on_start(fn: Callable) -> Callable:
    """Method decorator: run once after the worker's runtime is up."""
    fn._dynamo_on_start = True
    return fn


class Dependency:
    """A ``depends(Other)`` edge: descriptor that resolves to a client proxy.

    At class-definition time it records the edge (for graph discovery); at
    runtime (after ``resolve``) it proxies generate/direct/round_robin/random
    to a routed Client on the dependency's primary endpoint.
    """

    def __init__(self, target: Type, endpoint: Optional[str] = None):
        self.target = target
        meta: ServiceMeta = target._dynamo_meta
        self.endpoint_name = endpoint or (meta.endpoints[0] if meta.endpoints else "generate")
        self._client: Optional[Client] = None

    async def resolve(self, runtime: DistributedRuntime, router_mode=RouterMode.ROUND_ROBIN) -> None:
        meta: ServiceMeta = self.target._dynamo_meta
        ep = (
            runtime.namespace(meta.namespace)
            .component(meta.name)
            .endpoint(self.endpoint_name)
        )
        self._client = await ep.client(router_mode=router_mode)

    @property
    def client(self) -> Client:
        assert self._client is not None, "dependency not resolved (worker not started?)"
        return self._client

    # Proxy the client verbs (reference: sdk dependency __call__ surface).
    async def generate(self, request: Any, **kw) -> ResponseStream:
        req = request if isinstance(request, Context) else Context(request)
        return await self.client.generate(req, **kw)

    async def direct(self, request: Any, worker_id: int) -> ResponseStream:
        req = request if isinstance(request, Context) else Context(request)
        return await self.client.direct(req, worker_id)

    async def round_robin(self, request: Any) -> ResponseStream:
        req = request if isinstance(request, Context) else Context(request)
        return await self.client.round_robin(req)

    async def random(self, request: Any) -> ResponseStream:
        req = request if isinstance(request, Context) else Context(request)
        return await self.client.random(req)


def depends(target: Type, endpoint: Optional[str] = None) -> Dependency:
    return Dependency(target, endpoint)


class DynamoService:
    """Optional convenience base class giving services typed accessors."""

    _dynamo_meta: ServiceMeta

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        self.config = config or {}

    @classmethod
    def meta(cls) -> ServiceMeta:
        return cls._dynamo_meta


def collect_dependencies(cls: Type) -> Dict[str, Dependency]:
    """Class-level Dependency attributes, keyed by attribute name."""
    return {
        name: member
        for name, member in vars(cls).items()
        if isinstance(member, Dependency)
    }
