"""Cross-engine KV block transfer: host-staged export/import (the
cross-process wire format) and the same-process device-to-device path.

Split out of engine.py as a pure move (r5; VERDICT r4 weak #7).
"""

from __future__ import annotations

import asyncio
import time
import logging
from typing import TYPE_CHECKING, Any, Dict, List, Optional

if TYPE_CHECKING:  # annotation-only (transfer_blocks_device signature)
    from .engine import TpuEngine

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)


def _scales_close(a, b, rtol: float = 1e-3) -> bool:
    """Stored-representation scale compatibility for KV transfers.

    Exact equality would silently disable disagg transfers between two
    workers that each ran kv_scale='auto' (independent calibration drifts
    at the ULP level across device generations / compiler versions).  The
    tolerance covers exactly that ULP/compiler drift and NO more: beyond it
    the quantized rows genuinely encode different values, and importing
    them raw would carry a systematic dequantization error — such imports
    are rejected and the caller prefills locally (r4 review: the earlier 5%
    tolerance silently accepted up to ~5% of real scale error)."""
    if a is None or b is None:
        return a is None and b is None
    av = np.asarray(a, np.float32).reshape(-1)
    bv = np.asarray(b, np.float32).reshape(-1)
    if av.shape != bv.shape and av.size != 1 and bv.size != 1:
        return False
    return bool(np.allclose(av, bv, rtol=rtol))


class KvTransferMixin:
    async def export_prompt_blocks(
        self,
        token_ids: List[int],
        start_block: int = 0,
        max_blocks: int = 0,
        salt: Optional[str] = None,
        blocks: Optional[List[Any]] = None,
    ) -> Optional[Dict[str, Any]]:
        """Gather cached KV for ``token_ids``'s complete blocks to host.

        Exports the longest RESIDENT run starting at ``start_block`` (not
        all-or-nothing — a prompt that lost tail blocks to eviction still
        transfers its resident prefix; round-2 returned None in that case
        and recomputed everything).  ``max_blocks`` bounds the run (chunked
        transfer).  Returns None when nothing is resident at start_block.
        ``salt`` is the owning tenant's KV salt (llm/tenancy): tenant
        blocks seal under salted chained hashes, so an unsalted lookup
        cannot see them — and can never LEAK them to another tenant.

        ``blocks`` lets a caller that already holds the sealed chained-hash
        list (migration sends the same tokens chunk after chunk; the
        indexer sealed the chain once) pass it through instead of paying
        the O(len(tokens)) rehash per chunk.  Under ``__debug__`` the
        passed chain is asserted equal to a fresh recompute — a stale or
        wrongly-salted chain must fail loudly, not seal wrong bytes.
        """
        from ..tokens import hash_token_blocks

        if jax.process_count() > 1:
            # Sharded global pages can't be gathered from one host (same
            # restriction as host_cache_bytes); refuse cleanly at request
            # time so the caller falls back to local prefill instead of
            # hanging on a non-addressable array (ADVICE r3).
            return None
        if blocks is None:
            blocks = hash_token_blocks(token_ids, self.cfg.block_size, salt)
        elif __debug__:
            fresh = hash_token_blocks(token_ids, self.cfg.block_size, salt)
            assert [tb.sequence_hash for tb in blocks] == [
                tb.sequence_hash for tb in fresh
            ], "export_prompt_blocks: passed block chain != sealed recompute"
        ids: List[int] = []
        for tb in blocks[start_block:]:
            bid = self.kv._by_hash.get(tb.sequence_hash)
            if bid is None:
                break
            ids.append(bid)
            if max_blocks and len(ids) >= max_blocks:
                break
        if not ids:
            return None
        async with self._device_lock:
            pages = np.asarray(self.cache.pages[:, np.asarray(ids, np.int32)])
        k = pages[:, :, :, 0::2]  # [L, n, page_size, KV, hd]
        v = pages[:, :, :, 1::2]
        from .integrity import payload_block_checksums

        return {
            "n_blocks": len(ids),
            "start_block": start_block,
            "block_size": self.cfg.block_size,
            "dtype": str(k.dtype),
            # Stored representation metadata: the importer must match (a
            # different quantization scale/dtype would seal wrongly-scaled
            # KV under valid hashes).
            "kv_scale": self._kv_scale_repr(),
            "shape": list(k.shape),
            # Per-block content checksums stamped from the HBM gather (the
            # source of truth) — the importer verifies before sealing, so
            # a wire/staging bit-flip costs one block's recompute instead
            # of fleet-wide poison.  Omit-when-absent on the importer side
            # keeps checksum-less peers servable.
            "checksums": payload_block_checksums(k, v),
            "k": np.ascontiguousarray(k).tobytes(),
            "v": np.ascontiguousarray(v).tobytes(),
        }

    async def inject_blocks(
        self,
        token_ids: List[int],
        payload: Dict[str, Any],
        salt: Optional[str] = None,
        donor: Optional[int] = None,
    ) -> int:
        """Write transferred KV into this engine's cache as sealed blocks.

        ``payload["start_block"]`` supports chunked transfers: chunk k's
        blocks seal under their chained hashes as they arrive, so decode can
        overlap with the remaining chunks' transfer (match_prefix walks from
        block 0, so chunks are useful as soon as their predecessors landed —
        the sender streams them in order).

        When the payload carries per-block ``checksums`` they are VERIFIED
        against the parsed arrays before anything is allocated or sealed
        (the wire integrity boundary — covers cross-worker pull, migration
        push and disagg import alike): the verified prefix seals, the first
        corrupt block and everything after it is dropped and the hash
        negative-cached.  Payloads without checksums (older peers) inject
        unverified — omit-when-absent wire compat.  ``donor`` attributes a
        corrupt payload to its sender for the health watchdog's ledger.

        Returns the number of tokens covered by this injection.  The blocks
        are immediately released to the reuse pool (contents intact), so the
        very next generate() for these tokens admits with a prefix hit — no
        special remote-prefill state in the scheduler.
        """
        from ..tokens import hash_token_blocks

        start = int(payload.get("start_block", 0))
        # Tenant imports (llm/tenancy) seal under the tenant's salted hash
        # chain — the same identity the exporter read them under, so a
        # cross-tenant inject structurally cannot produce a matching hash.
        blocks = hash_token_blocks(token_ids, self.cfg.block_size, salt)[start:]
        n = min(int(payload["n_blocks"]), len(blocks))
        if n == 0:
            return 0
        blocks = blocks[:n]
        # Validate the payload BEFORE allocating: allocation can LRU-evict
        # sealed prefix-cache blocks, and an import that is about to be
        # rejected must never pay that eviction for blocks it frees right
        # back (the freed blocks return anonymous — the evicted contents
        # are gone for nothing).
        if int(payload.get("block_size", self.cfg.block_size)) != self.cfg.block_size:
            # Mismatched layouts would seal misaligned KV under valid hashes
            # — refuse and let the caller prefill locally.
            logger.warning(
                "rejecting KV import: block_size %s != local %s",
                payload.get("block_size"),
                self.cfg.block_size,
            )
            return 0
        local_scale = self._kv_scale_repr()
        if (
            payload.get("dtype", str(jnp.dtype(self.cfg.cache_dtype)))
            != str(jnp.dtype(self.cfg.cache_dtype))
            or not _scales_close(
                payload.get("kv_scale", local_scale), local_scale
            )
        ):
            # Stored-representation mismatch (quantization dtype/scale):
            # importing raw rows would mis-scale the prefix silently.
            logger.warning(
                "rejecting KV import: stored repr %s/scale %s != local %s/%s",
                payload.get("dtype"), payload.get("kv_scale"),
                jnp.dtype(self.cfg.cache_dtype), local_scale,
            )
            return 0
        # Parse/validate the payload ARRAYS before allocating too: a
        # malformed payload (truncated bytes, inconsistent shape) raising
        # after allocate_sequence would leak the freshly-taken blocks AND
        # may already have LRU-evicted sealed contents to take them.
        shape = tuple(payload["shape"])
        name = payload["dtype"]
        dt = jnp.dtype(name)  # ml_dtypes registers bf16/fp8 names
        expected = int(np.prod(shape)) * dt.itemsize
        if len(payload["k"]) != expected or len(payload["v"]) != expected:
            # Byte-length mismatch against the claimed shape: reject before
            # any array is even viewed, let alone copied.
            logger.warning("rejecting KV import: payload bytes != shape")
            return 0
        if shape[1] < n:
            logger.warning(
                "rejecting KV import: payload carries %d pages for n_blocks "
                "%d", shape[1], n,
            )
            return 0
        if not self.kv.would_fit(blocks, n):
            # Destination-budget reject-early: an import the block pool
            # cannot take must fail BEFORE the interleave below stages a
            # payload-sized copy in host RAM (and before allocation could
            # evict sealed contents it frees right back).
            logger.warning(
                "rejecting KV import: %d blocks exceed free KV capacity", n
            )
            return 0
        try:
            k = np.frombuffer(payload["k"], dtype=dt).reshape(shape)[:, :n]
            v = np.frombuffer(payload["v"], dtype=dt).reshape(shape)[:, :n]
        except ValueError:
            logger.warning("rejecting KV import: malformed payload arrays")
            return 0
        from ..runtime.faultinject import faults

        if faults.enabled and faults.should("kv_corrupt", "wire"):
            # Chaos hook: flip one byte of the staged K payload — models a
            # wire/staging bit-flip the structural checks cannot see.
            from .integrity import flip_array_byte

            k = flip_array_byte(k)
        sums = payload.get("checksums")
        if sums is not None:
            # The wire integrity boundary: verify every block BEFORE the
            # interleave copy (and long before allocation/sealing).  The
            # verified prefix stays usable; the first corrupt block
            # truncates the import — its chained descendants are
            # unreachable without it, so nothing poisoned can ever seal.
            from ..llm.metrics import kv_integrity_metrics
            from .integrity import payload_block_checksums

            got = payload_block_checksums(k, v)
            valid = n
            for i in range(n):
                if i >= len(sums) or int(sums[i]) != got[i]:
                    valid = i
                    break
            kv_integrity_metrics.verified_total["wire"] += valid
            if valid < n:
                self._record_corruption(
                    "wire", blocks[valid].sequence_hash, donor=donor
                )
                self._flush_tier_events()
                logger.warning(
                    "KV import failed checksum at block %d/%d; sealing the "
                    "verified prefix only", valid, n,
                )
                n = valid
                if n == 0:
                    return 0
                blocks = blocks[:n]
                k = k[:, :n]
                v = v[:, :n]
        # Interleave back to combined pages [L, n, ps, 2KV, hd] (K even).
        comb = np.stack([k, v], axis=4).reshape(
            k.shape[0], n, k.shape[2], 2 * k.shape[3], k.shape[4]
        )
        alloc = self.kv.allocate_sequence(blocks, n, count_hits=False)
        if alloc is None:
            return 0  # no capacity; caller falls back to local prefill
        ids, cached = alloc
        # Pad the page count to a power-of-two bucket so _inject_fn compiles
        # once per bucket, not once per distinct imported prompt length.
        pad = 1 << max(0, (n - 1).bit_length())
        page_ids = np.full((pad,), self.cfg.num_blocks, np.int32)  # OOB pad
        page_ids[:n] = ids
        comb_p = np.zeros(comb.shape[:1] + (pad,) + comb.shape[2:], comb.dtype)
        comb_p[:, :n] = comb

        try:
            async with self._device_lock:
                # Lock-HOLD wall only (t0 inside the lock — queueing behind a
                # decode chunk is the scheduler working as intended, not import
                # cost): the decode/transfer-overlap contract is that an import
                # never blocks decode longer than ONE chunk's scatter
                # (tests/test_disagg.py overlap test reads this).
                t0 = time.perf_counter()
                # Publish under the device lock (broadcast order == enqueue
                # order; see _run_unified).
                if self._publisher is not None:
                    await self._publisher.publish("inject", (page_ids, comb_p))
                # to_thread: compile/execute must not stall the engine loop.
                self.cache = await asyncio.to_thread(
                    self._inject_fn, self.cache, *self._prep((page_ids, comb_p))
                )
                hold = time.perf_counter() - t0
        except BaseException:
            # Mid-transfer failure: the blocks were never sealed — return
            # them to the pool instead of leaking them as allocated-forever
            # scratch, then surface the error (the sender retries/drops and
            # the decode side's timeout falls back to local prefill).
            self.kv.free_sequence(ids)
            raise
        self.step_trace.append(("inject", hold, n, 0))
        for bid, tb in zip(ids, blocks):
            self.kv.seal_block(bid, tb)
        self.kv.free_sequence(ids)
        return n * self.cfg.block_size

    async def inject_blocks_from_device(
        self,
        token_ids: List[int],
        pages_dev,
        n: int,
        start_block: int = 0,
        salt: Optional[str] = None,
    ) -> int:
        """Seal ``n`` transferred blocks whose pages are ALREADY on device
        (the ICI/device_put fast path — no host staging).  ``pages_dev`` is
        [L, pad, ps, 2KV, hd] with the first n slots valid."""
        from ..tokens import hash_token_blocks

        if jax.process_count() > 1:
            # Device handles can't cross the leader/follower broadcast; the
            # host-staged inject_blocks path handles multi-host transfers.
            return 0
        blocks = hash_token_blocks(token_ids, self.cfg.block_size, salt)[
            start_block:
        ]
        n = min(n, len(blocks))
        if n == 0:
            return 0
        # Validate config/capacity BEFORE allocating (mirror of the host
        # path's fix): a mismatched layout would seal wrong KV under valid
        # hashes, and a doomed allocation must never LRU-evict sealed
        # contents it immediately frees back.  transfer_blocks_device checks
        # these on the source side too, but this entry point is public
        # (disagg transfer_direct) and must be safe on its own.
        if (
            pages_dev.ndim != 5
            or pages_dev.shape[0] != self.cache.pages.shape[0]
            or pages_dev.shape[1] < n
            or pages_dev.shape[2:] != self.cache.pages.shape[2:]
            or pages_dev.dtype != self.cache.pages.dtype
        ):
            logger.warning(
                "rejecting device KV import: pages %s/%s vs local cache %s/%s",
                getattr(pages_dev, "shape", None), pages_dev.dtype,
                self.cache.pages.shape, self.cache.pages.dtype,
            )
            return 0
        alloc = self.kv.allocate_sequence(blocks[:n], n, count_hits=False)
        if alloc is None:
            return 0
        ids, _ = alloc
        pad = pages_dev.shape[1]
        page_ids = np.full((pad,), self.cfg.num_blocks, np.int32)  # OOB pad
        page_ids[:n] = ids
        try:
            async with self._device_lock:
                t0 = time.perf_counter()  # lock HOLD, not wait (see inject_blocks)
                self.cache = await asyncio.to_thread(
                    self._inject_fn, self.cache, page_ids, pages_dev
                )
                hold = time.perf_counter() - t0
        except BaseException:
            self.kv.free_sequence(ids)  # roll back: blocks never sealed
            raise
        self.step_trace.append(("inject", hold, n, 0))
        for bid, tb in zip(ids, blocks[:n]):
            self.kv.seal_block(bid, tb)
        self.kv.free_sequence(ids)
        return n * self.cfg.block_size

    def _pin_prefix(self, token_ids: List[int], salt: Optional[str] = None):
        """Take references on the resident prefix blocks of ``token_ids``
        (see generate(): keeps pre-admission sp/restore work alive)."""
        from ..tokens import hash_token_blocks

        return self.kv.acquire_prefix(
            hash_token_blocks(token_ids, self.cfg.block_size, salt)
        )

async def transfer_blocks_device(
    src: TpuEngine, dst: TpuEngine, token_ids, salt: Optional[str] = None
) -> int:
    """Co-located prefill→decode KV transfer that never stages in host RAM:
    device gather from the source cache → ``jax.device_put`` onto the
    destination's sharding → in-place scatter.  On one chip this is an HBM
    copy; across chips of a shared slice the put rides ICI — the reference's
    NIXL/GPUDirect block path (SURVEY §2.6) for same-slice deployments.
    Returns tokens covered (the longest resident prefix run)."""
    from ..tokens import hash_token_blocks

    if jax.process_count() > 1:
        return 0  # same single-process restriction as export_prompt_blocks
    if src.cfg.block_size != dst.cfg.block_size:
        return 0
    if src.cache.pages.shape[0] != dst.cache.pages.shape[0]:
        return 0  # different layer counts: not the same model
    if src.cache.pages.dtype != dst.cache.pages.dtype or not _scales_close(
        src._kv_scale_repr(), dst._kv_scale_repr()
    ):
        return 0  # stored representation differs: host path will also refuse
    blocks = hash_token_blocks(token_ids, src.cfg.block_size, salt)
    src_ids: List[int] = []
    for tb in blocks:
        bid = src.kv._by_hash.get(tb.sequence_hash)
        if bid is None:
            break
        src_ids.append(bid)
    if not src_ids:
        return 0
    n = len(src_ids)
    pad = 1 << max(0, (n - 1).bit_length())
    gather_ids = np.zeros((pad,), np.int32)
    gather_ids[:n] = src_ids
    async with src._device_lock:
        pages = await asyncio.to_thread(src._gather_fn, src.cache, gather_ids)
    if dst.mesh is not None:
        pages = jax.device_put(
            pages, jax.tree_util.tree_leaves(dst.cache)[0].sharding
        )
    elif pages.devices() != dst.cache.pages.devices():
        pages = jax.device_put(pages, next(iter(dst.cache.pages.devices())))
    return await dst.inject_blocks_from_device(token_ids, pages, n, salt=salt)
