"""Draft-free speculative decoding: n-gram proposer + in-step verification.

Decode is memory-bound (r5: decode MFU 54.89% with the fused pipeline —
every decode step streams the full weights for ONE token per row), so the
remaining hot-path lever is verifying several tokens per weight stream.
Classic speculative decoding (Leviathan et al., ICML 2023) needs a draft
model; the prompt-lookup variant (Saxena 2023) replaces it with an n-gram
match against the sequence's OWN prompt+output history — free drafts that
win hardest on the prefix-heavy templated traffic the KV-router already
optimizes for.

The engine needs no new device code.  The unified ragged program already
mixes rows of arbitrary q_len/kv_len with per-row sampling, so a draft of
``k`` tokens verifies as ``k+1`` SINGLE-TOKEN ROWS of one unified step:
row ``j`` feeds draft position ``num_computed + j`` with
``kv_len = num_computed + j + 1`` over the sequence's own block table,
producing that position's logits AND its seeded sample in the same
dispatch (ops/sampling.py draws from ``fold_in(PRNGKey(seed), step)``
where ``step`` is the row's output-token index, so the sample at a
position depends only on the committed prefix — not on how it was
batched).

Acceptance is therefore EXACT-STREAM: accept the longest draft prefix
that matches the sampled tokens row by row.  Under greedy this is the
argmax match of Leviathan's Theorem 1; under temperature>0 the sampled
token at each position IS the token non-speculative decoding would have
drawn (same seed, same step, same logits), so speculation on/off produces
identical token streams at ANY temperature — a strictly stronger property
than distribution-level rejection sampling, and the one the tier-1
equivalence gate asserts.

Rollback is bookkeeping-only: rejected rows wrote KV into slots past
``num_computed``, but blocks only seal (hash-publish) once accepted
tokens cover them, so a rejected tail is plain scratch that the next real
token overwrites.  ``num_computed`` simply does not advance past the
accepted prefix.

The per-sequence adaptive controller moves each sequence's draft length
``k`` inside [k_min, k] on acceptance results and benches collapsed
proposers (EWMA below ``accept_floor``) for ``cooldown_tokens`` committed
tokens; when no sequence drafts — or the expected tokens-per-round-trip
falls below the fused pipeline's ``decode_steps`` per row — the engine
falls back to the fused multi-step pipeline unchanged.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..llm.metrics import spec_metrics
from ..models.llama import RaggedBatch
from .config import SpecDecodeConfig
from .scheduler import SequenceState, StepPlan

logger = logging.getLogger(__name__)


def propose_ngram(
    hist: np.ndarray, ngram_min: int, ngram_max: int, k: int
) -> np.ndarray:
    """Prompt-lookup proposal: match the last ``n`` tokens (longest ``n``
    first) against the rest of ``hist`` and return up to ``k`` tokens that
    followed an earlier occurrence — the most recent one whose
    continuation covers ``k`` (recency beats the canonical first-match on
    drifting templated traffic, but a truncated continuation must not cap
    drafts at period-1 on short loops).  Vectorized numpy: one
    sliding-window comparison per tried ``n``.  Empty when nothing
    matches."""
    empty = np.empty((0,), dtype=hist.dtype)
    size = int(hist.size)
    if k < 1 or size < ngram_min + 1:
        return empty
    # Windows over hist[:-1]: a match always has >= 1 continuation token,
    # and the suffix can never match itself.
    for n in range(min(ngram_max, size - 1), ngram_min - 1, -1):
        pattern = hist[size - n :]
        windows = np.lib.stride_tricks.sliding_window_view(
            hist[: size - 1], n
        )
        hits = np.nonzero((windows == pattern).all(axis=1))[0]
        if hits.size:
            # Latest hit whose continuation still covers k tokens; when
            # none does (short periodic loops — every late hit runs into
            # the end of history), the hit with the longest continuation.
            # Pure recency would cap drafts at period-1 tokens exactly on
            # the loops speculation wins hardest on.
            cont = size - (hits + n)
            full = hits[cont >= k]
            start = int(full[-1] if full.size else hits[np.argmax(cont)]) + n
            return hist[start : start + k].copy()
    return empty


class AcceptanceController:
    """Per-sequence adaptive draft length, EWMA-driven.

    State lives on the SequenceState (spec_k / spec_ewma /
    spec_bench_until) so it follows the request through preemption; the
    controller itself is pure policy."""

    def __init__(self, sd: SpecDecodeConfig):
        self.sd = sd

    def current_k(self, seq: SequenceState) -> int:
        sd = self.sd
        if seq.spec_k < 0:
            seq.spec_k = sd.k
        if seq.spec_bench_until >= 0:
            if seq.num_output_tokens < seq.spec_bench_until:
                return 0
            # Cooldown served: re-probe gently (k_min) with the EWMA reset
            # above the floor so one miss doesn't instantly re-bench.
            seq.spec_bench_until = -1
            seq.spec_k = sd.k_min
            seq.spec_ewma = min(1.0, 2.0 * sd.accept_floor)
        return seq.spec_k

    def record(self, seq: SequenceState, drafted: int, accepted: int) -> None:
        sd = self.sd
        if drafted <= 0:
            return
        ratio = accepted / drafted
        seq.spec_ewma += sd.ewma_alpha * (ratio - seq.spec_ewma)
        if accepted >= drafted:
            # Fully accepted: the match run is longer than we dared — grow.
            seq.spec_k = min(sd.k, max(seq.spec_k + 1, seq.spec_k * 2))
        else:
            # Partial/none: next draft needs only cover the observed run.
            seq.spec_k = max(sd.k_min, min(seq.spec_k, accepted + 1))
        if seq.spec_ewma < sd.accept_floor:
            seq.spec_bench_until = seq.num_output_tokens + sd.cooldown_tokens


class SpecDecodeMixin:
    """TpuEngine methods for the speculative decode path (engine.py mixes
    this in next to the fused-pipeline mixin; ``self._spec_ctl`` is the
    AcceptanceController, or None when spec_decode.enable is false)."""

    # Session-probe backoff: accept rounds to skip after a probe whose
    # drafts failed the engagement bar (otherwise a batch that drafts but
    # never engages re-scans every member's history every chunk).
    _spec_probe_skip = 0
    _spec_probe_miss = 0

    # ------------------------------------------------------------- proposal
    def _spec_draft_for(
        self, seq: SequenceState, start: int, rows_free: int
    ) -> Optional[np.ndarray]:
        """One sequence's draft candidate at position ``start`` — budgeted
        against free batch rows and the sequence's remaining output /
        context / table headroom, but NOT against KV block allocation
        (allocation-free so the fused pipeline can probe mid-session)."""
        cfg = self.cfg
        sd = cfg.spec_decode
        if not seq.spec_enabled:
            return None
        if seq.freq_penalty != 0 or seq.pres_penalty != 0:
            # Penalty counts are built per dispatch; mid-draft accepts
            # would need in-window count updates — not worth the HLO.
            return None
        k = self._spec_ctl.current_k(seq)
        if k < 1:
            return None
        if seq.total_tokens < seq.spec_next_try:
            return None  # backing off after misses: skip the scan entirely
        out_budget = (
            seq.max_new_tokens - seq.num_output_tokens
            if seq.max_new_tokens is not None
            else cfg.max_model_len
        )
        len_budget = cfg.max_model_len - seq.total_tokens
        cap = min(
            k,
            rows_free,
            out_budget - 1,
            len_budget - 1,
            cfg.max_blocks_per_seq * cfg.block_size - start - 1,
        )
        if cap < 1:
            return None
        # Slice the tails BEFORE concatenating: building the full
        # prompt+output list first would make every proposal O(context),
        # defeating the lookback bound at long contexts.
        lb = sd.lookback
        if lb and len(seq.prompt) + len(seq.output) > lb:
            out_tail = seq.output[-lb:]
            need = lb - len(out_tail)
            hist_list = (seq.prompt[-need:] if need > 0 else []) + out_tail
        else:
            hist_list = seq.prompt + seq.output
        hist = np.asarray(hist_list, np.int64)
        d = propose_ngram(hist, sd.ngram_min, sd.ngram_max, cap)
        if d.size == 0:
            # Exponential miss backoff (2..64 tokens): random traffic must
            # not pay a history scan per scheduling round forever.
            seq.spec_miss = min(seq.spec_miss + 1, 6)
            seq.spec_next_try = seq.total_tokens + (1 << seq.spec_miss)
            return None
        seq.spec_miss = 0
        seq.spec_next_try = 0
        return d

    def _spec_collect(
        self, pairs: List[Tuple[SequenceState, int]], rows_free: int
    ) -> List[Tuple[SequenceState, List[int]]]:
        """Draft candidates for (seq, start) pairs, trimmed to the free-row
        budget.  Trimming pops from the LONGEST draft first, so the row
        budget spreads across drafting sequences instead of the plan-order
        head draining it."""
        cands: List[Tuple[SequenceState, List[int]]] = []
        for seq, start in pairs:
            d = self._spec_draft_for(seq, start, rows_free)
            if d is not None:
                cands.append((seq, [int(x) for x in d]))
        total = sum(len(d) for _, d in cands)
        while total > rows_free:
            _, longest = max(cands, key=lambda c: len(c[1]))
            longest.pop()
            total -= 1
        return [(s, d) for s, d in cands if d]

    def _spec_engaged(self, expected: int, n_decode: int) -> bool:
        """Engagement bar vs the fused pipeline: a verification step
        streams the weights once where a fused chunk streams them
        ``decode_steps`` times, so speculation wins well below raw
        tokens-per-round-trip parity (pipeline_margin)."""
        cfg = self.cfg
        if cfg.decode_steps <= 1:
            return True
        bar = cfg.spec_decode.pipeline_margin * n_decode * cfg.decode_steps
        return expected >= bar

    def _spec_propose(self, plan: StepPlan) -> Dict[str, List[int]]:
        """Drafts for this plan's decode rows: {request_id: tokens}.

        Each draft token is one extra row of the unified step; for
        pure-decode plans speculation must also beat the fused pipeline
        (_spec_engaged), else stand down — the adaptive controller keeps
        dead proposers from dragging live batches."""
        cfg = self.cfg
        decode_items = [
            (seq, start)
            for seq, start, n in plan.items
            if n == 1 and start >= len(seq.prompt)
        ]
        if not decode_items:
            return {}
        rows_free = cfg.max_batch - len(plan.items)
        if rows_free <= 0:
            return {}
        cands = self._spec_collect(decode_items, rows_free)
        if not cands:
            return {}
        if plan.pure_decode:
            # Engagement BEFORE allocation: standing down must not have
            # paid _ensure_slot evictions (which can LRU-evict sealed
            # prefix-cache blocks) for drafts that never run.
            expected = sum(len(d) + 1 for _, d in cands) + (
                len(decode_items) - len(cands)
            )
            if not self._spec_engaged(expected, len(decode_items)):
                spec_metrics.fallback_total += 1
                return {}
        drafts: Dict[str, List[int]] = {}
        bs = cfg.block_size
        for seq, d in cands:
            start = seq.num_computed
            # KV slots for the fed tail token + every draft position; on a
            # tight pool, trim the draft to the blocks we actually got.
            if not self.scheduler._ensure_slot(seq, lookahead=len(d) + 1):
                limit = len(seq.block_ids) * bs
                d = d[: max(0, limit - start - 1)]
                if not d:
                    continue
            drafts[seq.request_id] = d
        return drafts

    def _spec_session_probe(self, members: List[SequenceState]) -> bool:
        """Would speculation beat the fused pipeline for ``members`` RIGHT
        NOW?  Called by the pipeline after each accept round (drafts only
        appear as output accrues — a session started draft-less must not
        lock repetitive traffic out of speculation).  Pure numpy over the
        committed history, no allocation; a True verdict drains the
        session and lets the next schedule() re-propose for real."""
        if self._spec_ctl is None:
            return False
        rows_free = self.cfg.max_batch - len(members)
        if rows_free <= 0:
            return False  # saturated batch: no rows for draft expansion
        if any(seq.finished for seq in members):
            return False  # session is about to rebuild anyway
        if self._spec_probe_skip > 0:
            self._spec_probe_skip -= 1
            return False
        cands = self._spec_collect(
            [(seq, seq.num_computed) for seq in members], rows_free
        )
        if not cands:
            return False
        expected = sum(len(d) + 1 for _, d in cands) + (
            len(members) - len(cands)
        )
        if not self._spec_engaged(expected, len(members)):
            # Drafts exist but are not worth leaving the pipeline for;
            # exponential probe backoff (the per-seq miss backoff never
            # fires here because the scans HIT) caps the re-scan rate.
            self._spec_probe_miss = min(self._spec_probe_miss + 1, 3)
            self._spec_probe_skip = 1 << self._spec_probe_miss
            return False
        self._spec_probe_miss = 0
        return True

    # ------------------------------------------------------------- dispatch
    async def _run_spec_unified(
        self, plan: StepPlan, drafts: Dict[str, List[int]]
    ) -> None:
        """One unified ragged step verifying every drafted row in-step.

        Drafted decode rows expand to ``1 + len(draft)`` single-token rows
        (per-position logits + seeded samples); prefill chunks and
        undrafted decode rows ride along exactly as in _run_unified.  The
        token fetch is deferred (kind "spec"): acceptance, rollback and
        metrics land at the harvest point."""
        cfg = self.cfg
        bs, S, PP = cfg.block_size, cfg.max_batch, cfg.max_blocks_per_seq
        tok_l: List[int] = []
        pos_l: List[int] = []
        slot_l: List[int] = []
        aslot_l: List[int] = []  # per-token LoRA slot (llm/tenancy)
        kv_lens = np.zeros((S,), np.int32)
        tables = np.zeros((S, PP), np.int32)
        cu = np.zeros((S + 1,), np.int32)
        row_seqs: List[SequenceState] = []
        offsets: List[int] = []
        gstates: List[Optional[int]] = []
        spec_groups: List[Tuple[SequenceState, int, List[int]]] = []
        plain_rows: List[Tuple[SequenceState, int, int, int]] = []
        at = 0
        row = 0
        for seq, start, n in plan.items:
            d = (
                drafts.get(seq.request_id)
                if n == 1 and start >= len(seq.prompt)
                else None
            )
            all_toks = seq.prompt + seq.output
            blk = np.asarray(seq.block_ids, np.int32)
            if d:
                feed = [all_toks[start]] + list(d)
                # Grammar × spec (llm/tenancy): the logit mask must hold at
                # EVERY draft-verify position — row j samples output
                # position j, whose automaton state is the current state
                # advanced through draft[0..j-1] (acceptance implies the
                # committed tokens ARE the draft tokens, so these states
                # are exact for every committable position).  A draft token
                # the automaton rejects makes all later states -1 =
                # unconstrained: their samples can never commit (the
                # admissible sample at j must differ from the inadmissible
                # draft[j], so acceptance breaks there), but they must not
                # draw from an all-masked distribution.
                st: Optional[int] = (
                    seq.grammar_state if seq.grammar is not None else None
                )
                row_states: List[Optional[int]] = []
                for dt in d:
                    row_states.append(st if st is not None else None)
                    if st is not None and st != -1:
                        nxt = seq.grammar.advance(st, int(dt))
                        st = -1 if nxt is None else nxt
                    # st stays -1 (or None for unconstrained seqs)
                row_states.append(st)
                row0 = row
                for j, t in enumerate(feed):
                    p = start + j
                    tok_l.append(int(t))
                    pos_l.append(p)
                    slot_l.append(int(blk[p // bs]) * bs + p % bs)
                    aslot_l.append(seq.adapter_slot)
                    self._tables_row(tables, row, seq)
                    kv_lens[row] = p + 1
                    at += 1
                    cu[row + 1] = at
                    row_seqs.append(seq)
                    offsets.append(j)
                    gstates.append(row_states[j])
                    row += 1
                seq.awaiting_fetch = True
                spec_groups.append((seq, row0, list(d)))
            else:
                tok_l.extend(all_toks[start : start + n])
                p = np.arange(start, start + n, dtype=np.int32)
                pos_l.extend(p.tolist())
                slot_l.extend((blk[p // bs] * bs + p % bs).tolist())
                aslot_l.extend([seq.adapter_slot] * n)
                self._tables_row(tables, row, seq)
                kv_lens[row] = start + n
                at += n
                cu[row + 1] = at
                row_seqs.append(seq)
                offsets.append(0)
                gstates.append(None)  # plain row: current automaton state
                plain_rows.append((seq, start, n, row))
                if start + n >= len(seq.prompt):
                    # Parked BEFORE the dispatch, like drafted rows above:
                    # quiescence pollers (freeze_sequence) must see the
                    # in-flight token from commit time (engine/migrate.py).
                    seq.awaiting_fetch = True
                row += 1
        cu[row + 1 :] = at
        T = cfg.bucket_tokens(at)
        tok = np.zeros((T,), np.int32)
        tok[:at] = tok_l
        pos = np.zeros((T,), np.int32)
        pos[:at] = pos_l
        slots = np.full((T,), -1, np.int32)
        slots[:at] = slot_l
        # LoRA rows in a spec step (llm/tenancy): the verify forward must
        # apply each row's OWN adapter — and LoRA-less engines must keep
        # the None leaf so their compiled programs are unchanged.
        if self._lora_registry is not None:
            aslots: Any = np.full((T,), -1, np.int32)
            aslots[:at] = aslot_l
        else:
            aslots = None
        rb = RaggedBatch(
            token_ids=tok,
            positions=pos,
            slot_mapping=slots,
            kv_lens=kv_lens,
            page_indices=tables,
            cu_q_lens=cu,
            num_seqs=np.asarray([row], np.int32),
            adapter_slots=aslots,
        )
        samp = self._sampling_arrays(
            row_seqs, step_offsets=offsets, grammar_states=gstates
        )
        need_lp = bool(samp.need_logprobs)
        if self._rep_sharding is not None:
            rb_d, samp_d = self._prep((rb, samp))
        else:
            rb_d, samp_d = rb, samp
        step = self._step_fn
        while self._pending_fetches and self._pending_fetches[0][1].done():
            await self._harvest_pending()  # free: task already complete

        def run():
            out, self.cache = step(self.params, self.cache, rb_d, samp_d)
            # Capability probed once at engine init (pipeline._start_d2h) —
            # no per-dispatch AttributeError swallowing.
            self._start_d2h(out, need_lp)
            return out

        t0 = time.perf_counter()
        async with self._device_lock:
            # Broadcast order must equal enqueue order (see _run_unified).
            if self._publisher is not None:
                await self._publisher.publish(
                    "unified",
                    (rb, jax.tree_util.tree_map(np.asarray, samp)),
                )
            # Same decode-stall watchdog as every other device-op await
            # (engine/pipeline.py _await_device): a wedge inside a spec
            # verify step is the identical hang class.
            out = await self._await_device(
                self._device_task(run), "spec_dispatch", len(plan.items)
            )
        self.step_trace.append(
            ("spec_verify", time.perf_counter() - t0, len(plan.items), at)
        )
        spec_metrics.dispatches_total += 1

        first_rows: List[Tuple[SequenceState, int]] = []
        for seq, start, n, r in plain_rows:
            if seq.finished:
                seq.awaiting_fetch = False  # pre-marked; never parked
                continue
            if start >= len(seq.prompt):
                # Decode row: the fed token joins the hash stream.
                seq.block_seq.append((seq.prompt + seq.output)[start])
            seq.num_computed = start + n
            self._seal_completed_blocks(seq)
            if not seq.in_prefill:
                seq.awaiting_fetch = True
                first_rows.append((seq, r))
        self._stash_fetch("spec", out, need_lp, first_rows, spec_groups)

    # -------------------------------------------------------------- harvest
    def _harvest_spec(self, entry, sampled, logp, top_ids, top_lp) -> None:
        """Apply a spec step's tokens: plain rows accept like "first"
        entries; each drafted group commits its longest sampled-matching
        prefix plus the correcting sample, rolls the rest back (num_computed
        simply stops at the accepted frontier — rejected KV is unsealed
        scratch), and feeds the acceptance controller."""
        first_rows, groups = entry[2], entry[3]
        for seq, i in first_rows:
            seq.awaiting_fetch = False
            if seq.finished:
                continue  # cancelled while the token was in flight
            self._accept_token(
                seq,
                int(sampled[i]),
                logprobs=self._lp_info(seq, i, logp, top_ids, top_lp),
            )
        bs = self.cfg.block_size
        ctl = self._spec_ctl
        finished: List[SequenceState] = []
        for seq, row0, draft in groups:
            seq.awaiting_fetch = False
            if seq.finished:
                continue
            accepted = committed = 0
            limit = len(seq.block_ids) * bs
            for j in range(len(draft) + 1):
                if seq.num_computed >= limit:
                    break  # beyond allocation: never KV-backed
                fed = (seq.prompt + seq.output)[seq.num_computed]
                if seq.num_computed >= len(seq.prompt):
                    seq.block_seq.append(fed)
                seq.num_computed += 1
                self._seal_completed_blocks(seq)
                tok = int(sampled[row0 + j])
                self._accept_token(
                    seq,
                    tok,
                    defer_removal=True,
                    logprobs=self._lp_info(
                        seq, row0 + j, logp, top_ids, top_lp
                    ),
                )
                committed += 1
                if seq.finished:
                    finished.append(seq)
                    break
                if j < len(draft):
                    if int(draft[j]) != tok:
                        break  # rejection: rows past here are rolled back
                    accepted += 1
            ctl.record(seq, drafted=len(draft), accepted=accepted)
            spec_metrics.drafted_total += len(draft)
            spec_metrics.accepted_total += accepted
            spec_metrics.emitted_total += committed
        for seq in finished:
            self.scheduler.remove(seq)
