"""Fused decode pipeline: unified ragged steps, multi-step decode chains,
deferred token fetches/harvest, mixed-phase bursts, and token acceptance.

Split out of engine.py as a pure move (r5; VERDICT r4 weak #7) — these are
TpuEngine methods, combined via mixin inheritance.  See engine.py for the
engine-wide invariants (device lock, dispatch ordering, trace format).
"""

from __future__ import annotations

import asyncio
import time
import logging
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)

from collections import deque

from ..llm.metrics import tenancy_metrics
from ..llm.protocols import FinishReason, LLMEngineOutput
from ..ops.sampling import SamplingParams
from .scheduler import RowSlots, SequenceState, StepPlan
from ..models.llama import RaggedBatch

_FINISHED = object()  # queue sentinel (engine.py imports this)


class DecodePipelineMixin:
    # Numpy fast path for per-chunk token acceptance (_accept_chunk); tests
    # flip this off to prove equivalence against the scalar loop.
    _vectorized_accept = True
    # Continuous batching in the fused decode loop: retire finished rows and
    # admit waiting sequences between chunk dispatches instead of draining
    # the whole pipeline on every membership change.  Tests and the churn
    # bench flip this off to run the legacy drain-on-any-change behaviour
    # as the exact-stream control (both modes are token-identical; only the
    # scheduling shape differs).
    _continuous_decode = True

    def _start_d2h(self, out, need_lp: bool) -> None:
        """Start the sampled-output device→host copies for a dispatched
        step.  Capability is probed ONCE at engine init (``_copy_async``,
        engine.py): the per-dispatch ``except AttributeError: pass`` this
        replaces could mask a real attribute error raised inside the
        logprobs path (a renamed SampleOut field would silently turn every
        fetch into a synchronous round trip instead of failing loudly)."""
        if not self._copy_async:
            return
        out.tokens.copy_to_host_async()
        if need_lp:
            out.logprob.copy_to_host_async()
            out.top_ids.copy_to_host_async()
            out.top_logprobs.copy_to_host_async()

    def _sampling_arrays(
        self,
        seqs: List[Optional[SequenceState]],
        step_offsets: Optional[List[int]] = None,
        grammar_states: Optional[List[Optional[int]]] = None,
    ) -> SamplingParams:
        """Build the per-row device sampling state for this step.

        ``seqs`` is one entry per batch ROW (a sequence may own several
        rows in a speculative verification step; ``step_offsets[i]`` then
        shifts row i's rng-stream position to the output index it scores —
        engine/spec.py).  The counts matrix ([S, V], penalties) is the
        engine's cached all-zeros DEVICE buffer unless some row actually
        uses a penalty — the common path never pays the [S, V]
        host→device transfer.  Same economy for the grammar mask
        ([S, ceil(V/32)] packed bits, llm/tenancy): the cached all-zero
        device buffer rides along (cond-skipped) unless a constrained row
        is present.  ``grammar_states[i]`` overrides row i's automaton
        state (spec verification scores draft positions, whose states are
        the current state advanced through the draft prefix); -1 forces
        the row unconstrained (positions past an inadmissible draft token
        — their samples can never commit, but they must not sample from an
        all-masked distribution)."""
        S = self.cfg.max_batch
        V = self.model_config.vocab_size
        seeds = np.zeros((S,), np.uint32)
        steps = np.zeros((S,), np.int32)
        temp = np.zeros((S,), np.float32)
        topk = np.zeros((S,), np.int32)
        topp = np.ones((S,), np.float32)
        fpen = np.zeros((S,), np.float32)
        ppen = np.zeros((S,), np.float32)
        need_lp = False
        any_pen = False
        # ``seqs[i] is None`` marks a free/retired row slot (the continuous
        # decode pipeline passes its RowSlots.rows directly): the row keeps
        # the same greedy defaults as padding rows past len(seqs).
        for i, seq in enumerate(seqs):
            if seq is None:
                continue
            seeds[i] = seq.sampling_seed
            steps[i] = seq.num_output_tokens + (
                step_offsets[i] if step_offsets is not None else 0
            )
            temp[i] = seq.sampling_temperature
            topk[i] = seq.sampling_top_k
            topp[i] = seq.sampling_top_p
            fpen[i] = seq.freq_penalty
            ppen[i] = seq.pres_penalty
            need_lp = need_lp or seq.logprobs is not None
            any_pen = any_pen or seq.freq_penalty != 0 or seq.pres_penalty != 0
        if any_pen:
            counts_np = np.zeros((S, V), np.int16)
            for i, seq in enumerate(seqs):
                if seq is None:
                    continue
                # Generated tokens since the ORIGINAL prompt: preemption and
                # migration-resume fold output into ``prompt``, and counting
                # ``output`` alone would silently drop the folded tokens'
                # penalty contributions exactly when a request resumes.
                gen = np.asarray(
                    (seq.prompt + seq.output)[seq.orig_prompt_len :], np.int64
                )
                if gen.size:
                    np.add.at(counts_np[i], gen % V, 1)
            if self._rep_sharding is not None:
                counts = self._prep(counts_np)
            else:
                counts = jnp.asarray(counts_np)  # committed, key matches cache
        else:
            counts = self._zero_counts

        # Grammar masks (llm/tenancy/grammar.py): packed admissible-token
        # bits for constrained rows; unconstrained rows get all-ones.
        masked_rows = [
            i
            for i, seq in enumerate(seqs)
            if seq is not None
            and seq.grammar is not None
            and (grammar_states is None or grammar_states[i] != -1)
        ]
        if masked_rows:
            mw = np.full((S, self._mask_w), 0xFFFFFFFF, np.uint32)
            for i in masked_rows:
                seq = seqs[i]
                state = seq.grammar_state
                if grammar_states is not None and grammar_states[i] is not None:
                    state = grammar_states[i]
                mw[i] = seq.grammar.packed_mask(state)
            # jnp, not np: device arrays and numpy arrays key DIFFERENT
            # jit-cache entries, and the warmup/common path dispatches the
            # cached device zero-mask — same trick as the counts buffer.
            mask_words: Any = jnp.asarray(mw)
            any_mask = np.asarray(True)
            tenancy_metrics.grammar_masked_rows_total += len(masked_rows)
        else:
            mask_words = self._zero_mask
            any_mask = np.asarray(False)
        # LoRA slots (llm/tenancy/lora.py): per-row resident adapter slot,
        # -1 = base.  None (absent from the jit treedef) on LoRA-less
        # engines so their compiled programs are unchanged.
        if self._lora_registry is not None:
            aslots: Any = np.full((S,), -1, np.int32)
            for i, seq in enumerate(seqs):
                if seq is not None:
                    aslots[i] = seq.adapter_slot
        else:
            aslots = None
        return SamplingParams(
            seeds=seeds,
            steps=steps,
            temperature=temp,
            top_k=topk,
            top_p=topp,
            freq_penalty=fpen,
            pres_penalty=ppen,
            counts=counts,
            need_logprobs=np.asarray(need_lp),
            mask_words=mask_words,
            any_mask=any_mask,
            adapter_slots=aslots,
        )

    def _tables_row(self, out: np.ndarray, i: int, seq: SequenceState) -> None:
        ids = seq.block_ids[: out.shape[1]]
        out[i, : len(ids)] = ids

    def _build_ragged(self, items) -> RaggedBatch:
        bs = self.cfg.block_size
        S = self.cfg.max_batch
        PP = self.cfg.max_blocks_per_seq
        total = sum(n for _, _, n in items)
        T = self.cfg.bucket_tokens(total)

        tok = np.zeros((T,), np.int32)
        pos = np.zeros((T,), np.int32)
        slots = np.full((T,), -1, np.int32)
        kv_lens = np.zeros((S,), np.int32)
        tables = np.zeros((S, PP), np.int32)
        cu = np.zeros((S + 1,), np.int32)
        aslots = (
            np.full((T,), -1, np.int32)
            if self._lora_registry is not None
            else None
        )
        at = 0
        for i, (seq, start, n) in enumerate(items):
            all_toks = seq.prompt + seq.output
            tok[at : at + n] = all_toks[start : start + n]
            p = np.arange(start, start + n, dtype=np.int32)
            pos[at : at + n] = p
            blk = np.asarray(seq.block_ids, np.int32)
            slots[at : at + n] = blk[p // bs] * bs + p % bs
            if aslots is not None:
                aslots[at : at + n] = seq.adapter_slot
            self._tables_row(tables, i, seq)
            kv_lens[i] = start + n
            at += n
            cu[i + 1] = at
        cu[len(items) + 1 :] = at
        return RaggedBatch(
            token_ids=tok,
            positions=pos,
            slot_mapping=slots,
            kv_lens=kv_lens,
            page_indices=tables,
            cu_q_lens=cu,
            num_seqs=np.asarray([len(items)], np.int32),
            adapter_slots=aslots,
        )

    async def _run_unified(self, plan: StepPlan) -> None:
        rb = self._build_ragged(plan.items)
        samp = self._sampling_arrays([s for s, _, _ in plan.items])
        need_lp = bool(samp.need_logprobs)
        # A step whose every row stays mid-prefill produces sampled tokens
        # nobody consumes — skip the device→host fetch entirely and let the
        # next chunk's dispatch queue behind this one.  Over the tunneled
        # chip a blocking fetch costs ~100ms/chunk, which made chunked
        # prefill RTT-bound (r3: TTFT 1343ms for ISL 3000 vs ~200ms of
        # device compute); co-located it still saves a sync per chunk.
        need_tokens = any(
            start + n >= len(seq.prompt) for seq, start, n in plan.items
        )
        if self._rep_sharding is not None:
            rb_d, samp_d = self._prep((rb, samp))
        else:
            rb_d, samp_d = rb, samp
        step = self._step_fn
        # Park rows BEFORE the first suspension point, not after the
        # dispatch: from here to the harvest this coroutine yields, and
        # anything polling quiescence (freeze_sequence, engine/migrate.py)
        # must see these rows as having a token en route — marking after
        # the await left a window where a migration snapshot missed the
        # in-flight token and the client received it twice.  (Rows of OLD
        # pending fetches are disjoint from this plan's rows — the
        # scheduler never plans a parked row — so the harvests below can't
        # clear these marks early.)
        for seq, start, n in plan.items:
            if not seq.finished and start + n >= len(seq.prompt):
                seq.awaiting_fetch = True
        while self._pending_fetches and self._pending_fetches[0][1].done():
            await self._harvest_pending()  # free: task already complete

        def run():
            out, self.cache = step(self.params, self.cache, rb_d, samp_d)
            if need_tokens:
                # Start the D2H now; the accept is deferred to a harvest
                # point so the round trip overlaps later dispatches.
                self._start_d2h(out, need_lp)
            return out

        await self._pace()
        t0 = time.perf_counter()
        async with self._device_lock:
            # Publish INSIDE the device lock: broadcast order must equal
            # device enqueue order or followers replay a different program
            # sequence than the leader ran (SPMD divergence).
            if self._publisher is not None:
                await self._publisher.publish(
                    "unified",
                    (rb, jax.tree_util.tree_map(np.asarray, samp)),
                )
            out = await self._await_device(
                self._device_task(run), "unified_dispatch", len(plan.items)
            )
        wall = time.perf_counter() - t0
        self.step_trace.append(
            (
                "unified_fetch" if need_tokens else "unified",
                wall,
                len(plan.items),
                len(rb.token_ids),
            )
        )
        # Prefill-chunk accounting: any step that advanced prompt tokens
        # counts as one chunk (mixed plans attribute the whole dispatch
        # wall — the prefill rows dominate it by construction of the
        # chunked scheduler).  Feeds the per-chunk latency quantiles on
        # /metrics and the prefill-MFU breakdown in bench.py.
        prefill_tokens = sum(
            min(n, len(seq.prompt) - start)
            for seq, start, n in plan.items
            if start < len(seq.prompt)
        )
        if prefill_tokens > 0:
            self._note_prefill_chunk(wall, prefill_tokens)

        pending_rows: List[Tuple[SequenceState, int]] = []
        for i, (seq, start, n) in enumerate(plan.items):
            if seq.finished:
                seq.awaiting_fetch = False  # pre-marked above; never parked
                continue
            if start >= len(seq.prompt):
                # Decode row: the fed token joins the hash stream.
                seq.block_seq.append((seq.prompt + seq.output)[start])
            seq.num_computed = start + n
            self._seal_completed_blocks(seq)
            if not seq.in_prefill:
                # This row's sampled token is in flight (pre-marked before
                # the dispatch); park the row until a harvest point applies
                # it.
                seq.awaiting_fetch = True
                pending_rows.append((seq, i))
        if pending_rows:
            self._stash_fetch("first", out, need_lp, pending_rows)

    async def _pace(self) -> None:
        """Await the injectable test pace hook (engine.py pace_hook)
        before a device op.  Always called OUTSIDE ``_device_lock``: the
        hook is allowed to BLOCK (tests/test_migration.py gates decode on
        a per-copy-round budget), and the KV copy/export plane needs the
        device lock to make the progress that un-blocks it — pacing under
        the lock would deadlock that interlock."""
        if self.pace_hook is not None:
            await self.pace_hook()

    async def _await_device(self, task, kind: str, rows: int):
        """Await a device-op task (token fetch OR dispatch) under the
        decode-stall watchdog.

        r5 diagnosed a ~3-minute ``decode_wait`` hang (a wedged device
        fetch) that no engine-side detector caught — the worker kept
        answering health probes while every stream it owned sat frozen.
        With the threshold set (EngineConfig.decode_stall_s /
        ``DYN_DECODE_STALL_S``; default off), a device op that exceeds it
        LOUDLY logs the recent dispatch trace, bumps ``decode_stalls``
        (``dynamo_tpu_engine_stall_total`` on /metrics) and records
        ``last_stall`` for ``dispatch_summary()`` — then KEEPS WAITING:
        the watchdog attributes the hang, it does not guess at recovery
        (killing an op whose DMA later lands would corrupt the
        dispatch-order invariants).  Dispatch awaits are covered too: a
        wedge can just as well surface one await earlier, blocking the
        ``to_thread(run)`` handoff with no fetch outstanding."""
        thr = self._stall_threshold_s
        if thr <= 0:
            return await task
        waited = 0.0
        while True:
            done, _ = await asyncio.wait({task}, timeout=thr)
            if done:
                return task.result()
            first = waited == 0.0
            waited += thr
            if first:
                self.decode_stalls += 1
            trace = [
                [k, round(t, 4), r, n]
                for k, t, r, n in list(self.step_trace)[-8:]
            ]
            self.last_stall = {
                "kind": kind,
                "rows": rows,
                "waited_s": round(waited, 3),
                "trace": trace,
            }
            logger.error(
                "decode stall: %s (%d rows) exceeded %.1fs (waited %.1fs, "
                "threshold decode_stall_s/DYN_DECODE_STALL_S); recent "
                "dispatch trace: %s",
                kind, rows, thr, waited, trace,
            )

    def _device_task(self, fn):
        """Wrap a device-op thread in a Task so _await_device can watch it."""
        return asyncio.get_running_loop().create_task(asyncio.to_thread(fn))

    @staticmethod
    def _fetch_outs(out, need_lp: bool):
        """Materialize a step's sampled outputs on host (ONE definition of
        the SampleOut fetch shape — the stash path and the fused pipeline
        both use it, so a payload change cannot silently diverge them)."""
        if need_lp:
            return (
                np.asarray(out.tokens),
                np.asarray(out.logprob),
                np.asarray(out.top_ids),
                np.asarray(out.top_logprobs),
            )
        return np.asarray(out.tokens), None, None, None

    def _stash_fetch(self, kind: str, out, need_lp: bool, *meta) -> None:
        """Park a dispatched step's token fetch: the np.asarray runs on a
        worker thread STARTING NOW (the D2H was already initiated with
        copy_to_host_async), and the loop applies the result at a harvest
        point once the task completes — the device round trip never blocks
        dispatching."""
        task = asyncio.get_running_loop().create_task(
            asyncio.to_thread(self._fetch_outs, out, need_lp)
        )
        self._pending_fetches.append((kind, task, *meta))

    async def _harvest_pending(self, all_pending: bool = False) -> None:
        """Apply deferred fetches in dispatch order.  Harvests the oldest
        entry (awaiting its background task), or everything outstanding."""
        while self._pending_fetches:
            entry = self._pending_fetches.pop(0)
            kind, task = entry[0], entry[1]

            await self._pace()
            t0 = time.perf_counter()
            sampled, logp, top_ids, top_lp = await self._await_device(
                task, f"{kind}_fetch", len(entry[2])
            )
            self.step_trace.append(
                (
                    f"{kind}_harvest",
                    time.perf_counter() - t0,
                    len(entry[2]),
                    0,
                )
            )
            if kind == "first":
                for seq, i in entry[2]:
                    seq.awaiting_fetch = False
                    if seq.finished:
                        continue  # cancelled while the token was in flight
                    self._accept_token(
                        seq,
                        int(sampled[i]),
                        logprobs=self._lp_info(seq, i, logp, top_ids, top_lp),
                    )
            elif kind == "spec":  # speculative verification (engine/spec.py)
                self._harvest_spec(entry, sampled, logp, top_ids, top_lp)
            else:  # burst
                members, pos0 = entry[2], entry[3]
                chained = entry[4] if len(entry) > 4 else False
                finished: List[SequenceState] = []
                self._accept_chunk(
                    members, pos0, sampled, logp, top_ids, top_lp, finished
                )
                if chained:
                    # A chained burst chunk for these rows is still in
                    # flight (_decode_burst's pipelined shape): keep them
                    # parked — freeze_sequence's quiescence poll must see
                    # the in-flight tokens — and defer removals to the
                    # final chunk's harvest, so no member's blocks are
                    # freed while a dispatch that writes them is in flight.
                    for seq in members:
                        if not seq.finished:
                            seq.awaiting_fetch = True
                else:
                    # Sweep by flag, not the local ``finished`` list: a row
                    # that stopped in the FIRST chunk of a chained burst is
                    # skipped by this chunk's accept and must still be
                    # removed here.
                    for seq in members:
                        if seq.finished and any(
                            s is seq for s in self.scheduler.running
                        ):
                            self.scheduler.remove(seq)
            if not all_pending:
                break

    async def _decode_pipeline(self, members: List[SequenceState]) -> bool:
        """Continuous fused decode: multi-step dispatches with the token
        carry on device, up to cfg.pipeline_depth dispatches in flight,
        host readback overlapped — and CONTINUOUS membership:

        - **In-loop retirement**: a row that stops (or whose client
          cancels) is excluded from further dispatches immediately
          (``pos_disp = -1``) and its slot + KV blocks are released once
          the write barrier passes — every chunk dispatched while it was
          active has been harvested — while the session keeps fusing for
          everyone else.
        - **In-loop admission**: compatible waiting sequences are admitted
          into free row slots mid-session; their prompts prefill through
          ordinary unified steps INTERLEAVED between fused chunks (the
          fused cadence never stops), and once the first token lands they
          join the chain at the next chain-break merge — a drain of
          in-flight chunks only, never an exit to the scheduler and the
          mixed-phase single-step regime.
        - **Double-buffered dispatch**: the oldest chunk's token fetch runs
          in a worker thread while the next chunk's host-side planning
          (slot ensure, table rows), the admission prefill dispatch and
          completed first-token harvests all proceed — the host never
          plans on the critical path (``decode_wait`` measures device
          compute, not host work).

        ``want_rebuild`` fires only for genuinely incompatible changes:
        engine close, a frozen (mid-migration) row, a waiting head the
        fused loop cannot host (grammar-constrained), KV exhaustion, or a
        speculation-session flip.  Everything else is absorbed in-loop.

        Exactness: samples depend only on (seed, rng-step, committed
        prefix), and a chain-break merge re-seeds the device carry with
        exactly the values it already holds — so continuous and
        drain-rebuild scheduling produce byte-identical streams at any
        temperature (tests/test_continuous_batching.py gates it, spec
        on/off; ``_continuous_decode = False`` is the legacy control).

        Invariant: no member's KV blocks are freed while any dispatch that
        writes them is in flight — retirement defers the release to the
        per-row write barrier (the legacy path deferred ALL finishes to
        the full drain).
        """
        cfg = self.cfg
        bs = cfg.block_size
        S, T = cfg.max_batch, cfg.decode_steps
        continuous = self._continuous_decode
        # Visible to freeze_sequence (engine/migrate.py) BEFORE the first
        # suspension point; maintained as membership changes below.
        self._pipeline_members = {s.request_id for s in members}
        self.pipeline_sessions += 1
        session_t0 = time.perf_counter()
        multi = self._multi_fn

        tok0 = np.zeros((S,), np.int32)
        pos_disp = np.full((S,), -1, np.int32)  # dispatch frontier (-1 = free)
        tables = np.zeros((S, cfg.max_blocks_per_seq), np.int32)
        limits = np.zeros((S,), np.int32)
        slots = RowSlots(S)
        samp: Optional[SamplingParams] = None
        samp_np: Any = None
        need_lp = False
        # (token, rng-step, penalty-counts) carry: host seeds at each chain
        # break, then the previous dispatch's on-device outputs.
        carry: Optional[Tuple[Any, Any, Any]] = None

        inflight: deque = deque()  # (outs, pos0, chunk_id, need_lp)
        chunk_id = 0   # monotone dispatch counter — the write-barrier clock
        harvested = 0  # highest chunk id applied so far
        # (seq, slot, barrier, remove): remove=False parks a FROZEN row out
        # of the session (migration quiescence) without releasing it from
        # the scheduler — the row stays resident, just unplanned.
        retired: List[Tuple[SequenceState, int, int, bool]] = []
        prefilling: List[SequenceState] = []  # admitted in-loop, prompt computing
        # Sequences joining the fused chain at the next chain-break merge.
        # The INITIAL members seed through the same merge: one code path
        # for session start and mid-session joins.
        ready: List[SequenceState] = list(members)
        rebuild = False
        dispatched_any = False

        def merge_ready() -> None:
            """Chain-break merge: assign slots to joining sequences and
            re-seed the whole chain from host state.  Only legal with
            nothing in flight — exactly then the continuing rows' frontier
            tokens are host-known (accepted == dispatched), and the host
            (steps, counts) equal the device carry they replace."""
            nonlocal samp, samp_np, need_lp, carry
            for seq in ready:
                slots.assign(seq)
            ready.clear()
            for i, seq in slots.active():
                all_toks = seq.prompt + seq.output
                tok0[i] = all_toks[seq.num_computed]
                # Rows whose frontier overshot a wall earlier re-dispatch
                # those positions; the recomputed (seeded) samples are
                # identical — same as a full rebuild.
                pos_disp[i] = seq.num_computed
            samp = self._sampling_arrays(slots.rows)
            # Host copy only needed for the follower broadcast — np.asarray
            # on samp.counts would otherwise drag the [S, V] device buffer
            # to host on every merge.
            samp_np = (
                jax.tree_util.tree_map(np.asarray, samp)
                if self._publisher is not None
                else None
            )
            need_lp = bool(samp.need_logprobs)
            carry = None  # next dispatch re-seeds (tok, steps, counts)

        def sweep_retire() -> int:
            """Retire finished (and, in continuous mode, client-cancelled
            and migration-frozen) rows: excluded from future dispatches
            NOW; slot (+ blocks, unless frozen) released once the write
            barrier passes."""
            m = 0
            for i, seq in slots.active():
                if continuous and not seq.finished:
                    c = self._contexts.get(seq.request_id)
                    if c is not None and c.is_stopped:
                        # In-loop cancellation IS retirement — the stream
                        # is dead; nobody needs a whole-pipeline drain.
                        seq.finished = True
                        self._finish(seq, FinishReason.CANCELLED)
                if seq.finished:
                    slots.retire(i)
                    pos_disp[i] = -1
                    retired.append((seq, i, chunk_id, True))
                    if continuous:
                        self.continuous_retired += 1
                    m += 1
                elif continuous and seq.frozen:
                    # Migration freeze: park the row OUT of the session.
                    # Its slot goes None, so any not-yet-harvested chunk
                    # tokens for the row are DROPPED at accept (recomputed
                    # identically on resume — seeded sampler), keeping the
                    # snapshot frontier equal to the emitted stream; the
                    # barrier hands quiescence to freeze_sequence via the
                    # _pipeline_members discard — the session keeps fusing
                    # for everyone else.  Legacy mode drains instead
                    # (want_rebuild).
                    slots.retire(i)
                    pos_disp[i] = -1
                    retired.append((seq, i, chunk_id, False))
                    m += 1
            return m

        def flush_retired() -> None:
            """Release retirements whose write barrier has passed: every
            chunk dispatched while the row was active has been harvested,
            so nothing in flight can still write its blocks (or, for a
            frozen row, still advance it — quiescence)."""
            while retired and retired[0][2] <= harvested:
                seq, i, _, remove = retired.pop(0)
                if remove:
                    self.scheduler.remove(seq)
                self._pipeline_members.discard(seq.request_id)
                slots.free(i)

        def rejoin_strays() -> None:
            """Running decode rows OUTSIDE the session rejoin at the next
            chain break — a migration rollback's unfreeze is the one way a
            planned row falls out of membership, and with long-lived
            continuous sessions it would otherwise starve until the
            session ends (legacy sessions rebuilt constantly, so schedule()
            picked such rows up within a few chunks)."""
            nonlocal rebuild
            known = (
                slots.num_active
                + len(prefilling)
                + len(ready)
                + len(retired)
            )
            if len(self.scheduler.running) == known:
                return
            in_session = (
                {id(s) for _, s in slots.active()}
                | {id(s) for s in prefilling}
                | {id(s) for s in ready}
                | {id(s) for s, _, _, _ in retired}
            )
            for seq in self.scheduler.running:
                if (
                    id(seq) in in_session
                    or seq.frozen
                    or seq.finished
                    or seq.awaiting_fetch  # parked: its fetch lands first
                ):
                    continue
                if seq.grammar is not None:
                    # Constrained rows can't ride fused chunks: drain for
                    # the scheduler's unified-step routing.
                    rebuild = True
                    continue
                if seq.in_prefill:
                    prefilling.append(seq)  # froze mid-prefill: resume it
                else:
                    ready.append(seq)
                self._pipeline_members.add(seq.request_id)

        def want_rebuild() -> bool:
            if self._closed:
                return True
            if any(s.frozen for s in prefilling) or any(
                s.frozen for s in ready
            ):
                # A freeze landing in the join window (rare): drain — the
                # joining row has no slot to park out of.
                return True
            if not continuous:
                # Legacy static membership: ANY change drains the session —
                # a frozen member (quiescence needs the full drain), an
                # admissible waiting head, a finish, or a cancellation.
                # Waiting requests only force a rebuild when one could
                # actually be ADMITTED (free slot + blocks) — at
                # oversubscription the queue is never empty, and gating on
                # num_waiting alone kept the fused pipeline permanently
                # disabled (round-3 saturation collapse).
                return (
                    any(s.frozen for _, s in slots.active())
                    or
                    self.scheduler.admission_ready()
                    or any(s.finished for _, s in slots.active())
                    or any(
                        (c := self._contexts.get(s.request_id)) is not None
                        and c.is_stopped
                        for _, s in slots.active()
                    )
                )
            # Continuous: only a head the fused loop cannot host (grammar-
            # constrained — its mask advances host-side per token) still
            # needs the full scheduler rebuild.
            return (
                self.scheduler.admission_ready()
                and not self.scheduler.waiting_head_compatible()
            )

        def admit() -> None:
            if not continuous or rebuild:
                return
            room = slots.capacity_left - len(prefilling) - len(ready)
            if room <= 0 or not self.scheduler.admission_ready():
                return
            if not self.scheduler.waiting_head_compatible():
                return
            for seq in self.scheduler.admit_continuous(room):
                self._pipeline_members.add(seq.request_id)
                self.continuous_admissions += 1
                prefilling.append(seq)

        async def prefill_step() -> bool:
            """One unified step advancing every in-loop-admitted prompt by
            a chunk (ordinary _run_unified: chunked prefill, deferred
            first-token fetch, block sealing).  Fused chunks around it
            touch disjoint rows and blocks."""
            budget = cfg.prefill_chunk
            items: List[Tuple[SequenceState, int, int]] = []
            for seq in prefilling:
                if budget <= 0:
                    break
                if (
                    seq.finished
                    or seq.frozen
                    or seq.awaiting_fetch
                    or not seq.in_prefill
                ):
                    continue
                chunk = min(budget, len(seq.prompt) - seq.num_computed)
                items.append((seq, seq.num_computed, chunk))
                budget -= chunk
            if not items:
                return False
            # Counted as in-session DEVICE work for host_gap_frac: an
            # admitted prompt's prefill dispatches run inside the session
            # wall, and excluding them would read as a host-side gap
            # exactly when in-loop admission is active.
            t0 = time.perf_counter()
            await self._run_unified(StepPlan(items))
            self.decode_busy_s += time.perf_counter() - t0
            return True

        def promote_ready() -> None:
            for seq in list(prefilling):
                if seq.finished:
                    # First token hit a stop / the client cancelled:
                    # _accept_token already removed it — it never joins.
                    prefilling.remove(seq)
                    self._pipeline_members.discard(seq.request_id)
                elif not seq.in_prefill and not seq.awaiting_fetch:
                    # Prompt computed AND first token harvested: joins the
                    # fused chain at the next chain break.
                    prefilling.remove(seq)
                    ready.append(seq)

        def plan_chunk() -> Optional[np.ndarray]:
            """Host-side planning for one fused chunk: KV slot ensure,
            table refresh, per-row write limits.  None = nothing worth
            dispatching (or KV exhausted → rebuild)."""
            nonlocal rebuild
            # Don't dispatch chunks no row can still use — checked BEFORE
            # allocating lookahead blocks: a never-dispatched chunk must
            # not take KV capacity from other sequences.
            if not self._any_useful_rows(slots.rows, pos_disp):
                return None
            ok = True
            for i, seq in slots.active():
                need = int(pos_disp[i]) + T - seq.num_computed
                if not self.scheduler._ensure_slot(seq, lookahead=need):
                    ok = False
                self._tables_row(tables, i, seq)
                limits[i] = min(
                    len(seq.block_ids) * bs, cfg.max_blocks_per_seq * bs
                )
            if not ok:
                # Out of KV headroom: drain any in-flight work, then return
                # so schedule() can preempt with nothing pending.
                rebuild = True
                return None
            return pos_disp.copy()

        async def dispatch_chunk(pos0: np.ndarray) -> None:
            nonlocal carry, chunk_id, dispatched_any
            first = carry is None
            n_active = slots.num_active
            pub_payload = (
                tok0 if first else None,  # None → follower's own carry
                pos0,
                tables.copy(),
                limits.copy(),
                samp_np,
            )
            if first:
                c_tok, c_steps, c_counts = tok0, samp.steps, samp.counts
                if self._rep_sharding is not None:
                    c_tok, c_steps = self._prep((c_tok, c_steps))
            else:
                c_tok, c_steps, c_counts = carry
            if self._rep_sharding is not None:
                d_args = self._prep((pos0, tables.copy(), limits.copy(), samp))
            else:
                d_args = (pos0, tables, limits, samp)

            def run(args=d_args, tok_in=c_tok, st=c_steps, ct=c_counts):
                outs, last, steps_f, counts_f, self.cache = multi(
                    self.params, self.cache, tok_in, st, ct, *args
                )
                return outs, (last, steps_f, counts_f)

            await self._pace()
            t0 = time.perf_counter()
            async with self._device_lock:
                # Broadcast order must equal device enqueue order (see
                # _run_unified) — publish under the device lock.
                if self._publisher is not None:
                    await self._publisher.publish("multi", pub_payload)
                outs, new_carry = await self._await_device(
                    self._device_task(run), "decode_dispatch", n_active
                )
            carry = new_carry
            t1 = time.perf_counter()
            wall = t1 - t0
            self.decode_busy_s += wall  # unbounded host-gap accounting
            self.step_trace.append(
                ("decode_dispatch", wall, n_active, n_active * T)
            )
            self._trace_decode_chunk(slots.active(), t0, t1, T)
            # Start the D2H copy NOW: it proceeds in the background while
            # later chunks compute, so the wait below pays ~zero round trip
            # instead of compute + full link latency.
            self._start_d2h(outs, need_lp)
            chunk_id += 1
            inflight.append((outs, pos0, chunk_id, need_lp))
            dispatched_any = True
            pos_disp[:] = np.where(pos_disp >= 0, pos_disp + T, pos_disp)

        while True:
            if sweep_retire() and not continuous:
                rebuild = True
            flush_retired()
            if continuous and not rebuild:
                rejoin_strays()
            if want_rebuild():
                rebuild = True
            if ready and not inflight and not rebuild:
                merge_ready()

            # Pop the oldest chunk and start its fetch FIRST: everything
            # below — next-chunk planning + dispatch, admission, the
            # interleaved prefill, completed first-token harvests —
            # overlaps the D2H running in the fetch thread.
            fetch_task = None
            if inflight:
                outs, pos0_c, cid, lp = inflight.popleft()
                wait_t0 = time.perf_counter()
                fetch_task = asyncio.get_running_loop().create_task(
                    asyncio.to_thread(self._fetch_outs, outs, lp)
                )

            # Top up the dispatch window.  With anyone waiting to join
            # (queued, prefilling, or merge-pending), cap the in-flight
            # depth at 2 — enough to overlap fetch with compute — so the
            # drain a join must wait for stays bounded.  A pending merge
            # holds fused dispatch entirely: the chain must break first.
            depth = (
                min(cfg.pipeline_depth, 2)
                if (self.scheduler.num_waiting or prefilling or ready)
                else cfg.pipeline_depth
            )
            in_flight_now = len(inflight) + (1 if fetch_task is not None else 0)
            progressed = False
            while (
                not rebuild
                and not ready
                and samp is not None
                and in_flight_now < depth
            ):
                pos0 = plan_chunk()
                if pos0 is None:
                    break
                await dispatch_chunk(pos0)
                in_flight_now += 1
                progressed = True
                if want_rebuild():
                    rebuild = True
            if not rebuild:
                admit()
                if await prefill_step():
                    dispatched_any = True
                    progressed = True
            # Completed deferred fetches (admitted rows' first tokens)
            # apply for free while the oldest chunk is still in flight.
            while self._pending_fetches and self._pending_fetches[0][1].done():
                await self._harvest_pending()
                progressed = True

            if fetch_task is not None:
                await self._pace()
                sampled, logp, top_ids, top_lp = await self._await_device(
                    fetch_task, "decode_wait", slots.num_active
                )
                wait_wall = time.perf_counter() - wait_t0
                self.decode_busy_s += wait_wall
                self.step_trace.append(
                    # "wait" not "fetch": the D2H copy started at dispatch,
                    # so this wall is dominated by the chunk's device
                    # compute.
                    (
                        "decode_wait",
                        wait_wall,
                        slots.num_active,
                        slots.num_active * T,
                    )
                )
                self._accept_chunk(
                    slots.rows, pos0_c, sampled, logp, top_ids, top_lp, []
                )
                harvested = cid
                if not rebuild and self._spec_session_probe(
                    [s for _, s in slots.active()]
                ):
                    # Output grew repetitive enough that in-step speculation
                    # now beats the fused chunks: drain and let schedule()
                    # re-propose for real (engine/spec.py).
                    rebuild = True
            elif not progressed:
                if self._pending_fetches:
                    # Nothing dispatchable until a first-token fetch lands:
                    # block on the oldest instead of spinning.
                    await self._harvest_pending()
                else:
                    promote_ready()
                    if ready and not rebuild:
                        continue  # late joiners: merge next iteration
                    # Nothing in flight, nothing to dispatch, nothing
                    # pending: drained for a rebuild, or every member
                    # finished — the session is over.
                    break
            promote_ready()
            if rebuild and not inflight:
                break
            await asyncio.sleep(0)  # let ingress/egress run between chunks

        # Drained: every dispatched chunk was harvested, so every write
        # barrier has passed — release whatever retirement is pending.
        sweep_retire()
        flush_retired()
        self._pipeline_members = set()
        self.pipeline_wall_s += time.perf_counter() - session_t0
        if rebuild:
            self.pipeline_rebuilds += 1
        return dispatched_any

    async def _decode_burst(self, members: List[SequenceState]) -> bool:
        """Fused multi-step dispatch(es) for ``members`` (all decoding),
        used in mixed phases where prefill rows keep the full pipeline from
        engaging.  Pipelined shape (ISSUE 11): when KV headroom covers TWO
        chunks and some row can still use the second, a second dispatch is
        CHAINED off the first's on-device token carry — two in-flight
        chunks (2 × decode_steps tokens per row) for the same host-side
        planning cost, matching the full pipeline's double-buffered shape.
        Same discard semantics as the pipeline: tokens past a row's
        stop/limit are dropped host-side.  Returns False (dispatching
        nothing) when KV headroom for even one full burst is missing."""
        cfg = self.cfg
        bs = cfg.block_size
        S, T = cfg.max_batch, cfg.decode_steps
        n = len(members)
        tok0 = np.zeros((S,), np.int32)
        pos0 = np.full((S,), -1, np.int32)
        tables = np.zeros((S, cfg.max_blocks_per_seq), np.int32)
        limits = np.zeros((S,), np.int32)
        chain = True  # headroom for a second chained chunk on every row?
        for i, seq in enumerate(members):
            if seq.finished or seq.frozen:
                return False  # membership changed under us: replan
            if seq.grammar is not None:
                # Constrained rows never burst: their mask advances
                # host-side per accepted token (callers route them to
                # unified steps — this is the safety net).
                return False
            if not self.scheduler._ensure_slot(seq, lookahead=T):
                return False
            # Second-chunk headroom is best-effort: blocks the 2T ensure
            # allocates stay with the row either way (used by later steps).
            if chain and not self.scheduler._ensure_slot(seq, lookahead=2 * T):
                chain = False
            all_toks = seq.prompt + seq.output
            tok0[i] = all_toks[seq.num_computed]
            pos0[i] = seq.num_computed
            self._tables_row(tables, i, seq)
            limits[i] = min(
                len(seq.block_ids) * bs, cfg.max_blocks_per_seq * bs
            )
        # A second chunk no row can still use is pure waste (all its tokens
        # would be discarded host-side): chain only when some member's
        # budget reaches past the first chunk's frontier.
        if chain:
            chain = self._any_useful_rows(
                members, np.where(pos0 >= 0, pos0 + T, pos0)
            )
        # Park BEFORE the first suspension point (see _run_unified):
        # quiescence pollers must count the burst's in-flight tokens from
        # the moment this coroutine can yield, not from when the dispatch
        # returns.
        for seq in members:
            seq.awaiting_fetch = True
        while self._pending_fetches and self._pending_fetches[0][1].done():
            await self._harvest_pending()  # free: task already complete
        samp = self._sampling_arrays(members)
        need_lp = bool(samp.need_logprobs)
        samp_np = (
            jax.tree_util.tree_map(np.asarray, samp)
            if self._publisher is not None
            else None
        )
        c_tok, c_steps = tok0, samp.steps
        if self._rep_sharding is not None:
            c_tok, c_steps = self._prep((c_tok, c_steps))
            d_args = self._prep((pos0, tables, limits, samp))
        else:
            d_args = (pos0, tables, limits, samp)
        multi = self._multi_fn

        def run():
            outs, last, steps_f, counts_f, self.cache = multi(
                self.params, self.cache, c_tok, c_steps, samp.counts, *d_args
            )
            # Async D2H + deferred accept: the burst's tokens are only
            # needed at the next harvest point (its rows are parked), so
            # the round trip overlaps the following prefill chunks instead
            # of stalling behind the device queue.
            self._start_d2h(outs, need_lp)
            return outs, (last, steps_f, counts_f)

        await self._pace()
        t0 = time.perf_counter()
        async with self._device_lock:
            if self._publisher is not None:
                await self._publisher.publish(
                    "multi",
                    (tok0, pos0, tables.copy(), limits, samp_np),
                )
            outs, carry = await self._await_device(
                self._device_task(run), "burst_dispatch", n
            )
        t1 = time.perf_counter()
        self.step_trace.append(("decode_burst", t1 - t0, n, n * T))
        self._trace_decode_chunk(enumerate(members), t0, t1, T)
        self._stash_fetch("burst", outs, need_lp, members, pos0, chain)
        if not chain:
            return True

        # Chained second chunk: the carry (token, rng step, penalty counts)
        # stays ON DEVICE — warmup pre-compiles this exact device-carry
        # variant, so no new program is reachable here.
        pos0b = np.where(pos0 >= 0, pos0 + T, pos0)
        if self._rep_sharding is not None:
            d_args_b = self._prep((pos0b, tables, limits, samp))
        else:
            d_args_b = (pos0b, tables, limits, samp)

        def run_b():
            outs, last, steps_f, counts_f, self.cache = multi(
                self.params, self.cache, *carry, *d_args_b
            )
            self._start_d2h(outs, need_lp)
            return outs

        await self._pace()
        t0 = time.perf_counter()
        async with self._device_lock:
            if self._publisher is not None:
                # tok None → follower chains its own mirror carry.
                await self._publisher.publish(
                    "multi",
                    (None, pos0b, tables.copy(), limits, samp_np),
                )
            outs_b = await self._await_device(
                self._device_task(run_b), "burst_dispatch", n
            )
        t1 = time.perf_counter()
        self.step_trace.append(("decode_burst", t1 - t0, n, n * T))
        self._trace_decode_chunk(enumerate(members), t0, t1, T)
        self._stash_fetch("burst", outs_b, need_lp, members, pos0b, False)
        return True

    def _any_useful_rows(
        self, members: List[Optional[SequenceState]], pos_disp: np.ndarray
    ) -> bool:
        """True if any active member could still accept a token from one more
        fused chunk, given how far its dispatch frontier already overshoots
        its accepted position (in-flight tokens count against the budget).
        ``None`` entries are free/retired row slots."""
        for i, seq in enumerate(members):
            if seq is None or seq.finished or pos_disp[i] < 0:
                continue
            overshoot = int(pos_disp[i]) - seq.num_computed
            budget = self.cfg.max_model_len - seq.total_tokens
            if seq.max_new_tokens is not None:
                budget = min(budget, seq.max_new_tokens - seq.num_output_tokens)
            if budget - overshoot > 0:
                return True
        return False

    def _seal_completed_blocks(self, seq: SequenceState) -> None:
        complete = seq.num_computed // self.cfg.block_size
        hashed = len(seq.block_seq.blocks)
        while seq.num_sealed_blocks < min(complete, hashed):
            idx = seq.num_sealed_blocks
            tb = seq.block_seq.blocks[idx]
            self.kv.seal_block(seq.block_ids[idx], tb)
            seq.num_sealed_blocks += 1
            if self.host_kv is not None and not self.host_kv.contains(
                tb.sequence_hash
            ):
                self._offload_queue.append((seq.block_ids[idx], tb))

    def _accept_chunk(
        self,
        members: List[SequenceState],
        pos0: np.ndarray,
        sampled: np.ndarray,  # [T, S]
        logp,
        top_ids,
        top_lp,
        finished: List[SequenceState],
    ) -> None:
        """Apply one fused chunk's sampled tokens to ``members``.

        Fast path: a row without logprobs computes its whole accept run
        with numpy mask math (allocation wall, LENGTH cutoffs, stop
        tokens under min_new_tokens) and emits ONE multi-token queue item
        — the scalar ``for t: for seq`` loop was the dominant term of the
        r5 16% host gap at batch 256.  Rows needing per-token logprob
        payloads (and engines with ``_vectorized_accept=False``, the
        test toggle) take the scalar row loop; both paths produce
        identical streams (tests/test_spec_decode.py asserts it)."""
        T = int(sampled.shape[0])
        bs = self.cfg.block_size
        for i, seq in enumerate(members):
            if seq is None:
                continue  # free/retired row slot (continuous pipeline)
            seq.awaiting_fetch = False
            if seq.finished or pos0[i] < 0:
                continue
            p0 = int(pos0[i])
            if seq.num_computed != p0:
                continue  # stopped/hit the allocation wall in a prior chunk
            if not self._vectorized_accept or seq.logprobs is not None:
                self._accept_chunk_row_scalar(
                    seq, i, p0, sampled, logp, top_ids, top_lp, finished
                )
                continue
            n_cap = min(T, len(seq.block_ids) * bs - p0)
            if n_cap <= 0:
                continue  # beyond allocation: tokens were never KV-backed
            if seq.trace is not None:
                # Normally latched by the "first" harvest path; belt for a
                # traced row whose first token rides a fused chunk.  AFTER
                # the n_cap guard: a row that accepts zero tokens from this
                # chunk has not produced its first token yet.
                self._trace_first_token(seq)
            col = np.asarray(sampled[:, i])
            # LENGTH cutoff: the token that reaches the budget is accepted
            # (and emitted) with finish_reason length, exactly as
            # _check_stop does after each append.
            m_len = self.cfg.max_model_len - seq.total_tokens
            if seq.max_new_tokens is not None:
                m_len = min(
                    m_len, seq.max_new_tokens - seq.num_output_tokens
                )
            m_len = max(1, m_len)
            if m_len <= n_cap:
                n_acc, reason = m_len, FinishReason.LENGTH
            else:
                n_acc, reason = n_cap, None
            stops = set(seq.stop_token_ids)
            if not seq.ignore_eos:
                stops |= set(self.model_config.eos_token_ids)
            if stops:
                hit = np.isin(col, np.fromiter(stops, np.int64))
                if seq.min_new_tokens is not None:
                    # Token m (1-based) lands at output index n_out + m.
                    hit &= (
                        seq.num_output_tokens + 1 + np.arange(T)
                    ) >= seq.min_new_tokens
                idx = np.nonzero(hit)[0]
                if idx.size and int(idx[0]) + 1 <= n_acc:
                    # STOP wins ties with LENGTH (stop checks run first).
                    n_acc, reason = int(idx[0]) + 1, FinishReason.STOP
            # Fed tokens: the committed tail + each previously sampled
            # token — members are decoding, so all join the hash stream.
            fed = [(seq.prompt + seq.output)[p0]] + [
                int(x) for x in col[: n_acc - 1]
            ]
            seq.block_seq.extend(fed)
            seq.num_computed += n_acc
            self._seal_completed_blocks(seq)
            toks = [int(x) for x in col[:n_acc]]
            seq.output.extend(toks)
            emit = toks[:-1] if reason is FinishReason.STOP else toks
            queue = self._queues.get(seq.request_id)
            if queue is not None and emit:
                queue.put_nowait(LLMEngineOutput.tokens(emit))
            if reason is not None:
                seq.finished = True
                finished.append(seq)
                self._finish(seq, reason)

    def _accept_chunk_row_scalar(
        self,
        seq: SequenceState,
        i: int,
        p0: int,
        sampled: np.ndarray,
        logp,
        top_ids,
        top_lp,
        finished: List[SequenceState],
    ) -> None:
        """Reference per-token accept loop for one row (logprob payloads
        are per token; also the oracle the vectorized path is tested
        against)."""
        bs = self.cfg.block_size
        for t in range(sampled.shape[0]):
            if seq.num_computed != p0 + t:
                continue  # stopped earlier in this chunk
            if seq.num_computed >= len(seq.block_ids) * bs:
                continue  # beyond allocation: token was never KV-backed
            fed = (seq.prompt + seq.output)[seq.num_computed]
            if seq.num_computed >= len(seq.prompt):
                seq.block_seq.append(fed)
            seq.num_computed += 1
            self._seal_completed_blocks(seq)
            self._accept_token(
                seq,
                int(sampled[t, i]),
                defer_removal=True,
                logprobs=self._lp_info(
                    seq,
                    i,
                    None if logp is None else logp[t],
                    None if top_ids is None else top_ids[t],
                    None if top_lp is None else top_lp[t],
                ),
            )
            if seq.finished:
                finished.append(seq)
                break

    def _lp_info(
        self, seq: SequenceState, i: int, logp, top_ids, top_lp
    ) -> Optional[Dict[str, Any]]:
        """Per-token logprob payload for row ``i`` (None unless requested)."""
        if seq.logprobs is None or logp is None:
            return None
        k = min(int(seq.logprobs), top_ids.shape[-1])
        return {
            "logprob": float(logp[i]),
            "top": [
                (int(top_ids[i, j]), float(top_lp[i, j])) for j in range(k)
            ],
        }

    def _trace_first_token(self, seq: SequenceState) -> None:
        """First output token of a traced sequence: record the
        ``engine.prefill`` span (admission → first token — chunked prompt
        compute plus the first sampled fetch) with a ``first_token`` event,
        the TTFT decomposition's engine-side anchor.  One latch per
        sequence; untraced rows cost a single attr check."""
        st = seq.trace
        if st is None or st.first_done:
            return
        st.first_done = True
        from ..runtime.tracing import _wall_ms
        from ..runtime.tracing import collector as trace_collector

        now = time.perf_counter()
        trace_collector.record(
            st.ctx, "engine.prefill", "engine",
            st.t_admit or st.t_enqueue, now,
            attrs={
                "prompt_tokens": len(seq.prompt),
                "cached_tokens": seq.num_cached_prompt,
            },
            events=[{"name": "first_token", "t_ms": round(_wall_ms(now), 3)}],
        )

    def _trace_decode_chunk(self, rows, t0: float, t1: float, steps: int) -> None:
        """One ``engine.decode_chunk`` span per TRACED row per fused
        dispatch — the ISSUE 15 granularity contract: decode records at
        chunk (dispatch) granularity only, never per token.  Untraced rows
        cost one attr check per chunk; rows whose first token hasn't
        landed yet are skipped (their wall belongs to engine.prefill)."""
        for _i, seq in rows:
            if seq is None:
                continue
            st = seq.trace
            if st is None or not st.first_done:
                continue
            from ..runtime.tracing import collector as trace_collector

            trace_collector.record(
                st.ctx, "engine.decode_chunk", "engine", t0, t1,
                attrs={"steps": steps},
            )

    def _accept_token(
        self,
        seq: SequenceState,
        token: int,
        defer_removal: bool = False,
        logprobs: Optional[Dict[str, Any]] = None,
    ) -> None:
        if seq.trace is not None:
            self._trace_first_token(seq)
        seq.output.append(token)
        reason = self._check_stop(seq, token)
        # Grammar advance (llm/tenancy): the automaton state moves per
        # ACCEPTED token — constrained rows only flow through this accept
        # path (never the fused-chunk ones), so this is the single place
        # tenant state advances.
        emit_with_stop = False
        violation = False
        if seq.grammar is not None and reason is not FinishReason.STOP:
            nxt = seq.grammar.advance(seq.grammar_state, token)
            if nxt is None:
                # Defensive — the logit mask makes this unreachable; if it
                # ever fires, fail the stream rather than emit output that
                # cannot parse under the schema.
                tenancy_metrics.grammar_violations_total += 1
                violation = True
                reason = reason or FinishReason.ERROR
            else:
                seq.grammar_state = nxt
                if reason is None and seq.grammar.is_terminal(nxt):
                    # The value is complete and only EOS could follow: this
                    # token is real content (unlike eos/stop tokens), so it
                    # is emitted AND the stream finishes.
                    reason = FinishReason.STOP
                    emit_with_stop = True
        queue = self._queues.get(seq.request_id)
        # Stop-triggering tokens (eos / stop_token_ids) are not emitted,
        # matching the reference Backend's stop handling (backend.rs:234-423).
        if queue is not None and not violation and (
            reason is not FinishReason.STOP or emit_with_stop
        ):
            item = LLMEngineOutput.token(token)
            if logprobs is not None:
                item["logprobs"] = logprobs
            queue.put_nowait(item)
        if reason is not None:
            seq.finished = True
            if not defer_removal:
                self.scheduler.remove(seq)
            self._finish(seq, reason)

    def _check_stop(self, seq: SequenceState, token: int) -> Optional[FinishReason]:
        n_out = seq.num_output_tokens  # survives preemption's prompt-folding
        if (
            seq.grammar is not None
            and token in self.model_config.eos_token_ids
        ):
            # Grammar completion ends the stream regardless of ignore_eos /
            # min_tokens: the mask admits EOS only in accepting states, and
            # an un-advanceable eos "content" token would wedge the
            # automaton (eos has no edge).
            return FinishReason.STOP
        min_ok = seq.min_new_tokens is None or n_out >= seq.min_new_tokens
        if min_ok and token in seq.stop_token_ids:
            return FinishReason.STOP
        if (
            min_ok
            and not seq.ignore_eos
            and token in self.model_config.eos_token_ids
        ):
            return FinishReason.STOP
        if seq.max_new_tokens is not None and n_out >= seq.max_new_tokens:
            return FinishReason.LENGTH
        if seq.total_tokens >= self.cfg.max_model_len:
            return FinishReason.LENGTH
        return None

    def _finish(self, seq: SequenceState, reason: FinishReason) -> None:
        # Drop the adapter-slot pin BEFORE the queue check: every finish
        # path funnels here (including cancelled/error streams whose queue
        # is already gone), and a leaked ref would pin the slot forever.
        if (
            self._lora_registry is not None
            and seq.adapter is not None
            and not seq.adapter_released
        ):
            seq.adapter_released = True
            self._lora_registry.release(seq.adapter)
        queue = self._queues.get(seq.request_id)
        if queue is None:
            return
        queue.put_nowait(
            LLMEngineOutput.finished(
                reason,
                usage={
                    "prompt_tokens": seq.orig_prompt_len,
                    "completion_tokens": seq.num_output_tokens,
                    "total_tokens": seq.total_tokens,
                },
            )
        )
        queue.put_nowait(_FINISHED)
