"""Fused decode pipeline: unified ragged steps, multi-step decode chains,
deferred token fetches/harvest, mixed-phase bursts, and token acceptance.

Split out of engine.py as a pure move (r5; VERDICT r4 weak #7) — these are
TpuEngine methods, combined via mixin inheritance.  See engine.py for the
engine-wide invariants (device lock, dispatch ordering, trace format).
"""

from __future__ import annotations

import asyncio
import time
import logging
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)

from collections import deque

from ..llm.metrics import tenancy_metrics
from ..llm.protocols import FinishReason, LLMEngineOutput
from ..ops.sampling import SamplingParams
from .scheduler import SequenceState, StepPlan
from ..models.llama import RaggedBatch

_FINISHED = object()  # queue sentinel (engine.py imports this)


class DecodePipelineMixin:
    # Numpy fast path for per-chunk token acceptance (_accept_chunk); tests
    # flip this off to prove equivalence against the scalar loop.
    _vectorized_accept = True

    def _sampling_arrays(
        self,
        seqs: List[SequenceState],
        step_offsets: Optional[List[int]] = None,
        grammar_states: Optional[List[Optional[int]]] = None,
    ) -> SamplingParams:
        """Build the per-row device sampling state for this step.

        ``seqs`` is one entry per batch ROW (a sequence may own several
        rows in a speculative verification step; ``step_offsets[i]`` then
        shifts row i's rng-stream position to the output index it scores —
        engine/spec.py).  The counts matrix ([S, V], penalties) is the
        engine's cached all-zeros DEVICE buffer unless some row actually
        uses a penalty — the common path never pays the [S, V]
        host→device transfer.  Same economy for the grammar mask
        ([S, ceil(V/32)] packed bits, llm/tenancy): the cached all-zero
        device buffer rides along (cond-skipped) unless a constrained row
        is present.  ``grammar_states[i]`` overrides row i's automaton
        state (spec verification scores draft positions, whose states are
        the current state advanced through the draft prefix); -1 forces
        the row unconstrained (positions past an inadmissible draft token
        — their samples can never commit, but they must not sample from an
        all-masked distribution)."""
        S = self.cfg.max_batch
        V = self.model_config.vocab_size
        seeds = np.zeros((S,), np.uint32)
        steps = np.zeros((S,), np.int32)
        temp = np.zeros((S,), np.float32)
        topk = np.zeros((S,), np.int32)
        topp = np.ones((S,), np.float32)
        fpen = np.zeros((S,), np.float32)
        ppen = np.zeros((S,), np.float32)
        need_lp = False
        any_pen = False
        for i, seq in enumerate(seqs):
            seeds[i] = seq.sampling_seed
            steps[i] = seq.num_output_tokens + (
                step_offsets[i] if step_offsets is not None else 0
            )
            temp[i] = seq.sampling_temperature
            topk[i] = seq.sampling_top_k
            topp[i] = seq.sampling_top_p
            fpen[i] = seq.freq_penalty
            ppen[i] = seq.pres_penalty
            need_lp = need_lp or seq.logprobs is not None
            any_pen = any_pen or seq.freq_penalty != 0 or seq.pres_penalty != 0
        if any_pen:
            counts_np = np.zeros((S, V), np.int16)
            for i, seq in enumerate(seqs):
                # Generated tokens since the ORIGINAL prompt: preemption and
                # migration-resume fold output into ``prompt``, and counting
                # ``output`` alone would silently drop the folded tokens'
                # penalty contributions exactly when a request resumes.
                gen = np.asarray(
                    (seq.prompt + seq.output)[seq.orig_prompt_len :], np.int64
                )
                if gen.size:
                    np.add.at(counts_np[i], gen % V, 1)
            if self._rep_sharding is not None:
                counts = self._prep(counts_np)
            else:
                counts = jnp.asarray(counts_np)  # committed, key matches cache
        else:
            counts = self._zero_counts

        # Grammar masks (llm/tenancy/grammar.py): packed admissible-token
        # bits for constrained rows; unconstrained rows get all-ones.
        masked_rows = [
            i
            for i, seq in enumerate(seqs)
            if seq.grammar is not None
            and (grammar_states is None or grammar_states[i] != -1)
        ]
        if masked_rows:
            mw = np.full((S, self._mask_w), 0xFFFFFFFF, np.uint32)
            for i in masked_rows:
                seq = seqs[i]
                state = seq.grammar_state
                if grammar_states is not None and grammar_states[i] is not None:
                    state = grammar_states[i]
                mw[i] = seq.grammar.packed_mask(state)
            # jnp, not np: device arrays and numpy arrays key DIFFERENT
            # jit-cache entries, and the warmup/common path dispatches the
            # cached device zero-mask — same trick as the counts buffer.
            mask_words: Any = jnp.asarray(mw)
            any_mask = np.asarray(True)
            tenancy_metrics.grammar_masked_rows_total += len(masked_rows)
        else:
            mask_words = self._zero_mask
            any_mask = np.asarray(False)
        # LoRA slots (llm/tenancy/lora.py): per-row resident adapter slot,
        # -1 = base.  None (absent from the jit treedef) on LoRA-less
        # engines so their compiled programs are unchanged.
        if self._lora_registry is not None:
            aslots: Any = np.full((S,), -1, np.int32)
            for i, seq in enumerate(seqs):
                aslots[i] = seq.adapter_slot
        else:
            aslots = None
        return SamplingParams(
            seeds=seeds,
            steps=steps,
            temperature=temp,
            top_k=topk,
            top_p=topp,
            freq_penalty=fpen,
            pres_penalty=ppen,
            counts=counts,
            need_logprobs=np.asarray(need_lp),
            mask_words=mask_words,
            any_mask=any_mask,
            adapter_slots=aslots,
        )

    def _tables_row(self, out: np.ndarray, i: int, seq: SequenceState) -> None:
        ids = seq.block_ids[: out.shape[1]]
        out[i, : len(ids)] = ids

    def _build_ragged(self, items) -> RaggedBatch:
        bs = self.cfg.block_size
        S = self.cfg.max_batch
        PP = self.cfg.max_blocks_per_seq
        total = sum(n for _, _, n in items)
        T = self.cfg.bucket_tokens(total)

        tok = np.zeros((T,), np.int32)
        pos = np.zeros((T,), np.int32)
        slots = np.full((T,), -1, np.int32)
        kv_lens = np.zeros((S,), np.int32)
        tables = np.zeros((S, PP), np.int32)
        cu = np.zeros((S + 1,), np.int32)
        aslots = (
            np.full((T,), -1, np.int32)
            if self._lora_registry is not None
            else None
        )
        at = 0
        for i, (seq, start, n) in enumerate(items):
            all_toks = seq.prompt + seq.output
            tok[at : at + n] = all_toks[start : start + n]
            p = np.arange(start, start + n, dtype=np.int32)
            pos[at : at + n] = p
            blk = np.asarray(seq.block_ids, np.int32)
            slots[at : at + n] = blk[p // bs] * bs + p % bs
            if aslots is not None:
                aslots[at : at + n] = seq.adapter_slot
            self._tables_row(tables, i, seq)
            kv_lens[i] = start + n
            at += n
            cu[i + 1] = at
        cu[len(items) + 1 :] = at
        return RaggedBatch(
            token_ids=tok,
            positions=pos,
            slot_mapping=slots,
            kv_lens=kv_lens,
            page_indices=tables,
            cu_q_lens=cu,
            num_seqs=np.asarray([len(items)], np.int32),
            adapter_slots=aslots,
        )

    async def _run_unified(self, plan: StepPlan) -> None:
        rb = self._build_ragged(plan.items)
        samp = self._sampling_arrays([s for s, _, _ in plan.items])
        need_lp = bool(samp.need_logprobs)
        # A step whose every row stays mid-prefill produces sampled tokens
        # nobody consumes — skip the device→host fetch entirely and let the
        # next chunk's dispatch queue behind this one.  Over the tunneled
        # chip a blocking fetch costs ~100ms/chunk, which made chunked
        # prefill RTT-bound (r3: TTFT 1343ms for ISL 3000 vs ~200ms of
        # device compute); co-located it still saves a sync per chunk.
        need_tokens = any(
            start + n >= len(seq.prompt) for seq, start, n in plan.items
        )
        if self._rep_sharding is not None:
            rb_d, samp_d = self._prep((rb, samp))
        else:
            rb_d, samp_d = rb, samp
        step = self._step_fn
        # Park rows BEFORE the first suspension point, not after the
        # dispatch: from here to the harvest this coroutine yields, and
        # anything polling quiescence (freeze_sequence, engine/migrate.py)
        # must see these rows as having a token en route — marking after
        # the await left a window where a migration snapshot missed the
        # in-flight token and the client received it twice.  (Rows of OLD
        # pending fetches are disjoint from this plan's rows — the
        # scheduler never plans a parked row — so the harvests below can't
        # clear these marks early.)
        for seq, start, n in plan.items:
            if not seq.finished and start + n >= len(seq.prompt):
                seq.awaiting_fetch = True
        while self._pending_fetches and self._pending_fetches[0][1].done():
            await self._harvest_pending()  # free: task already complete

        def run():
            out, self.cache = step(self.params, self.cache, rb_d, samp_d)
            if need_tokens:
                # Start the D2H now; the accept is deferred to a harvest
                # point so the round trip overlaps later dispatches.
                try:
                    out.tokens.copy_to_host_async()
                    if need_lp:
                        out.logprob.copy_to_host_async()
                        out.top_ids.copy_to_host_async()
                        out.top_logprobs.copy_to_host_async()
                except AttributeError:
                    pass
            return out

        t0 = time.perf_counter()
        async with self._device_lock:
            # Publish INSIDE the device lock: broadcast order must equal
            # device enqueue order or followers replay a different program
            # sequence than the leader ran (SPMD divergence).
            if self._publisher is not None:
                await self._publisher.publish(
                    "unified",
                    (rb, jax.tree_util.tree_map(np.asarray, samp)),
                )
            out = await asyncio.to_thread(run)
        self.step_trace.append(
            (
                "unified_fetch" if need_tokens else "unified",
                time.perf_counter() - t0,
                len(plan.items),
                len(rb.token_ids),
            )
        )

        pending_rows: List[Tuple[SequenceState, int]] = []
        for i, (seq, start, n) in enumerate(plan.items):
            if seq.finished:
                seq.awaiting_fetch = False  # pre-marked above; never parked
                continue
            if start >= len(seq.prompt):
                # Decode row: the fed token joins the hash stream.
                seq.block_seq.append((seq.prompt + seq.output)[start])
            seq.num_computed = start + n
            self._seal_completed_blocks(seq)
            if not seq.in_prefill:
                # This row's sampled token is in flight (pre-marked before
                # the dispatch); park the row until a harvest point applies
                # it.
                seq.awaiting_fetch = True
                pending_rows.append((seq, i))
        if pending_rows:
            self._stash_fetch("first", out, need_lp, pending_rows)

    def _stash_fetch(self, kind: str, out, need_lp: bool, *meta) -> None:
        """Park a dispatched step's token fetch: the np.asarray runs on a
        worker thread STARTING NOW (the D2H was already initiated with
        copy_to_host_async), and the loop applies the result at a harvest
        point once the task completes — the device round trip never blocks
        dispatching."""

        def fetch():
            if need_lp:
                return (
                    np.asarray(out.tokens),
                    np.asarray(out.logprob),
                    np.asarray(out.top_ids),
                    np.asarray(out.top_logprobs),
                )
            return np.asarray(out.tokens), None, None, None

        task = asyncio.get_running_loop().create_task(asyncio.to_thread(fetch))
        self._pending_fetches.append((kind, task, *meta))

    async def _harvest_pending(self, all_pending: bool = False) -> None:
        """Apply deferred fetches in dispatch order.  Harvests the oldest
        entry (awaiting its background task), or everything outstanding."""
        while self._pending_fetches:
            entry = self._pending_fetches.pop(0)
            kind, task = entry[0], entry[1]

            t0 = time.perf_counter()
            sampled, logp, top_ids, top_lp = await task
            self.step_trace.append(
                (
                    f"{kind}_harvest",
                    time.perf_counter() - t0,
                    len(entry[2]),
                    0,
                )
            )
            if kind == "first":
                for seq, i in entry[2]:
                    seq.awaiting_fetch = False
                    if seq.finished:
                        continue  # cancelled while the token was in flight
                    self._accept_token(
                        seq,
                        int(sampled[i]),
                        logprobs=self._lp_info(seq, i, logp, top_ids, top_lp),
                    )
            elif kind == "spec":  # speculative verification (engine/spec.py)
                self._harvest_spec(entry, sampled, logp, top_ids, top_lp)
            else:  # burst
                members, pos0 = entry[2], entry[3]
                finished: List[SequenceState] = []
                self._accept_chunk(
                    members, pos0, sampled, logp, top_ids, top_lp, finished
                )
                for seq in finished:
                    self.scheduler.remove(seq)
            if not all_pending:
                break

    async def _decode_pipeline(self, members: List[SequenceState]) -> bool:
        """Steady-state decode: fused multi-step dispatches with the token
        carry on device, up to cfg.pipeline_depth dispatches in flight, host
        readback overlapped.  Runs until membership must change (a sequence
        finished/cancelled, a new request arrived, or blocks ran out), then
        drains in-flight work before returning so the scheduler can rebuild.

        Invariant: no member's KV blocks are freed while any dispatch that
        writes them is in flight — finishes are deferred to the drain point.
        """
        cfg = self.cfg
        bs = cfg.block_size
        S, T = cfg.max_batch, cfg.decode_steps
        n = len(members)
        # Visible to freeze_sequence (engine/migrate.py): a member may have
        # fused chunks in flight until this pipeline run drains and returns.
        self._pipeline_members = {s.request_id for s in members}

        tok0 = np.zeros((S,), np.int32)
        pos_disp = np.full((S,), -1, np.int32)  # dispatch frontier (-1 = pad)
        for i, seq in enumerate(members):
            all_toks = seq.prompt + seq.output
            tok0[i] = all_toks[seq.num_computed]
            pos_disp[i] = seq.num_computed
        tables = np.zeros((S, cfg.max_blocks_per_seq), np.int32)
        for i, seq in enumerate(members):
            self._tables_row(tables, i, seq)
        samp = self._sampling_arrays(members)
        # Host copy only needed for the follower broadcast — np.asarray on
        # samp.counts would otherwise drag the [S, V] device buffer to host
        # on every pipeline build.
        samp_np = (
            jax.tree_util.tree_map(np.asarray, samp)
            if self._publisher is not None
            else None
        )
        need_lp = bool(samp.need_logprobs)
        # (token, rng-step, penalty-counts) carry: numpy seeds for the first
        # dispatch, then the previous dispatch's on-device outputs.
        carry: Optional[Tuple[Any, Any, Any]] = None
        multi = self._multi_fn

        inflight: deque = deque()
        finished_members: List[SequenceState] = []
        rebuild = False
        dispatched_any = False

        def want_rebuild() -> bool:
            # Waiting requests only force a rebuild when one could actually
            # be ADMITTED (free slot + blocks).  At oversubscription the
            # queue is never empty; gating on num_waiting alone would keep
            # the fused pipeline permanently disabled (round-3 saturation
            # collapse: conc 32 throughput below conc 16).
            return (
                self._closed
                or self.scheduler.admission_ready()
                or any(s.finished or s.frozen for s in members)
                or any(
                    (c := self._contexts.get(s.request_id)) is not None
                    and c.is_stopped
                    for s in members
                )
            )

        while True:
            # Top up the dispatch window.  With requests queued, cap the
            # in-flight depth at 2 (enough to overlap fetch with compute) so
            # the drain a newcomer's admission must wait for stays bounded.
            depth = (
                min(cfg.pipeline_depth, 2)
                if self.scheduler.num_waiting
                else cfg.pipeline_depth
            )
            while not rebuild and len(inflight) < depth:
                # Don't dispatch chunks no row can still use: once every
                # member's in-flight frontier covers its remaining token
                # budget, further chunks are pure waste (their tokens would
                # all be discarded host-side).  Checked BEFORE allocating
                # lookahead blocks below — a never-dispatched chunk must not
                # take KV capacity from other sequences.
                if not self._any_useful_rows(members, pos_disp):
                    rebuild = True
                    break
                # Ensure every active member has KV room for this chunk.
                limits = np.zeros((S,), np.int32)
                ok = True
                for i, seq in enumerate(members):
                    if seq.finished:
                        pos_disp[i] = -1
                        continue
                    need = int(pos_disp[i]) + T - seq.num_computed
                    if not self.scheduler._ensure_slot(seq, lookahead=need):
                        ok = False
                    self._tables_row(tables, i, seq)
                    limits[i] = min(
                        len(seq.block_ids) * bs,
                        cfg.max_blocks_per_seq * bs,
                    )
                if not ok:
                    # Out of KV headroom: drain any in-flight work, then
                    # return so schedule() can preempt with nothing pending.
                    rebuild = True
                    break
                pos0 = pos_disp.copy()
                first = carry is None
                pub_payload = (
                    tok0 if first else None,  # None → follower's own carry
                    pos0,
                    tables.copy(),
                    limits,
                    samp_np,
                )
                if first:
                    c_tok, c_steps, c_counts = tok0, samp.steps, samp.counts
                    if self._rep_sharding is not None:
                        c_tok, c_steps = self._prep((c_tok, c_steps))
                else:
                    c_tok, c_steps, c_counts = carry
                if self._rep_sharding is not None:
                    d_args = self._prep((pos0, tables.copy(), limits, samp))
                else:
                    d_args = (pos0, tables, limits, samp)

                def dispatch(args=d_args, tok_in=c_tok, st=c_steps, ct=c_counts):
                    outs, last, steps_f, counts_f, self.cache = multi(
                        self.params, self.cache, tok_in, st, ct, *args
                    )
                    return outs, (last, steps_f, counts_f)

                t0 = time.perf_counter()
                async with self._device_lock:
                    # Broadcast order must equal enqueue order (see
                    # _run_unified) — publish under the device lock.
                    if self._publisher is not None:
                        await self._publisher.publish("multi", pub_payload)
                    outs, carry = await asyncio.to_thread(dispatch)
                self.step_trace.append(
                    ("decode_dispatch", time.perf_counter() - t0, n, n * T)
                )
                # Start the D2H copy NOW: it proceeds in the background while
                # later chunks compute, so the drain fetch below pays ~zero
                # round-trip instead of compute + full link latency (round-2
                # measured 323ms per serial fetch over the tunneled chip).
                try:
                    outs.tokens.copy_to_host_async()
                    if need_lp:
                        outs.logprob.copy_to_host_async()
                        outs.top_ids.copy_to_host_async()
                        outs.top_logprobs.copy_to_host_async()
                except AttributeError:
                    pass
                inflight.append((outs, pos0))
                dispatched_any = True
                pos_disp = np.where(pos_disp >= 0, pos_disp + T, pos_disp)
                if want_rebuild():
                    rebuild = True

            if not inflight:
                break

            # Await the oldest chunk's tokens and apply them.
            outs, pos0 = inflight.popleft()
            t0 = time.perf_counter()

            def fetch(o=outs):
                if need_lp:
                    return (
                        np.asarray(o.tokens),
                        np.asarray(o.logprob),
                        np.asarray(o.top_ids),
                        np.asarray(o.top_logprobs),
                    )
                return np.asarray(o.tokens), None, None, None

            sampled, logp, top_ids, top_lp = await asyncio.to_thread(fetch)
            self.step_trace.append(
                # "wait" not "fetch": the D2H copy was started at dispatch,
                # so this wall is dominated by the chunk's device compute.
                ("decode_wait", time.perf_counter() - t0, n, n * T)
            )
            self._accept_chunk(
                members, pos0, sampled, logp, top_ids, top_lp, finished_members
            )
            if not rebuild and self._spec_session_probe(members):
                # Output grew repetitive enough that in-step speculation
                # now beats the fused chunks: drain and let schedule()
                # re-propose for real (engine/spec.py).
                rebuild = True
            if want_rebuild():
                rebuild = True
            if rebuild and not inflight:
                break
            await asyncio.sleep(0)  # let ingress/egress run between chunks

        # Drained: now it is safe to release finished members' blocks.
        self._pipeline_members = set()
        for seq in finished_members:
            self.scheduler.remove(seq)
        return dispatched_any

    async def _decode_burst(self, members: List[SequenceState]) -> bool:
        """ONE fused multi-step dispatch for ``members`` (all decoding):
        decode_steps tokens per row for a single device round trip, used in
        mixed phases where prefill rows keep the full pipeline from
        engaging.  Same discard semantics as the pipeline: tokens past a
        row's stop/limit are dropped host-side.  Returns False (dispatching
        nothing) when KV headroom for a full burst is missing."""
        cfg = self.cfg
        bs = cfg.block_size
        S, T = cfg.max_batch, cfg.decode_steps
        n = len(members)
        tok0 = np.zeros((S,), np.int32)
        pos0 = np.full((S,), -1, np.int32)
        tables = np.zeros((S, cfg.max_blocks_per_seq), np.int32)
        limits = np.zeros((S,), np.int32)
        for i, seq in enumerate(members):
            if seq.finished or seq.frozen:
                return False  # membership changed under us: replan
            if seq.grammar is not None:
                # Constrained rows never burst: their mask advances
                # host-side per accepted token (callers route them to
                # unified steps — this is the safety net).
                return False
            if not self.scheduler._ensure_slot(seq, lookahead=T):
                return False
            all_toks = seq.prompt + seq.output
            tok0[i] = all_toks[seq.num_computed]
            pos0[i] = seq.num_computed
            self._tables_row(tables, i, seq)
            limits[i] = min(
                len(seq.block_ids) * bs, cfg.max_blocks_per_seq * bs
            )
        # Park BEFORE the first suspension point (see _run_unified):
        # quiescence pollers must count the burst's in-flight tokens from
        # the moment this coroutine can yield, not from when the dispatch
        # returns.
        for seq in members:
            seq.awaiting_fetch = True
        while self._pending_fetches and self._pending_fetches[0][1].done():
            await self._harvest_pending()  # free: task already complete
        samp = self._sampling_arrays(members)
        need_lp = bool(samp.need_logprobs)
        c_tok, c_steps = tok0, samp.steps
        if self._rep_sharding is not None:
            c_tok, c_steps = self._prep((c_tok, c_steps))
            d_args = self._prep((pos0, tables, limits, samp))
        else:
            d_args = (pos0, tables, limits, samp)
        multi = self._multi_fn

        def run():
            outs, _last, _steps, _counts, self.cache = multi(
                self.params, self.cache, c_tok, c_steps, samp.counts, *d_args
            )
            # Async D2H + deferred accept: the burst's tokens are only
            # needed at the next harvest point (its rows are parked), so
            # the round trip overlaps the following prefill chunks instead
            # of stalling behind the device queue.
            try:
                outs.tokens.copy_to_host_async()
                if need_lp:
                    outs.logprob.copy_to_host_async()
                    outs.top_ids.copy_to_host_async()
                    outs.top_logprobs.copy_to_host_async()
            except AttributeError:
                pass
            return outs

        t0 = time.perf_counter()
        async with self._device_lock:
            if self._publisher is not None:
                await self._publisher.publish(
                    "multi",
                    (
                        tok0,
                        pos0,
                        tables.copy(),
                        limits,
                        jax.tree_util.tree_map(np.asarray, samp),
                    ),
                )
            outs = await asyncio.to_thread(run)
        self.step_trace.append(
            ("decode_burst", time.perf_counter() - t0, n, n * T)
        )
        self._stash_fetch("burst", outs, need_lp, members, pos0)
        return True

    def _any_useful_rows(
        self, members: List[SequenceState], pos_disp: np.ndarray
    ) -> bool:
        """True if any active member could still accept a token from one more
        fused chunk, given how far its dispatch frontier already overshoots
        its accepted position (in-flight tokens count against the budget)."""
        for i, seq in enumerate(members):
            if seq.finished or pos_disp[i] < 0:
                continue
            overshoot = int(pos_disp[i]) - seq.num_computed
            budget = self.cfg.max_model_len - seq.total_tokens
            if seq.max_new_tokens is not None:
                budget = min(budget, seq.max_new_tokens - seq.num_output_tokens)
            if budget - overshoot > 0:
                return True
        return False

    def _seal_completed_blocks(self, seq: SequenceState) -> None:
        complete = seq.num_computed // self.cfg.block_size
        hashed = len(seq.block_seq.blocks)
        while seq.num_sealed_blocks < min(complete, hashed):
            idx = seq.num_sealed_blocks
            tb = seq.block_seq.blocks[idx]
            self.kv.seal_block(seq.block_ids[idx], tb)
            seq.num_sealed_blocks += 1
            if self.host_kv is not None and not self.host_kv.contains(
                tb.sequence_hash
            ):
                self._offload_queue.append((seq.block_ids[idx], tb))

    def _accept_chunk(
        self,
        members: List[SequenceState],
        pos0: np.ndarray,
        sampled: np.ndarray,  # [T, S]
        logp,
        top_ids,
        top_lp,
        finished: List[SequenceState],
    ) -> None:
        """Apply one fused chunk's sampled tokens to ``members``.

        Fast path: a row without logprobs computes its whole accept run
        with numpy mask math (allocation wall, LENGTH cutoffs, stop
        tokens under min_new_tokens) and emits ONE multi-token queue item
        — the scalar ``for t: for seq`` loop was the dominant term of the
        r5 16% host gap at batch 256.  Rows needing per-token logprob
        payloads (and engines with ``_vectorized_accept=False``, the
        test toggle) take the scalar row loop; both paths produce
        identical streams (tests/test_spec_decode.py asserts it)."""
        T = int(sampled.shape[0])
        bs = self.cfg.block_size
        for i, seq in enumerate(members):
            seq.awaiting_fetch = False
            if seq.finished or pos0[i] < 0:
                continue
            p0 = int(pos0[i])
            if seq.num_computed != p0:
                continue  # stopped/hit the allocation wall in a prior chunk
            if not self._vectorized_accept or seq.logprobs is not None:
                self._accept_chunk_row_scalar(
                    seq, i, p0, sampled, logp, top_ids, top_lp, finished
                )
                continue
            n_cap = min(T, len(seq.block_ids) * bs - p0)
            if n_cap <= 0:
                continue  # beyond allocation: tokens were never KV-backed
            col = np.asarray(sampled[:, i])
            # LENGTH cutoff: the token that reaches the budget is accepted
            # (and emitted) with finish_reason length, exactly as
            # _check_stop does after each append.
            m_len = self.cfg.max_model_len - seq.total_tokens
            if seq.max_new_tokens is not None:
                m_len = min(
                    m_len, seq.max_new_tokens - seq.num_output_tokens
                )
            m_len = max(1, m_len)
            if m_len <= n_cap:
                n_acc, reason = m_len, FinishReason.LENGTH
            else:
                n_acc, reason = n_cap, None
            stops = set(seq.stop_token_ids)
            if not seq.ignore_eos:
                stops |= set(self.model_config.eos_token_ids)
            if stops:
                hit = np.isin(col, np.fromiter(stops, np.int64))
                if seq.min_new_tokens is not None:
                    # Token m (1-based) lands at output index n_out + m.
                    hit &= (
                        seq.num_output_tokens + 1 + np.arange(T)
                    ) >= seq.min_new_tokens
                idx = np.nonzero(hit)[0]
                if idx.size and int(idx[0]) + 1 <= n_acc:
                    # STOP wins ties with LENGTH (stop checks run first).
                    n_acc, reason = int(idx[0]) + 1, FinishReason.STOP
            # Fed tokens: the committed tail + each previously sampled
            # token — members are decoding, so all join the hash stream.
            fed = [(seq.prompt + seq.output)[p0]] + [
                int(x) for x in col[: n_acc - 1]
            ]
            seq.block_seq.extend(fed)
            seq.num_computed += n_acc
            self._seal_completed_blocks(seq)
            toks = [int(x) for x in col[:n_acc]]
            seq.output.extend(toks)
            emit = toks[:-1] if reason is FinishReason.STOP else toks
            queue = self._queues.get(seq.request_id)
            if queue is not None and emit:
                queue.put_nowait(LLMEngineOutput.tokens(emit))
            if reason is not None:
                seq.finished = True
                finished.append(seq)
                self._finish(seq, reason)

    def _accept_chunk_row_scalar(
        self,
        seq: SequenceState,
        i: int,
        p0: int,
        sampled: np.ndarray,
        logp,
        top_ids,
        top_lp,
        finished: List[SequenceState],
    ) -> None:
        """Reference per-token accept loop for one row (logprob payloads
        are per token; also the oracle the vectorized path is tested
        against)."""
        bs = self.cfg.block_size
        for t in range(sampled.shape[0]):
            if seq.num_computed != p0 + t:
                continue  # stopped earlier in this chunk
            if seq.num_computed >= len(seq.block_ids) * bs:
                continue  # beyond allocation: token was never KV-backed
            fed = (seq.prompt + seq.output)[seq.num_computed]
            if seq.num_computed >= len(seq.prompt):
                seq.block_seq.append(fed)
            seq.num_computed += 1
            self._seal_completed_blocks(seq)
            self._accept_token(
                seq,
                int(sampled[t, i]),
                defer_removal=True,
                logprobs=self._lp_info(
                    seq,
                    i,
                    None if logp is None else logp[t],
                    None if top_ids is None else top_ids[t],
                    None if top_lp is None else top_lp[t],
                ),
            )
            if seq.finished:
                finished.append(seq)
                break

    def _lp_info(
        self, seq: SequenceState, i: int, logp, top_ids, top_lp
    ) -> Optional[Dict[str, Any]]:
        """Per-token logprob payload for row ``i`` (None unless requested)."""
        if seq.logprobs is None or logp is None:
            return None
        k = min(int(seq.logprobs), top_ids.shape[-1])
        return {
            "logprob": float(logp[i]),
            "top": [
                (int(top_ids[i, j]), float(top_lp[i, j])) for j in range(k)
            ],
        }

    def _accept_token(
        self,
        seq: SequenceState,
        token: int,
        defer_removal: bool = False,
        logprobs: Optional[Dict[str, Any]] = None,
    ) -> None:
        seq.output.append(token)
        reason = self._check_stop(seq, token)
        # Grammar advance (llm/tenancy): the automaton state moves per
        # ACCEPTED token — constrained rows only flow through this accept
        # path (never the fused-chunk ones), so this is the single place
        # tenant state advances.
        emit_with_stop = False
        violation = False
        if seq.grammar is not None and reason is not FinishReason.STOP:
            nxt = seq.grammar.advance(seq.grammar_state, token)
            if nxt is None:
                # Defensive — the logit mask makes this unreachable; if it
                # ever fires, fail the stream rather than emit output that
                # cannot parse under the schema.
                tenancy_metrics.grammar_violations_total += 1
                violation = True
                reason = reason or FinishReason.ERROR
            else:
                seq.grammar_state = nxt
                if reason is None and seq.grammar.is_terminal(nxt):
                    # The value is complete and only EOS could follow: this
                    # token is real content (unlike eos/stop tokens), so it
                    # is emitted AND the stream finishes.
                    reason = FinishReason.STOP
                    emit_with_stop = True
        queue = self._queues.get(seq.request_id)
        # Stop-triggering tokens (eos / stop_token_ids) are not emitted,
        # matching the reference Backend's stop handling (backend.rs:234-423).
        if queue is not None and not violation and (
            reason is not FinishReason.STOP or emit_with_stop
        ):
            item = LLMEngineOutput.token(token)
            if logprobs is not None:
                item["logprobs"] = logprobs
            queue.put_nowait(item)
        if reason is not None:
            seq.finished = True
            if not defer_removal:
                self.scheduler.remove(seq)
            self._finish(seq, reason)

    def _check_stop(self, seq: SequenceState, token: int) -> Optional[FinishReason]:
        n_out = seq.num_output_tokens  # survives preemption's prompt-folding
        if (
            seq.grammar is not None
            and token in self.model_config.eos_token_ids
        ):
            # Grammar completion ends the stream regardless of ignore_eos /
            # min_tokens: the mask admits EOS only in accepting states, and
            # an un-advanceable eos "content" token would wedge the
            # automaton (eos has no edge).
            return FinishReason.STOP
        min_ok = seq.min_new_tokens is None or n_out >= seq.min_new_tokens
        if min_ok and token in seq.stop_token_ids:
            return FinishReason.STOP
        if (
            min_ok
            and not seq.ignore_eos
            and token in self.model_config.eos_token_ids
        ):
            return FinishReason.STOP
        if seq.max_new_tokens is not None and n_out >= seq.max_new_tokens:
            return FinishReason.LENGTH
        if seq.total_tokens >= self.cfg.max_model_len:
            return FinishReason.LENGTH
        return None

    def _finish(self, seq: SequenceState, reason: FinishReason) -> None:
        # Drop the adapter-slot pin BEFORE the queue check: every finish
        # path funnels here (including cancelled/error streams whose queue
        # is already gone), and a leaked ref would pin the slot forever.
        if (
            self._lora_registry is not None
            and seq.adapter is not None
            and not seq.adapter_released
        ):
            seq.adapter_released = True
            self._lora_registry.release(seq.adapter)
        queue = self._queues.get(seq.request_id)
        if queue is None:
            return
        queue.put_nowait(
            LLMEngineOutput.finished(
                reason,
                usage={
                    "prompt_tokens": seq.orig_prompt_len,
                    "completion_tokens": seq.num_output_tokens,
                    "total_tokens": seq.total_tokens,
                },
            )
        )
        queue.put_nowait(_FINISHED)
