"""Engine configuration knobs.

The reference exposes these through engine flags (`launch/dynamo-run/src/
flags.rs`: --context-length, --kv-cache-block-size, --tensor-parallel-size)
and vLLM config YAML; here they parameterise the native engine directly.
Bucketing fields exist because XLA compiles one program per shape: batch and
prefill-length buckets are powers of two, so a handful of compilations cover
every workload mix.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


# Canonical decode-kernel names (ops/ragged_attention.resolve_decode_kernel
# and the CLI --decode-kernel choices both derive from this — ONE list, so
# a new kernel cannot be reachable from the env but not the config/CLI).
# Lives here because config.py is the dependency-free bottom of the import
# graph; ops/ and cli import it lazily.
DECODE_KERNELS = ("pallas_fused", "stock", "xla")

# Canonical prefill-kernel names (ops/ragged_attention.resolve_prefill_kernel
# and the CLI share this the same way).
PREFILL_KERNELS = ("pallas", "stock", "xla")


def _pow2_buckets(lo: int, hi: int) -> List[int]:
    out, v = [], lo
    while v < hi:
        out.append(v)
        v *= 2
    out.append(hi)
    return sorted(set(out))


@dataclass
class SpecDecodeConfig:
    """Draft-free speculative decoding (engine/spec.py).

    The proposer is prompt-lookup (Saxena 2023): the last ``ngram_min..
    ngram_max`` tokens of a sequence are matched against its own
    prompt+output history and the continuation of the most recent match is
    proposed as a draft.  Drafts verify through the EXISTING unified ragged
    program — one single-token row per draft position, so per-position
    logits and the per-(seed, step) sampler come for free — and the longest
    prefix matching the seeded sample stream is accepted (greedy ≡ argmax
    match; temperature>0 ≡ exactly the tokens non-speculative decoding
    would have sampled).  Speculation on/off is token-for-token identical.
    """

    enable: bool = False
    # Suffix n-gram lengths tried longest-first against the history.
    ngram_min: int = 2
    ngram_max: int = 4
    # Draft-length ceiling per sequence per dispatch (the adaptive
    # controller moves each sequence's k inside [k_min, k]).
    k: int = 8
    k_min: int = 1
    # EWMA smoothing of per-dispatch acceptance (accepted/drafted).
    ewma_alpha: float = 0.3
    # Below this EWMA the sequence's proposer is benched ...
    accept_floor: float = 0.15
    # ... until this many more tokens have been committed, then re-probes
    # at k_min (templated traffic often turns repetitive mid-stream).
    cooldown_tokens: int = 64
    # Proposer matching window: only the last ``lookback`` history tokens
    # are scanned (0 = unlimited).  Bounds per-proposal cost at long
    # contexts; recent history is where templated repetition lives.
    lookback: int = 2048
    # Engagement bar vs the fused pipeline (pure-decode plans): speculate
    # when the expected committed tokens per round trip reach
    # ``pipeline_margin * n_decode * decode_steps``.  A verification step
    # streams the weights ONCE for all its rows where a fused chunk
    # streams them ``decode_steps`` times, so a verify step costs well
    # under half a chunk — 0.5 is conservative; raise toward 1.0 to be
    # stricter about leaving the pipeline.
    pipeline_margin: float = 0.5

    def __post_init__(self) -> None:
        if self.ngram_min < 1 or self.ngram_max < self.ngram_min:
            raise ValueError(
                f"spec_decode ngram range [{self.ngram_min}, {self.ngram_max}]"
                " must satisfy 1 <= ngram_min <= ngram_max"
            )
        if self.k < 1 or self.k_min < 1 or self.k_min > self.k:
            raise ValueError(
                f"spec_decode k range [{self.k_min}, {self.k}] must satisfy"
                " 1 <= k_min <= k"
            )
        if not 0.0 < self.ewma_alpha <= 1.0:
            raise ValueError("spec_decode ewma_alpha must be in (0, 1]")
        if self.pipeline_margin <= 0.0:
            raise ValueError("spec_decode pipeline_margin must be > 0")

    @classmethod
    def normalize(cls, v: Any) -> "SpecDecodeConfig":
        """Accept the config section in any layered-config shape: an
        instance, a dict (file/env layers), a bare bool, or None."""
        if v is None:
            return cls()
        if isinstance(v, cls):
            return v
        if isinstance(v, bool):
            return cls(enable=v)
        if isinstance(v, dict):
            known = set(cls.__dataclass_fields__)
            bad = set(v) - known
            if bad:
                raise ValueError(f"unknown spec_decode keys: {sorted(bad)}")
            return cls(**v)
        raise ValueError(f"bad spec_decode section: {v!r}")


@dataclass
class LoraConfig:
    """Batched multi-LoRA serving (llm/tenancy/lora.py — S-LoRA).

    ``max_adapters`` resident DEVICE slots of rank ceiling ``rank`` are
    allocated as fixed-shape banks at engine init, so registering /
    promoting / evicting adapters never changes a compiled program's shape
    — hot-swap is a host→device column write.  The host-side registry can
    hold arbitrarily many adapters; only the resident set is bounded.
    """

    enable: bool = False
    # Resident device slots (concurrent distinct adapters in one batch).
    max_adapters: int = 4
    # Per-slot rank ceiling; adapters with smaller rank zero-pad up.
    rank: int = 8
    # How long acquire() waits for a pinned slot to free before failing
    # the request (all residents actively serving sequences).
    promote_timeout_s: float = 30.0

    def __post_init__(self) -> None:
        if self.max_adapters < 1:
            raise ValueError("lora max_adapters must be >= 1")
        if self.rank < 1:
            raise ValueError("lora rank must be >= 1")

    @classmethod
    def normalize(cls, v: Any) -> "LoraConfig":
        """Accept the section in any layered-config shape (see
        SpecDecodeConfig.normalize)."""
        if v is None:
            return cls()
        if isinstance(v, cls):
            return v
        if isinstance(v, bool):
            return cls(enable=v)
        if isinstance(v, dict):
            known = set(cls.__dataclass_fields__)
            bad = set(v) - known
            if bad:
                raise ValueError(f"unknown lora keys: {sorted(bad)}")
            return cls(**v)
        raise ValueError(f"bad lora section: {v!r}")


@dataclass
class QosSchedConfig:
    """Scheduler-side QoS (engine/scheduler.py WfqQueue; llm/qos.py has the
    edge half).  Defaults reproduce pre-QoS behaviour exactly for
    single-tenant traffic: equal weights collapse WFQ to per-tenant FIFO,
    and FIFO within one tenant.
    """

    # Tenant → WFQ weight (share of admission work while backlogged).
    tenant_weights: Dict[str, float] = field(default_factory=dict)
    default_weight: float = 1.0
    # Batch-class starvation bound: at most this many consecutive
    # interactive admissions while batch is backlogged before one batch
    # admission is forced.
    batch_every: int = 4

    def __post_init__(self) -> None:
        if self.default_weight <= 0:
            raise ValueError("qos default_weight must be > 0")
        if self.batch_every < 1:
            raise ValueError("qos batch_every must be >= 1")
        for name, w in self.tenant_weights.items():
            if float(w) <= 0:
                raise ValueError(f"qos tenant weight {name!r} must be > 0")

    @classmethod
    def normalize(cls, v: Any) -> "QosSchedConfig":
        """Accept the section in any layered-config shape (see
        SpecDecodeConfig.normalize)."""
        if v is None:
            return cls()
        if isinstance(v, cls):
            return v
        if isinstance(v, dict):
            known = set(cls.__dataclass_fields__)
            bad = set(v) - known
            if bad:
                raise ValueError(f"unknown qos keys: {sorted(bad)}")
            return cls(**v)
        raise ValueError(f"bad qos section: {v!r}")


@dataclass
class EngineConfig:
    model: str = "debug-tiny"
    block_size: int = 16
    num_blocks: int = 256  # HBM KV blocks (per replica)
    max_batch: int = 8  # decode slots
    max_model_len: int = 1024  # context limit per sequence
    prefill_chunk: int = 512  # max tokens prefillled per device step
    # mesh
    dp: int = 1
    tp: int = 1
    ep: int = 1
    # sequence parallel (ring attention): long prompts >= sp_prefill_min
    # tokens prefill in ONE whole-prompt pass sharded over the "sp" axis
    # instead of serial prefill_chunk steps (models/llama.py
    # forward_sp_prefill).  Best fit: dedicated (disagg) prefill workers.
    sp: int = 1
    sp_prefill_min: int = 1024
    dtype: str = "bfloat16"
    # KV cache dtype; defaults to dtype.  Quantized page dtypes halve KV
    # memory (2x context capacity).  kv_scale: a static float, "auto"
    # (per-layer scales calibrated from a probe forward at engine start —
    # engine._calibrate_kv_scales), or a per-layer sequence.  "int8"
    # REQUIRES calibration/a real scale (stored values are value/kv_scale
    # rounded to integers — at the 1.0 default, normal sub-unit activations
    # all round to 0).  Accuracy evidence: tests/test_quantized_kv.py.
    cache_dtype: Optional[str] = None
    kv_scale: Any = 1.0
    # Weight quantization: "int8" = W8A8-dynamic (per-output-channel int8
    # weights quantized at load, per-token dynamic int8 activations, native
    # MXU int8 dots — models/quant.py, ops/quant_matmul.py).  Halves weight
    # HBM (full-depth 8B fits one v5e chip) and runs ~1.7-1.9x bf16.  The
    # TPU mapping of the reference baseline's FP8-dynamic checkpoint
    # (examples/llm/benchmarks/README.md).  None = bf16 weights.
    weight_quant: Optional[str] = None
    # Fuse q|k|v and gate|up projection weights at engine init (7 matmuls
    # per dense layer -> 5; fused dots share one activation quantization).
    # Applied on single-shard meshes only — a tp-sharded fused axis would
    # split across segment boundaries (models/quant.py fuse_projections).
    fuse_projections: bool = True
    seed: int = 0
    # derived buckets
    batch_buckets: List[int] = field(default_factory=list)
    prefill_buckets: List[int] = field(default_factory=list)
    enable_prefix_caching: bool = True
    checkpoint_path: Optional[str] = None  # safetensors dir; None = random init
    # Attention backend: auto (ragged pallas kernel on TPU, xla gather
    # fallback elsewhere) | tpu | xla.
    attn_impl: str = "auto"
    # Decode-path attention kernel (ops/ragged_attention.py
    # resolve_decode_kernel; env override DYN_DECODE_KERNEL):
    #   auto         — pallas_fused on TPU, stock elsewhere
    #   pallas_fused — our fused-dequant split-KV Pallas decode kernel
    #                  (ops/decode_attention.py; interpret-mode on CPU)
    #   stock        — the jax pallas ragged kernel with tuned decode
    #                  hints on TPU, XLA fallback elsewhere (pre-kernel
    #                  behaviour)
    #   xla          — force the XLA fallback (bit-exactness oracle)
    decode_kernel: str = "auto"
    # Prefill-path attention kernel (ops/ragged_attention.py
    # resolve_prefill_kernel; env override DYN_PREFILL_KERNEL):
    #   auto   — pallas on TPU, stock elsewhere
    #   pallas — our chunked paged Pallas prefill kernel with in-kernel
    #            dequant + KV splits (ops/prefill_attention.py;
    #            interpret-mode on CPU)
    #   stock  — the jax pallas ragged kernel on TPU, XLA fallback
    #            elsewhere (pre-kernel behaviour)
    #   xla    — force the XLA fallback (byte-identity oracle)
    prefill_kernel: str = "auto"
    # Decode-stall watchdog threshold in seconds (engine/pipeline.py
    # _await_device): a token fetch / device dispatch exceeding it logs the
    # dispatch trace loudly and bumps dynamo_tpu_engine_stall_total.
    # None resolves the DYN_DECODE_STALL_S env var; 0 disables (default).
    decode_stall_s: Optional[float] = None
    # Decode iterations fused into one device dispatch (lax.scan feeding
    # sampled tokens forward in HBM).  >1 amortises host→device dispatch
    # latency at the cost of token-delivery granularity; essential when the
    # chip is reached over a network tunnel, still useful locally.
    decode_steps: int = 4
    # Fused decode dispatches kept in flight before their token fetch is
    # awaited (the sampled-token carry stays ON DEVICE between dispatches, so
    # chunk k+1 runs while chunk k's tokens stream back).  Hides the full
    # device→host round trip behind compute; stop conditions are applied with
    # up to pipeline_depth*decode_steps tokens of lag (over-decoded tokens
    # are discarded host-side and never corrupt sealed KV blocks).
    pipeline_depth: int = 2
    # Host (CPU RAM) KV offload tier: sealed blocks are write-behind copied
    # to host so HBM eviction keeps contents; prompts restore evicted
    # prefixes with one scatter instead of recomputing (engine/host_cache.py;
    # reference kv/storage.rs + block_copy.cu).  0 disables.
    host_cache_bytes: int = 0
    # Seconds between offload pump cycles (device gather + async D2H).
    host_offload_interval: float = 0.05
    # Disk KV tier (engine/disk_cache.py): host-tier LRU eviction DEMOTES
    # blocks to hash-named files under ``disk_cache_dir`` instead of
    # dropping them; restores promote disk→host→HBM.  Requires
    # host_cache_bytes > 0 (demotion feeds it); single-process only.
    # 0 disables.
    disk_cache_bytes: int = 0
    # Directory for the disk tier's block files; None resolves to a
    # per-process dir under the system temp root.
    disk_cache_dir: Optional[str] = None
    # fsync the block file before the atomic rename (DYN_DISK_FSYNC=1 also
    # enables).  os.replace is rename-atomic, but a power loss can persist
    # a renamed file whose payload pages never hit the platter; default
    # OFF because the read-side checksum already turns that torn payload
    # into a recompute, never a wrong scatter (docs/kv_tiering.md has the
    # durability-vs-latency tradeoff).
    disk_fsync: bool = False
    # Object-store KV tier (engine/object_store.py): disk-tier LRU
    # eviction DEMOTES blocks into a durable object layout instead of
    # dropping them, and hot chains can be persisted there explicitly
    # (persist_hashes / the autopilot warming policy), so a
    # scale-from-zero worker pointed at the same ``object_store_dir``
    # boots warm.  Requires disk_cache_bytes > 0 (the demotion ladder
    # feeds it) and an EXPLICIT directory: the store outlives the
    # process by design, so the operator owns params stability — there
    # is deliberately no per-PID default to fall back to.  0 disables.
    object_store_bytes: int = 0
    object_store_dir: Optional[str] = None
    # fsync each object part before the atomic publish (durability knob,
    # same tradeoff as disk_fsync; DYN_OBJSTORE_FSYNC=1 also enables).
    object_store_fsync: bool = False
    # KV integrity plane (engine/integrity.py): seconds a checksum-failed
    # block hash stays negative-cached.  While banned, restore/promotion
    # treat the hash as a miss and cross-worker pulls skip it, so a donor
    # still holding the corrupt copy cannot be re-pulled in a loop; after
    # the TTL a healthy copy becomes reachable again.
    kv_corrupt_ttl_s: float = 30.0
    # Cross-worker prefix pull (llm/kv_router/pull.py): when the router's
    # index says a peer holds a strictly longer prefix than every local
    # tier, the engine pulls the sealed delta blocks over the KV transfer
    # plane instead of recomputing prefill.  Budgets bound the worst case:
    # a pull never moves more than ``kv_pull_max_bytes`` and never waits
    # longer than ``kv_pull_timeout_s`` — past either, local prefill runs
    # (the disagg degraded-mode shape; the request is never lost).
    kv_pull_max_bytes: int = 64 << 20
    kv_pull_timeout_s: float = 5.0
    # Persistent XLA compilation cache dir: None resolves DYN_XLA_CACHE_DIR
    # (default ~/.cache/dynamo_tpu/xla); "" disables.  Makes warmup ~free on
    # worker restart (engine/xla_cache.py; r3 cold warmup was 139.6s).
    compilation_cache_dir: Optional[str] = None
    # Mixed-phase cadence: while prompts are prefilling, decode rows are
    # excluded from the (fetch-free) prefill steps and advance via a fused
    # decode_steps burst once every this many prefill chunks — balancing
    # prefill throughput against decode stall (engine.py _run_loop).
    # Swept on the tunneled v5e at ISL3000/OSL150.  With deferred token
    # fetches (r4) bursts are cheap and the optimum moved up: conc 32 at
    # K=8 → 413, K=16 → 511, K=24 → 550 (ITL p99 0.97s), K=32 → 565
    # (ITL p99 1.16s) tok/s; 24 takes near-peak throughput at the best
    # high-K latency.
    prefill_chunks_per_burst: int = 24
    # Draft-free speculative decoding section (SpecDecodeConfig; accepts a
    # dict / bool from layered configs).  Engine-level default; requests
    # opt out per call via sampling_options.spec_decode=false (nvext).
    spec_decode: Any = None
    # Batched multi-LoRA section (LoraConfig; accepts dict/bool).  Requests
    # select an adapter via the OpenAI ``model`` field; rows without one run
    # the base model unchanged.
    lora: Any = None
    # Scheduler QoS section (QosSchedConfig; accepts dict): WFQ tenant
    # weights + the batch-class starvation bound.  Defaults are exact-FIFO
    # for single-tenant traffic.
    qos: Any = None

    def __post_init__(self) -> None:
        if not self.batch_buckets:
            self.batch_buckets = _pow2_buckets(1, self.max_batch)
        if not self.prefill_buckets:
            self.prefill_buckets = _pow2_buckets(
                min(self.block_size, self.prefill_chunk), self.prefill_chunk
            )
        if self.cache_dtype is None:
            self.cache_dtype = self.dtype
        self.spec_decode = SpecDecodeConfig.normalize(self.spec_decode)
        self.lora = LoraConfig.normalize(self.lora)
        self.qos = QosSchedConfig.normalize(self.qos)
        if self.disk_cache_bytes > 0 and self.host_cache_bytes <= 0:
            raise ValueError(
                "disk_cache_bytes requires host_cache_bytes > 0 (the disk "
                "tier is fed by host-tier demotion)"
            )
        if self.object_store_bytes > 0:
            if self.disk_cache_bytes <= 0:
                raise ValueError(
                    "object_store_bytes requires disk_cache_bytes > 0 (the "
                    "object tier is fed by disk-tier demotion)"
                )
            if self.object_store_dir is None:
                raise ValueError(
                    "object_store_bytes requires an explicit "
                    "object_store_dir: the store outlives the process, so "
                    "the operator must own the directory (and the params "
                    "stability its hashes assume)"
                )
        if self.decode_kernel not in ("auto",) + DECODE_KERNELS:
            raise ValueError(
                f"unknown decode_kernel {self.decode_kernel!r} "
                f"(auto|{'|'.join(DECODE_KERNELS)})"
            )
        if self.prefill_kernel not in ("auto",) + PREFILL_KERNELS:
            raise ValueError(
                f"unknown prefill_kernel {self.prefill_kernel!r} "
                f"(auto|{'|'.join(PREFILL_KERNELS)})"
            )
        if self.weight_quant not in (None, "int8"):
            # One check covering every load path (checkpoint / random-init /
            # externally supplied params).
            raise ValueError(
                f"unknown weight_quant {self.weight_quant!r} (supported: int8)"
            )

    @property
    def max_blocks_per_seq(self) -> int:
        return (self.max_model_len + self.block_size - 1) // self.block_size

    def bucket_batch(self, n: int) -> int:
        for b in self.batch_buckets:
            if n <= b:
                return b
        return self.batch_buckets[-1]

    def bucket_prefill(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        return self.prefill_buckets[-1]

    @property
    def max_step_tokens(self) -> int:
        """Token capacity of one unified (ragged) step: a full prefill
        budget plus a decode token for every batch slot."""
        n = self.prefill_chunk + self.max_batch
        return 1 << (n - 1).bit_length()

    def bucket_tokens(self, n: int) -> int:
        """Power-of-two token-count bucket for the unified ragged step."""
        b = max(16, 1 << (max(1, n) - 1).bit_length())
        return min(b, self.max_step_tokens)
